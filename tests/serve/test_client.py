"""`EvalClient` reliability-stack tests (ISSUE 10): deadline-knob boundary
validation (the PR 8 ``_check_timeout_s`` 5-degenerate-values pattern),
retry/backoff on retryable errors, per-host circuit breaker, bounded
in-flight, and the replay/migration bookkeeping.

All sockets bind port 0 (OS-assigned).
"""

import socket
import threading
import time
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import (
    BackpressureError,
    EvalClient,
    EvalDaemon,
    EvalServer,
    WireError,
    metric_spec,
)

NUM_CLASSES = 5


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _silent_server():
    """A TCP listener that accepts and never answers — the half-dead
    host shape a partition presents. Returns (endpoint, closer)."""
    sock = socket.create_server(("127.0.0.1", 0))
    conns = []

    def loop():
        while True:
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            conns.append(conn)  # hold it open, say nothing

    t = threading.Thread(target=loop, daemon=True)
    t.start()

    def close():
        try:
            sock.close()
        except OSError:
            pass
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    host, port = sock.getsockname()[:2]
    return f"{host}:{port}", close


class TestDeadlineKnobValidation(unittest.TestCase):
    """ISSUE 10 satellite: every new client/router deadline knob goes
    through the PR 8 ``_check_timeout_s`` boundary check — NaN/inf/<=0
    raise ``ValueError`` BEFORE any socket or thread wait exists."""

    DEGENERATE = (0, -1.0, float("nan"), float("inf"), "5")

    def test_client_constructor_knobs_rejected(self):
        for knob in (
            "request_timeout_s",
            "connect_timeout_s",
            "backoff_base_s",
            "backoff_cap_s",
            "breaker_reset_s",
        ):
            for bad in self.DEGENERATE:
                with self.assertRaisesRegex(ValueError, knob):
                    EvalClient("127.0.0.1:1", **{knob: bad})

    def test_per_call_timeout_rejected_before_any_socket(self):
        # endpoint is unroutable on purpose: validation must fire first
        client = EvalClient("127.0.0.1:1")
        for bad in self.DEGENERATE:
            with self.assertRaisesRegex(ValueError, "timeout_s"):
                client.health(timeout_s=bad)

    def test_integer_knobs_validated(self):
        for knob in (
            "max_attempts",
            "max_in_flight",
            "breaker_threshold",
            "replay_capacity",
        ):
            for bad in (0, -1, 1.5):
                with self.assertRaisesRegex(ValueError, knob):
                    EvalClient("127.0.0.1:1", **{knob: bad})

    def test_bad_address_rejected(self):
        with self.assertRaisesRegex(ValueError, "address"):
            EvalClient("no-port-here")

    def test_valid_knobs_accepted(self):
        # no over-rejection: positive finite values and None deadlines
        client = EvalClient(
            "127.0.0.1:1",
            request_timeout_s=None,
            connect_timeout_s=0.5,
            backoff_base_s=0.01,
        )
        client.close()

    def test_daemon_stop_timeout_validated(self):
        # the same boundary check guards EvalDaemon.stop's join budget
        daemon = EvalDaemon().start()
        for bad in self.DEGENERATE:
            with self.assertRaisesRegex(ValueError, "timeout_s"):
                daemon.stop(timeout=bad)
        daemon.stop(timeout=5.0)  # valid value still stops

    def test_daemon_drain_timeout_validated(self):
        daemon = EvalDaemon().start()
        self.addCleanup(daemon.stop)
        for bad in self.DEGENERATE:
            with self.assertRaisesRegex(ValueError, "timeout_s"):
                daemon.drain(timeout=bad)


class TestTransportFailures(unittest.TestCase):
    def test_connection_refused_is_retryable_transport_error(self):
        # bind-then-close: nothing listens on the port afterwards
        probe = socket.create_server(("127.0.0.1", 0))
        host, port = probe.getsockname()[:2]
        probe.close()
        client = EvalClient(
            f"{host}:{port}",
            max_attempts=2,
            backoff_base_s=0.01,
            connect_timeout_s=0.5,
        )
        self.addCleanup(client.close)
        t0 = time.monotonic()
        with self.assertRaises(WireError) as ctx:
            client.health()
        self.assertEqual(ctx.exception.reason, "transport")
        self.assertTrue(ctx.exception.retryable)
        self.assertIn(str(port), ctx.exception.endpoint)
        # two attempts with one small backoff between them
        self.assertLess(time.monotonic() - t0, 5.0)

    def test_silent_server_hits_request_timeout(self):
        endpoint, close = _silent_server()
        self.addCleanup(close)
        client = EvalClient(
            endpoint,
            request_timeout_s=0.2,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(client.close)
        with self.assertRaises(WireError) as ctx:
            client.health()
        self.assertEqual(ctx.exception.reason, "request_timeout")
        self.assertTrue(ctx.exception.retryable)

    def test_circuit_breaker_opens_then_half_opens(self):
        endpoint, close = _silent_server()
        self.addCleanup(close)
        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        client = EvalClient(
            endpoint,
            request_timeout_s=0.1,
            max_attempts=1,
            backoff_base_s=0.01,
            breaker_threshold=2,
            breaker_reset_s=0.3,
        )
        self.addCleanup(client.close)
        for _ in range(2):  # reach the threshold with real timeouts
            with self.assertRaises(WireError):
                client.health()
        # open: fail fast, no socket wait (far quicker than the 0.1s
        # request deadline)
        t0 = time.monotonic()
        with self.assertRaises(WireError) as ctx:
            client.health()
        self.assertEqual(ctx.exception.reason, "circuit_open")
        self.assertLess(time.monotonic() - t0, 0.05)
        # after breaker_reset_s a half-open probe goes through to the
        # socket again (and times out against the silent server)
        time.sleep(0.35)
        with self.assertRaises(WireError) as ctx:
            client.health()
        self.assertEqual(ctx.exception.reason, "request_timeout")
        snap = obs.snapshot()
        open_events = [
            v
            for k, v in snap["counters"].items()
            if k.startswith("serve.client.breaker{")
            and "event=open" in k
        ]
        self.assertTrue(open_events)

    def test_breaker_closes_on_success(self):
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(
            server.endpoint, breaker_threshold=2, breaker_reset_s=0.1
        )
        self.addCleanup(client.close)
        client._breaker_failure()
        client._breaker_failure()  # open
        time.sleep(0.15)
        client.health()  # half-open probe succeeds -> closed
        self.assertEqual(client._breaker_failures, 0)


class TestRetryOnRetryableServeErrors(unittest.TestCase):
    def test_backpressure_shed_retries_until_worker_drains(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(
            server.endpoint,
            max_attempts=8,
            backoff_base_s=0.05,
            backoff_cap_s=0.2,
        )
        self.addCleanup(client.close)
        client.attach(
            "t",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
            queue_capacity=1,
        )
        scores, labels = _batch()
        # a burst beyond the queue bound: some submits shed server-side
        # and the client's retry loop absorbs them (retryable=True)
        for _ in range(6):
            self.assertTrue(client.submit("t", scores, labels))
        got = client.compute("t")
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for _ in range(6):
            oracle.update(scores, labels)
        self.assertEqual(
            float(np.asarray(got["acc"])),
            float(np.asarray(oracle.compute())),
        )
        # exactly-once even through sheds+retries
        health = client.health()
        self.assertEqual(health["tenants"]["t"]["processed"], 6)

    def test_non_retryable_error_surfaces_immediately(self):
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(
            server.endpoint, max_attempts=1, backoff_base_s=0.01
        )
        self.addCleanup(client.close)
        client.attach(
            "t",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
            queue_capacity=1,
        )
        scores, labels = _batch()
        # max_attempts=1: the shed surfaces as the structured error with
        # its retryable flag for the CALLER to act on
        daemon._tenants["t"].capacity = 0  # wedge the queue artificially
        with self.assertRaises(BackpressureError) as ctx:
            client.submit("t", scores, labels)
        self.assertTrue(ctx.exception.retryable)
        # the rejected batch left no ghost in the replay buffer
        st = client._tenant_state("t")
        self.assertEqual(len(st.replay), 0)
        self.assertEqual(st.next_seq, 1)


class TestBoundedInFlight(unittest.TestCase):
    def test_in_flight_bound_holds_under_concurrency(self):
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(server.endpoint, max_in_flight=2)
        self.addCleanup(client.close)
        peak = [0]
        live = [0]
        lock = threading.Lock()
        orig = client._checkout

        def tracking_checkout():
            with lock:
                live[0] += 1
                peak[0] = max(peak[0], live[0])
            return orig()

        orig_in = client._checkin

        def tracking_checkin(sock):
            with lock:
                live[0] -= 1
            orig_in(sock)

        orig_discard = client._discard

        def tracking_discard(sock):
            with lock:
                live[0] -= 1
            orig_discard(sock)

        client._checkout = tracking_checkout
        client._checkin = tracking_checkin
        client._discard = tracking_discard
        threads = [
            threading.Thread(target=client.health) for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.assertLessEqual(peak[0], 2)


class TestAmbiguousRejectKeepsBooking(unittest.TestCase):
    """Review finding (ISSUE 10): a structured reject that arrives AFTER
    an ambiguous attempt of the same seq must NOT roll the seq back — an
    earlier send may have been admitted, and reusing the seq would hand
    it to the next batch, which dedup then silently drops."""

    def _scripted_server(self, script):
        """One-connection-at-a-time server driven by a list of actions:
        "drop" (close without answering) or ("error", err_dict)."""
        from torcheval_tpu.serve.wire import recv_frame, send_frame

        sock = socket.create_server(("127.0.0.1", 0))
        self.addCleanup(sock.close)

        def loop():
            while script:
                try:
                    conn, _ = sock.accept()
                except OSError:
                    return
                with conn:
                    while script:
                        try:
                            frame = recv_frame(conn)
                        except Exception:  # noqa: BLE001
                            break
                        if frame is None:
                            break
                        action = script.pop(0)
                        if action == "drop":
                            break  # close mid-request: ambiguous
                        if action[0] == "ok":
                            send_frame(conn, {"ok": True, **action[1]})
                            continue
                        send_frame(
                            conn, {"ok": False, "error": action[1]}
                        )

        threading.Thread(target=loop, daemon=True).start()
        host, port = sock.getsockname()[:2]
        return f"{host}:{port}"

    def _client_with_tenant(self, endpoint):
        client = EvalClient(
            endpoint,
            max_attempts=2,
            backoff_base_s=0.01,
            request_timeout_s=5.0,
        )
        self.addCleanup(client.close)
        from torcheval_tpu.serve.client import _ClientTenant

        with client._lock:
            client._tenants["t"] = _ClientTenant(0)
        return client

    def test_reject_after_ambiguous_attempt_stays_booked(self):
        quarantine = {
            "type": "TenantQuarantinedError",
            "reason": "poisoned_batch",
            "message": "bad",
            "tenant": "t",
            "retryable": False,
        }
        endpoint = self._scripted_server(["drop", ("error", quarantine)])
        client = self._client_with_tenant(endpoint)
        scores, labels = _batch()
        from torcheval_tpu.serve import TenantQuarantinedError

        with self.assertRaises(TenantQuarantinedError) as ctx:
            client.submit("t", scores, labels)
        self.assertTrue(getattr(ctx.exception, "batch_booked", False))
        st = client._tenant_state("t")
        self.assertEqual([s for s, _ in st.replay], [1])  # still booked
        self.assertEqual(st.next_seq, 2)  # seq 1 is NEVER reused

    def test_booked_transport_failure_resends_before_next_batch(self):
        """Review finding (ISSUE 10): a direct (router-less) caller that
        swallows a booked transport failure and keeps submitting must not
        let a NEW seq advance the daemon watermark past the undelivered
        one — the next call re-delivers the booked tail first."""
        script = [
            "drop",
            "drop",  # both attempts of seq 1 die: booked, needs_resend
            ("ok", {"applied": True, "acked_seq": 0}),  # resend of seq 1
            ("ok", {"applied": True, "acked_seq": 0}),  # fresh seq 2
        ]
        endpoint = self._scripted_server(script)
        client = self._client_with_tenant(endpoint)
        scores, labels = _batch()
        with self.assertRaises(WireError) as ctx:
            client.submit("t", scores, labels)
        self.assertTrue(getattr(ctx.exception, "batch_booked", False))
        st = client._tenant_state("t")
        self.assertTrue(st.needs_resend)
        # next submit: seq 1 is re-delivered BEFORE seq 2 goes out
        self.assertTrue(client.submit("t", scores, labels))
        self.assertFalse(st.needs_resend)
        self.assertEqual([s for s, _ in st.replay], [1, 2])
        self.assertEqual(script, [])  # all four scripted exchanges ran

    def test_clean_first_attempt_reject_rolls_back(self):
        quarantine = {
            "type": "TenantQuarantinedError",
            "reason": "poisoned_batch",
            "message": "bad",
            "tenant": "t",
            "retryable": False,
        }
        endpoint = self._scripted_server([("error", quarantine)])
        client = self._client_with_tenant(endpoint)
        scores, labels = _batch()
        from torcheval_tpu.serve import TenantQuarantinedError

        with self.assertRaises(TenantQuarantinedError):
            client.submit("t", scores, labels)
        st = client._tenant_state("t")
        self.assertEqual(len(st.replay), 0)  # un-booked: never admitted
        self.assertEqual(st.next_seq, 1)


class TestMigrationBookkeeping(unittest.TestCase):
    def test_export_adopt_replays_only_undurable_tail(self):
        root_daemon = EvalDaemon().start()
        server = EvalServer(root_daemon)
        self.addCleanup(root_daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(server.endpoint)
        self.addCleanup(client.close)
        client.attach(
            "t",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )
        scores, labels = _batch()
        for _ in range(4):
            client.submit("t", scores, labels)
        client.flush("t")  # seqs 1-4 durable
        for _ in range(2):
            client.submit("t", scores, labels)  # seqs 5-6 un-durable
        exported = client.export_tenant("t")
        self.assertEqual(exported["durable_seq"], 4)
        self.assertEqual([s for s, _ in exported["replay"]], [5, 6])
        # adopt on a FRESH host (new daemon, fresh tenant) restored at
        # seq 4: only 5 and 6 replay; entries <= the restored watermark
        # are pruned without touching the wire
        daemon2 = EvalDaemon().start()
        server2 = EvalServer(daemon2)
        self.addCleanup(daemon2.stop)
        self.addCleanup(server2.close)
        client2 = EvalClient(server2.endpoint)
        self.addCleanup(client2.close)
        client2.attach(
            "t",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )
        replayed = client2.adopt_tenant("t", exported, restored_seq=4)
        self.assertEqual(replayed, 2)
        client2.compute("t")  # drain the worker queue before reading stats
        health = daemon2.health()
        self.assertEqual(health["tenants"]["t"]["processed"], 2)
        st = client2._tenant_state("t")
        self.assertEqual(st.next_seq, 7)  # numbering continues


if __name__ == "__main__":
    unittest.main()

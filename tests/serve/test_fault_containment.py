"""Single-process fault containment (ISSUE 8 tentpole, leg 3).

The headline property: no tenant can take down the daemon or corrupt
another tenant's results. Each leg injects one fault family — a poisoned
batch (wrong shape / NaN under policy), a raising compute, a step that
outruns its PR 5 deadline, a stalled producer — and asserts (a) the faulty
tenant surfaces a STRUCTURED error with the right reason, (b) every other
tenant's results are bit-identical to an oracle fed the identical stream,
and (c) eviction checkpoints restore bit-identically through reattach.
Chaos-injected variants (the env-gated queue-boundary hooks) live at the
bottom; the real multi-process legs are in ``test_serve_faults_mp.py``.
"""

import os
import threading
import time
import unittest
from unittest import mock

import numpy as np

from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.resilience import chaos
from torcheval_tpu.serve import (
    EvalDaemon,
    ServeError,
    TenantEvictedError,
    TenantQuarantinedError,
    TenantStatus,
)


def _batches(n_batches, seed, n=32, c=5):
    rng = np.random.default_rng(seed)
    return [
        (rng.random((n, c)).astype(np.float32), rng.integers(0, c, n))
        for _ in range(n_batches)
    ]


def _oracle_value(batches, c=5):
    m = MulticlassAccuracy(num_classes=c)
    for s, l in batches:
        m.update(s, l)
    return float(np.asarray(m.compute()))


class RaisingComputeMetric(Metric):
    """Eager metric whose compute raises — the hostile-tenant fixture."""

    def update(self, *args):
        return self

    def compute(self):
        raise RuntimeError("tenant compute exploded")

    def merge_state(self, metrics):
        return self


class BlockingMetric(Metric):
    """Eager metric whose update blocks until released — the stuck-step
    fixture for the PR 5 per-step watchdog."""

    def __init__(self, gate, *, device=None):
        super().__init__(device=device)
        self.gate = gate

    def update(self, *args):
        self.gate.wait(30)
        return self

    def compute(self):
        return 0.0

    def merge_state(self, metrics):
        return self


class TestQuarantineContainment(unittest.TestCase):
    def test_wrong_shape_batch_quarantines_only_that_tenant(self):
        healthy_batches = _batches(6, seed=0)
        with EvalDaemon() as daemon:
            victim = daemon.attach("victim", MulticlassAccuracy(num_classes=5))
            healthy = daemon.attach("healthy", MulticlassAccuracy(num_classes=5))
            for i, (s, l) in enumerate(healthy_batches):
                healthy.submit(s, l)
                if i == 2:
                    # leading-dim mismatch: the poisoned batch
                    victim.submit(s, l[:-1])
                else:
                    victim.submit(s, l)
            with self.assertRaises(TenantQuarantinedError) as ctx:
                victim.compute(timeout=60)
            self.assertEqual(ctx.exception.reason, "poisoned_batch")
            self.assertEqual(ctx.exception.tenant, "victim")
            self.assertIsInstance(ctx.exception.__cause__, ValueError)
            self.assertIs(victim.status, TenantStatus.QUARANTINED)
            # containment: the healthy tenant's value is bit-identical to a
            # fault-free oracle, and the daemon keeps serving
            got = float(np.asarray(healthy.compute(timeout=60)))
            self.assertEqual(got, _oracle_value(healthy_batches))
            self.assertTrue(daemon.health()["worker_alive"])
            # subsequent ops on the quarantined tenant keep raising the
            # same structured error
            with self.assertRaises(TenantQuarantinedError):
                victim.submit(*healthy_batches[0])

    def test_nan_policy_reject_quarantines_and_propagate_contains(self):
        nan_scores = np.full((32, 5), np.nan, dtype=np.float32)
        labels = np.zeros(32, dtype=np.int64)
        clean = _batches(3, seed=1)
        with EvalDaemon() as daemon:
            strict = daemon.attach(
                "strict", MulticlassAccuracy(num_classes=5), nan_policy="reject"
            )
            lax_t = daemon.attach("lax", MulticlassAccuracy(num_classes=5))
            bystander = daemon.attach(
                "bystander", MulticlassAccuracy(num_classes=5)
            )
            for s, l in clean:
                bystander.submit(s, l)
            strict.submit(nan_scores, labels)
            lax_t.submit(nan_scores, labels)
            with self.assertRaises(TenantQuarantinedError) as ctx:
                strict.compute(timeout=60)
            self.assertEqual(ctx.exception.reason, "nan_policy")
            # propagate: garbage stays inside the submitting tenant
            lax_val = lax_t.compute(timeout=60)
            self.assertIs(lax_t.status, TenantStatus.ACTIVE)
            self.assertTrue(np.isfinite(float(np.asarray(lax_val))))
            got = float(np.asarray(bystander.compute(timeout=60)))
            self.assertEqual(got, _oracle_value(clean))

    def test_raising_compute_quarantines_with_cause(self):
        with EvalDaemon() as daemon:
            bad = daemon.attach("bad", {"boom": RaisingComputeMetric()})
            ok = daemon.attach("ok", MulticlassAccuracy(num_classes=5))
            batches = _batches(2, seed=2)
            for s, l in batches:
                ok.submit(s, l)
            bad.submit(np.float32([1.0]))
            with self.assertRaises(TenantQuarantinedError) as ctx:
                bad.compute(timeout=60)
            self.assertEqual(ctx.exception.reason, "compute_error")
            self.assertIsInstance(ctx.exception.__cause__, RuntimeError)
            got = float(np.asarray(ok.compute(timeout=60)))
            self.assertEqual(got, _oracle_value(batches))

    def test_step_deadline_quarantines_stuck_tenant(self):
        gate = threading.Event()
        try:
            with EvalDaemon() as daemon:
                stuck = daemon.attach(
                    "stuck",
                    {"block": BlockingMetric(gate)},
                    step_timeout_s=0.5,
                )
                ok = daemon.attach("ok", MulticlassAccuracy(num_classes=5))
                batches = _batches(2, seed=3)
                t0 = time.monotonic()
                stuck.submit(np.float32([1.0]))
                for s, l in batches:
                    ok.submit(s, l)
                # the PR 5 watchdog fires at ~step_timeout_s and the worker
                # moves on; the wedged dispatch is abandoned on its daemon
                # thread exactly like a hung collective
                with self.assertRaises(TenantQuarantinedError) as ctx:
                    stuck.compute(timeout=60)
                self.assertEqual(ctx.exception.reason, "step_timeout")
                self.assertLess(time.monotonic() - t0, 20.0)
                got = float(np.asarray(ok.compute(timeout=60)))
                self.assertEqual(got, _oracle_value(batches))
        finally:
            gate.set()


class TestEvictionAndResume(unittest.TestCase):
    def test_watchdog_evicts_idle_tenant_and_reattach_resumes_bit_identical(
        self,
    ):
        import tempfile

        evict_dir = tempfile.mkdtemp(prefix="serve_evict_")
        batches = _batches(8, seed=4)
        want = _oracle_value(batches)
        with EvalDaemon(
            evict_dir=evict_dir, watchdog_interval_s=0.05
        ) as daemon:
            h = daemon.attach(
                "w",
                MulticlassAccuracy(num_classes=5),
                watchdog_timeout_s=0.3,
            )
            for s, l in batches[:4]:
                h.submit(s, l)
            deadline = time.monotonic() + 30
            while (
                h.status is TenantStatus.ACTIVE
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            self.assertIs(h.status, TenantStatus.EVICTED)
            err = h.error
            self.assertIsInstance(err, TenantEvictedError)
            self.assertEqual(err.reason, "watchdog_idle")
            self.assertTrue(os.path.isdir(err.checkpoint))
            with self.assertRaises(TenantEvictedError):
                h.submit(*batches[4])
            # reattach under the same id restores the eviction checkpoint
            # and the stream continues exactly where it stopped
            h2 = daemon.attach(
                "w", MulticlassAccuracy(num_classes=5), resume="require"
            )
            for s, l in batches[4:]:
                h2.submit(s, l)
            got = float(np.asarray(h2.compute(timeout=60)))
            self.assertEqual(got, want)

    def test_explicit_evict_roundtrip(self):
        import tempfile

        evict_dir = tempfile.mkdtemp(prefix="serve_evict_")
        batches = _batches(6, seed=5)
        want = _oracle_value(batches)
        with EvalDaemon(evict_dir=evict_dir) as daemon:
            h = daemon.attach("e", MulticlassAccuracy(num_classes=5))
            for s, l in batches[:3]:
                h.submit(s, l)
            path = daemon.evict("e", timeout=60)
            self.assertTrue(os.path.isdir(path))
            self.assertIs(h.status, TenantStatus.EVICTED)
            self.assertEqual(h.error.checkpoint, path)
            h2 = daemon.attach("e", MulticlassAccuracy(num_classes=5))
            for s, l in batches[3:]:
                h2.submit(s, l)
            self.assertEqual(
                float(np.asarray(h2.compute(timeout=60))), want
            )

    def test_detach_with_checkpoint_is_graceful_eviction(self):
        import tempfile

        evict_dir = tempfile.mkdtemp(prefix="serve_evict_")
        batches = _batches(4, seed=6)
        with EvalDaemon(evict_dir=evict_dir) as daemon:
            h = daemon.attach("g", MulticlassAccuracy(num_classes=5))
            for s, l in batches[:2]:
                h.submit(s, l)
            path = h.detach(checkpoint=True, timeout=60)
            self.assertTrue(os.path.isdir(path))
            h2 = daemon.attach(
                "g", MulticlassAccuracy(num_classes=5), resume="auto"
            )
            for s, l in batches[2:]:
                h2.submit(s, l)
            self.assertEqual(
                float(np.asarray(h2.compute(timeout=60))),
                _oracle_value(batches),
            )

    def test_resume_never_starts_clean(self):
        import tempfile

        evict_dir = tempfile.mkdtemp(prefix="serve_evict_")
        with EvalDaemon(evict_dir=evict_dir) as daemon:
            h = daemon.attach("c", MulticlassAccuracy(num_classes=5))
            h.submit(*_batches(1, seed=7)[0])
            h.detach(checkpoint=True, timeout=60)
            fresh = _batches(2, seed=8)
            h2 = daemon.attach(
                "c", MulticlassAccuracy(num_classes=5), resume="never"
            )
            for s, l in fresh:
                h2.submit(s, l)
            self.assertEqual(
                float(np.asarray(h2.compute(timeout=60))),
                _oracle_value(fresh),
            )

    def test_quarantined_state_is_never_checkpointed(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("q", MulticlassAccuracy(num_classes=5))
            s, l = _batches(1, seed=9)[0]
            h.submit(s, l[:-1])  # poison
            with self.assertRaises(TenantQuarantinedError):
                h.compute(timeout=60)
            with self.assertRaises(ServeError):
                daemon.evict("q", timeout=60)


class _ChaosEnv:
    """Arm chaos through the environment for one test, resolving fresh and
    disarming afterwards (the library caches the parsed config)."""

    def __init__(self, **env):
        self.env = {k: str(v) for k, v in env.items()}

    def __enter__(self):
        self._patch = mock.patch.dict(os.environ, self.env)
        self._patch.__enter__()
        chaos.reset_for_tests()

    def __exit__(self, *exc):
        self._patch.__exit__(*exc)
        chaos.reset_for_tests()


class TestChaosAtTheQueueBoundary(unittest.TestCase):
    """The env-gated ingestion hooks (ISSUE 8 satellite): serve fault tests
    inject at the queue boundary the same way sync tests inject at the
    collective funnel."""

    def test_chaos_nan_poison_quarantines_target_tenant_only(self):
        clean = _batches(4, seed=10)
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="poison",
            TORCHEVAL_TPU_CHAOS_TENANT="victim",
            TORCHEVAL_TPU_CHAOS_STEP="2",
            TORCHEVAL_TPU_CHAOS_POISON="nan",
        ):
            with EvalDaemon() as daemon:
                victim = daemon.attach(
                    "victim",
                    MulticlassAccuracy(num_classes=5),
                    nan_policy="reject",
                )
                other = daemon.attach(
                    "other", MulticlassAccuracy(num_classes=5)
                )
                for s, l in clean:
                    try:
                        victim.submit(s, l)
                    except TenantQuarantinedError:
                        pass  # later submits observe the quarantine
                    other.submit(s, l)
                with self.assertRaises(TenantQuarantinedError) as ctx:
                    victim.compute(timeout=60)
                self.assertEqual(ctx.exception.reason, "nan_policy")
                got = float(np.asarray(other.compute(timeout=60)))
        self.assertEqual(got, _oracle_value(clean))

    def test_chaos_shape_poison_hits_update_validation(self):
        clean = _batches(3, seed=11)
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="poison",
            TORCHEVAL_TPU_CHAOS_TENANT="victim",
            TORCHEVAL_TPU_CHAOS_STEP="1",
            TORCHEVAL_TPU_CHAOS_POISON="shape",
        ):
            with EvalDaemon() as daemon:
                victim = daemon.attach(
                    "victim", MulticlassAccuracy(num_classes=5)
                )
                for s, l in clean:
                    try:
                        victim.submit(s, l)
                    except TenantQuarantinedError:
                        pass
                with self.assertRaises(TenantQuarantinedError) as ctx:
                    victim.compute(timeout=60)
                self.assertEqual(ctx.exception.reason, "poisoned_batch")

    def test_chaos_ingest_delay_stalls_only_the_producer(self):
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="ingest_delay",
            TORCHEVAL_TPU_CHAOS_TENANT="slow",
            TORCHEVAL_TPU_CHAOS_STEP="1",
            TORCHEVAL_TPU_CHAOS_DELAY_S="0.5",
        ):
            with EvalDaemon() as daemon:
                slow = daemon.attach("slow", MulticlassAccuracy(num_classes=5))
                s, l = _batches(1, seed=12)[0]
                t0 = time.monotonic()
                slow.submit(s, l)
                elapsed = time.monotonic() - t0
                self.assertGreaterEqual(elapsed, 0.45)
                self.assertIs(slow.status, TenantStatus.ACTIVE)

    def test_malformed_ingest_config_disarms(self):
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="poison",
            # TENANT/STEP missing: must disarm with a warning, never raise
        ):
            args = chaos.on_ingest("t", 1, (np.float32([1.0]),))
            self.assertEqual(len(args), 1)

    def test_poison_fires_once_per_process(self):
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="poison",
            TORCHEVAL_TPU_CHAOS_TENANT="*",
            TORCHEVAL_TPU_CHAOS_STEP="1",
            TORCHEVAL_TPU_CHAOS_POISON="nan",
        ):
            a1 = chaos.on_ingest("x", 1, (np.ones(4, np.float32),))
            self.assertTrue(np.isnan(np.asarray(a1[0])).all())
            a2 = chaos.on_ingest("y", 1, (np.ones(4, np.float32),))
            self.assertFalse(np.isnan(np.asarray(a2[0])).any())


if __name__ == "__main__":
    unittest.main()

"""The shared retry-classification source of truth (ISSUE 10 satellite).

Every serve failure carries ``retryable``: the wire client's retry loop,
the router's failover and any local caller branch on the SAME bit, and
the wire marshals it with the error so remote callers see exactly what
local callers would. These tests pin the classification table —
backpressure and capacity rejects are transient (True); quarantines,
duplicate ids and bad metric specs need caller action (False) — and that
``encode_error``/``decode_error`` round-trips class, reason, extras and
the flag.
"""

import unittest

from torcheval_tpu.resilience.snapshot import CheckpointError
from torcheval_tpu.serve import (
    AdmissionError,
    BackpressureError,
    ServeError,
    TenantError,
    TenantEvictedError,
    TenantQuarantinedError,
    WireError,
)
from torcheval_tpu.serve.wire import decode_error, encode_error


class TestRetryableClassification(unittest.TestCase):
    def test_backpressure_always_retryable(self):
        e = BackpressureError("queue_full", "full", tenant="t")
        self.assertTrue(e.retryable)

    def test_admission_capacity_retryable(self):
        self.assertTrue(AdmissionError("capacity", "at max_tenants").retryable)

    def test_admission_non_capacity_not_retryable(self):
        for reason in (
            "duplicate_tenant",
            "bad_metrics",
            "daemon_stopped",
            "no_checkpoint",
            "draining",
        ):
            self.assertFalse(
                AdmissionError(reason, "nope").retryable, reason
            )

    def test_quarantine_never_retryable(self):
        for reason in (
            "poisoned_batch",
            "nan_policy",
            "compute_error",
            "step_timeout",
        ):
            self.assertFalse(
                TenantQuarantinedError(reason, "bad", tenant="t").retryable,
                reason,
            )

    def test_eviction_not_retryable(self):
        # the tenant must be re-attached (a different request), not
        # the failed op retried verbatim
        e = TenantEvictedError(
            "watchdog_idle", "gone", tenant="t", checkpoint="/ckpt"
        )
        self.assertFalse(e.retryable)

    def test_generic_serve_error_not_retryable(self):
        for reason in ("daemon_stopped", "draining", "unknown_tenant"):
            self.assertFalse(ServeError(reason, "nope").retryable, reason)

    def test_wire_transport_family_retryable_protocol_not(self):
        for reason in ("transport", "request_timeout", "circuit_open"):
            self.assertTrue(WireError(reason, "net").retryable, reason)
        self.assertFalse(WireError("protocol", "skew").retryable)


class TestErrorMarshalling(unittest.TestCase):
    """encode/decode reconstructs class, reason, extras AND retryable."""

    def _roundtrip(self, exc):
        return decode_error(encode_error(exc))

    def test_backpressure_roundtrip(self):
        got = self._roundtrip(
            BackpressureError("queue_full", "queue is full", tenant="bob")
        )
        self.assertIsInstance(got, BackpressureError)
        self.assertEqual(got.reason, "queue_full")
        self.assertEqual(got.tenant, "bob")
        self.assertTrue(got.retryable)
        # the [reason] prefix is composed once, not stacked per hop
        self.assertEqual(str(got).count("[queue_full]"), 1)

    def test_quarantine_roundtrip(self):
        got = self._roundtrip(
            TenantQuarantinedError("nan_policy", "poisoned", tenant="bob")
        )
        self.assertIsInstance(got, TenantQuarantinedError)
        self.assertEqual((got.reason, got.tenant), ("nan_policy", "bob"))
        self.assertFalse(got.retryable)

    def test_eviction_roundtrip_carries_checkpoint(self):
        got = self._roundtrip(
            TenantEvictedError(
                "watchdog_idle", "gone", tenant="carol", checkpoint="/c/k"
            )
        )
        self.assertIsInstance(got, TenantEvictedError)
        self.assertEqual(got.checkpoint, "/c/k")

    def test_admission_and_tenant_error_roundtrip(self):
        got = self._roundtrip(AdmissionError("capacity", "full house"))
        self.assertIsInstance(got, AdmissionError)
        self.assertTrue(got.retryable)
        got = self._roundtrip(TenantError("weird", "odd", tenant="t"))
        self.assertIsInstance(got, TenantError)

    def test_checkpoint_error_crosses_the_wire(self):
        # attach(resume=...) restore failures surface remotely with the
        # structured reason intact
        got = self._roundtrip(CheckpointError("schema_mismatch", "drift"))
        self.assertIsInstance(got, CheckpointError)
        self.assertEqual(got.reason, "schema_mismatch")
        self.assertFalse(getattr(got, "retryable", False))

    def test_value_error_crosses_as_value_error(self):
        got = self._roundtrip(ValueError("timeout_s must be positive"))
        self.assertIsInstance(got, ValueError)
        self.assertIn("timeout_s", str(got))

    def test_unknown_type_decodes_as_generic_serve_error(self):
        got = decode_error(
            {"type": "SomethingNew", "reason": "later", "message": "m",
             "retryable": True}
        )
        self.assertIsInstance(got, ServeError)
        self.assertEqual(got.reason, "later")
        self.assertTrue(got.retryable)  # the wire flag is the truth


if __name__ == "__main__":
    unittest.main()

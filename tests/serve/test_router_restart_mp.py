"""Router-restart drill: REAL processes, ``os._exit`` mid-migration
(ISSUE 20 acceptance).

One world: three host processes (``mp_cluster_host.py``) share a
checkpoint root; a disposable DRIVER process (``mp_router_driver.py``)
runs a journaled ``EvalRouter`` — a plain tenant plus a split-by-2
tenant streaming live — and is chaos-killed (``router_kill`` at
``migrate_exported``) inside a drain's first live migration, the window
where a tenant's wire state is exported and adopted nowhere. This test
process then constructs a NEW router from the same journal directory:
the recovery pass replays the journal, reconciles against the live
hosts (adopting survivors, re-placing the drained host's tenants from
their checkpoints, re-deriving the split fan-out ordinal from replica
watermarks), and the test finishes both streams. The verdict: every
tenant bit-identical to its fault-free oracle, zero duplicate batch
application anywhere, and a measured, bounded control-plane blackout.

Artifacts (the journal itself, fleet status, a drill summary) land in
test-artifacts on every run. All sockets bind port 0 (OS-assigned).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import unittest
import zlib

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_HOST = os.path.join(_HERE, "mp_cluster_host.py")
_DRIVER = os.path.join(_HERE, "mp_router_driver.py")

NUM_CLASSES = 5
BATCH = 32
PHASE1, PHASE2 = 6, 5  # must match mp_router_driver.PHASE1
CHAOS_EXIT_CODE = 47
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
TENANTS = ("solo", "fan")


def _make_batch(tenant: str, idx: int):
    # crc32, not hash(): seeds must match the driver process exactly
    seed = 1000 * (zlib.crc32(tenant.encode()) % 97) + idx
    rng = np.random.default_rng(seed)
    return (
        rng.random((BATCH, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, BATCH),
    )


def _oracle(tenant: str, n: int) -> float:
    from torcheval_tpu.metrics import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for i in range(n):
        m.update(*_make_batch(tenant, i))
    return float(np.asarray(m.compute()))


def _artifact_dir() -> str:
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, "router_restart_drill")
        os.makedirs(out, exist_ok=True)
        return out
    return tempfile.mkdtemp(prefix="tpu_router_restart_drill_")


def _clean_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith("TORCHEVAL_TPU_CHAOS"):
            del env[k]
    if extra:
        env.update(extra)
    return env


def _wait_port(outdir: str, tag: str, timeout_s: float = 90.0) -> int:
    path = os.path.join(outdir, f"{tag}.port")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return int(f.read())
        time.sleep(0.05)
    raise TimeoutError(f"host {tag} never published its port.")


class TestRouterRestartDrill(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        cls.procs = {}
        try:
            cls._run_world()
        except BaseException:
            for proc in cls.procs.values():
                if proc.poll() is None:
                    proc.kill()
            raise

    @classmethod
    def _run_world(cls):
        from torcheval_tpu import obs
        from torcheval_tpu.serve import EvalClient, EvalRouter

        cls.outdir = _artifact_dir()
        cls.ckpt_root = os.path.join(cls.outdir, "ckpt_root")
        cls.journal_dir = os.path.join(cls.outdir, "journal")
        os.makedirs(cls.ckpt_root, exist_ok=True)

        endpoints = []
        for tag in ("hostA", "hostB", "hostC"):
            cls.procs[tag] = subprocess.Popen(
                [sys.executable, _HOST, cls.outdir, tag, cls.ckpt_root],
                env=_clean_env(),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            endpoints.append(f"127.0.0.1:{_wait_port(cls.outdir, tag)}")
        cls.endpoints = endpoints

        # the disposable router: journaled, armed to die mid-migration
        driver = subprocess.Popen(
            [
                sys.executable,
                _DRIVER,
                cls.outdir,
                cls.journal_dir,
                ",".join(endpoints),
            ],
            env=_clean_env(
                {
                    "TORCHEVAL_TPU_CHAOS": "1",
                    "TORCHEVAL_TPU_CHAOS_ACTION": "router_kill",
                    "TORCHEVAL_TPU_CHAOS_TENANT": "*",
                    "TORCHEVAL_TPU_CHAOS_STEP": "1",
                    "TORCHEVAL_TPU_CHAOS_POINT": "migrate_exported",
                    "TORCHEVAL_TPU_CHAOS_EXIT_CODE": str(
                        CHAOS_EXIT_CODE
                    ),
                }
            ),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        cls.driver_out, _ = driver.communicate(timeout=300)
        cls.driver_rc = driver.returncode
        with open(os.path.join(cls.outdir, "driver.state.json")) as f:
            cls.driver_state = json.load(f)

        # the restart: a NEW router recovers from the same journal
        obs.reset()
        obs.enable()
        router = EvalRouter(
            endpoints,
            journal_dir=cls.journal_dir,
            request_timeout_s=10.0,
            connect_timeout_s=5.0,
            max_attempts=2,
            backoff_base_s=0.05,
        )
        cls.recovery = dict(router.last_recovery)
        cls.placement_after = router.placement()
        for i in range(PHASE1, PHASE1 + PHASE2):
            for t in TENANTS:
                router.submit(t, *_make_batch(t, i))
        for t in TENANTS:
            router.flush(t)
        cls.results = {
            t: float(np.asarray(router.compute(t)["acc"]))
            for t in TENANTS
        }

        # zero duplicate application anywhere in the fleet
        cls.host_dupes = {}
        cls.fleet_status = router.fleet_status()
        for ep in endpoints:
            client = EvalClient(ep, request_timeout_s=30.0)
            health = client.health()
            cls.host_dupes[ep] = {
                tid: info.get("dupes", 0)
                for tid, info in health.get("tenants", {}).items()
            }
            client.close()
        router.close()

        # artifacts: the journal itself (the drill's black box), fleet
        # status, and a summary with the measured blackout
        journal_artifacts = os.path.join(cls.outdir, "journal_after")
        shutil.copytree(
            cls.journal_dir, journal_artifacts, dirs_exist_ok=True
        )
        with open(
            os.path.join(cls.outdir, "fleet.status.json"), "w"
        ) as f:
            json.dump(cls.fleet_status, f, indent=2, default=str)
        with open(
            os.path.join(cls.outdir, "restart.summary.json"), "w"
        ) as f:
            json.dump(
                {
                    "driver_exit_code": cls.driver_rc,
                    "recovery": cls.recovery,
                    "blackout_ms": cls.recovery["duration_s"] * 1e3,
                    "placement_before": cls.driver_state["placement"],
                    "placement_after": cls.placement_after,
                    "host_dupes": cls.host_dupes,
                },
                f,
                indent=2,
            )

        for tag in list(cls.procs):
            with open(os.path.join(cls.outdir, f"{tag}.stop"), "w"):
                pass
        for proc in cls.procs.values():
            try:
                proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        cls.leaked_threads = [
            t.name
            for t in threading.enumerate()
            if "torcheval-tpu-obs-" in t.name
            or t.name == "torcheval-tpu-router-rebalance"
        ]
        obs.disable()

    def test_chaos_killed_the_router_mid_migration(self):
        self.assertEqual(
            self.driver_rc,
            CHAOS_EXIT_CODE,
            self.driver_out.decode(errors="replace")[-2000:],
        )

    def test_recovery_reconciled_every_tenant(self):
        outcomes = self.recovery["outcomes"]
        # the drained host's tenants re-place from checkpoints; any
        # tenant living elsewhere is adopted where it stands
        self.assertGreaterEqual(outcomes.get("replaced", 0), 1)
        self.assertEqual(sum(outcomes.values()), 3)  # solo, fan, fan@r1
        self.assertEqual(
            sorted(self.placement_after),
            sorted(self.driver_state["placement"]),
        )
        # the drain the dead router had journaled survives the restart
        victim = self.driver_state["victim"]
        self.assertIn(victim, self.recovery["drained"])
        for t, ep in self.placement_after.items():
            self.assertNotEqual(ep, victim, t)

    def test_results_bit_identical_to_fault_free_oracles(self):
        for t in TENANTS:
            self.assertEqual(
                self.results[t], _oracle(t, PHASE1 + PHASE2), t
            )

    def test_zero_duplicate_application(self):
        for ep, dupes in self.host_dupes.items():
            for tid, n in dupes.items():
                self.assertEqual(n, 0, f"{tid} on {ep}")

    def test_blackout_measured_and_bounded(self):
        blackout_s = self.recovery["duration_s"]
        self.assertGreater(blackout_s, 0.0)
        self.assertLess(blackout_s, 60.0)

    def test_no_threads_leaked(self):
        self.assertEqual(self.leaked_threads, [])

    def test_artifacts_written(self):
        for name in (
            "driver.state.json",
            "fleet.status.json",
            "restart.summary.json",
            os.path.join("journal_after", "snapshot.json"),
        ):
            self.assertTrue(
                os.path.getsize(os.path.join(self.outdir, name)) > 0,
                name,
            )


if __name__ == "__main__":
    unittest.main()

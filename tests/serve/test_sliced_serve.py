"""Sliced tenants through the serve stack (ISSUE 15).

The serve integration surface: ``daemon.attach(slices=...)`` admission,
the wire attach header + ``EvalClient.attach(slices=...)``, submit with the
slice-id column, per-slice compute results over the wire, and the
evict→reattach round trip carrying the sparse id table bit-identically.

Plus the ISSUE 15 satellite regression: ``approx=`` (PR 14's per-tenant
knob) must COMPOSE with a sliced attach under validate-then-commit — a
spec that cannot slice rejects as ``bad_metrics`` BEFORE any member is
switched into sketch state, so a caller-held collection never ends up
half-mutated by a failed sliced admission.
"""

import tempfile
import unittest

import numpy as np

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    Cat,
    SlicedMetricCollection,
)
from torcheval_tpu.serve import (
    AdmissionError,
    EvalClient,
    EvalDaemon,
    EvalServer,
    metric_spec,
)


def _batches(seed=0, n_batches=3, n=200):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(0, 9, n).astype(np.int64) * 13 - 5
        s = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.4).astype(np.float32)
        out.append((ids, s, t))
    return out


def _spec():
    return {"acc": BinaryAccuracy(), "auroc": BinaryAUROC()}


class TestSlicedAttach(unittest.TestCase):
    def test_attach_submit_compute(self):
        with EvalDaemon() as daemon:
            h = daemon.attach(
                "t1", _spec(), approx=1024, slices={"capacity": 4}
            )
            self.assertIsInstance(
                h._tenant.collection, SlicedMetricCollection
            )
            for b in _batches():
                h.submit(*b)
            res = h.compute()
            self.assertEqual(
                sorted(res["acc"]), ["slice_ids", "values"]
            )
            self.assertEqual(
                len(res["acc"]["slice_ids"]),
                len(np.unique(np.concatenate([b[0] for b in _batches()]))),
            )
            h.detach()

    def test_slices_knob_shapes(self):
        with EvalDaemon() as daemon:
            daemon.attach("a", _spec(), approx=True, slices=True).detach()
            daemon.attach("b", _spec(), approx=True, slices=16).detach()
            with self.assertRaises(ValueError):
                daemon.attach("c", _spec(), approx=True, slices={"nope": 1})
            with self.assertRaises(ValueError):
                daemon.attach("d", _spec(), approx=True, slices="yes")

    def test_prebuilt_sliced_collection_passes_through(self):
        col = SlicedMetricCollection(
            {"acc": BinaryAccuracy()}, capacity=8
        )
        with EvalDaemon() as daemon:
            h = daemon.attach("t1", col, slices=True)
            self.assertIs(h._tenant.collection, col)
            h.detach()

    def test_evict_reattach_round_trips_id_table(self):
        batches = _batches(seed=2)
        with tempfile.TemporaryDirectory() as d:
            with EvalDaemon(evict_dir=d) as daemon:
                h = daemon.attach(
                    "t1", _spec(), approx=1024, slices={"capacity": 2}
                )
                for b in batches:
                    h.submit(*b)
                want = h.compute()
                table = h._tenant.collection.slice_table.registered_ids()
                daemon.evict("t1")
                h2 = daemon.attach(
                    "t1",
                    _spec(),
                    approx=1024,
                    slices={"capacity": 2},
                    resume="require",
                )
                np.testing.assert_array_equal(
                    h2._tenant.collection.slice_table.registered_ids(),
                    table,
                )
                got = h2.compute()
                for key in ("acc", "auroc"):
                    np.testing.assert_array_equal(
                        got[key]["slice_ids"], want[key]["slice_ids"]
                    )
                    np.testing.assert_array_equal(
                        np.asarray(got[key]["values"]),
                        np.asarray(want[key]["values"]),
                    )
                # the resumed tenant keeps streaming, new cohorts included
                ids, s, t = batches[0]
                h2.submit(ids * 31 + 2, s, t)
                h2.compute()


class TestApproxSlicedComposition(unittest.TestCase):
    """ISSUE 15 satellite: validate-then-commit covers slice expansion."""

    def test_unsliceable_member_rejects_before_approx_commits(self):
        # Cat has an approx mode (the value sketch) but no slice
        # expansion: a sliced attach must reject as bad_metrics WITHOUT
        # enable_metric_approx having switched the caller-held instances
        cat = Cat()
        auroc = BinaryAUROC()
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach(
                    "t1",
                    {"auroc": auroc, "cat": cat},
                    approx=1024,
                    slices=True,
                )
            self.assertEqual(ctx.exception.reason, "bad_metrics")
        # neither member was half-switched by the failed admission
        self.assertFalse(cat._sketch_enabled())
        self.assertIsNone(getattr(auroc, "_sketch_bits", None))
        self.assertIn("summary_tp", auroc._state_name_to_default)

    def test_exact_curve_without_approx_rejects_sliced(self):
        # an exact curve cannot slice (per-slice sample caches); the
        # rejection must name the approx requirement
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("t1", _spec(), slices=True)
            self.assertEqual(ctx.exception.reason, "bad_metrics")
            self.assertIn("approx", str(ctx.exception))

    def test_approx_with_slices_expands_sketch_members(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("t1", _spec(), approx=1024, slices=True)
            member = h._tenant.collection.metrics["auroc"]
            self.assertEqual(member._bits, 10)  # 1024 buckets
            h.detach()


class TestSlicedWire(unittest.TestCase):
    def setUp(self):
        self.daemon = EvalDaemon().start()
        self.server = EvalServer(self.daemon)
        self.client = EvalClient(
            self.server.endpoint, request_timeout_s=30.0
        )
        self.addCleanup(self.daemon.stop)
        self.addCleanup(self.server.close)
        self.addCleanup(self.client.close)

    def test_wire_attach_submit_compute_matches_local(self):
        batches = _batches(seed=4)
        with EvalDaemon() as local:
            h = local.attach(
                "ref", _spec(), approx=1024, slices={"capacity": 4}
            )
            for b in batches:
                h.submit(*b)
            want = h.compute()
        spec = {
            "acc": metric_spec("BinaryAccuracy"),
            "auroc": metric_spec("BinaryAUROC"),
        }
        self.client.attach("w1", spec, approx=1024, slices={"capacity": 4})
        for b in batches:
            self.client.submit("w1", *b)
        got = self.client.compute("w1")
        for key in ("acc", "auroc"):
            np.testing.assert_array_equal(
                got[key]["slice_ids"], want[key]["slice_ids"]
            )
            np.testing.assert_array_equal(
                np.asarray(got[key]["values"]),
                np.asarray(want[key]["values"]),
            )

    def test_wire_rejects_unsliceable_spec(self):
        spec = {"auroc": metric_spec("BinaryAUROC")}
        with self.assertRaises(AdmissionError) as ctx:
            self.client.attach("w2", spec, slices=True)
        self.assertEqual(ctx.exception.reason, "bad_metrics")


if __name__ == "__main__":
    unittest.main()

"""Hot-tenant splitting (ISSUE 19 tentpole leg b): one tenant's stream
sharded across hosts as replica tenants, each under its own seq
namespace (exactly-once holds PER REPLICA), merged back into one result
at ``compute()``. The marquee claim: a split SLICED tenant's merged
compute is bit-identical to the single-stream oracle — including
through a replica's host dying mid-stream (checkpoint + replay). The
metric states here are count-valued, so the merge fold is exact in
float arithmetic regardless of which replica saw which batch."""

import tempfile
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    MulticlassAccuracy,
)
from torcheval_tpu.serve import (
    EvalDaemon,
    EvalRouter,
    EvalServer,
    ServeError,
)

NUM_CLASSES = 5
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
SLICED_SPEC = {
    "acc": ["BinaryAccuracy", {}],
    "auroc": ["BinaryAUROC", {}],
}


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(batches):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for s, l in batches:
        m.update(s, l)
    return float(np.asarray(m.compute()))


def _sliced_batches(seed=0, n_batches=6, n=64):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(0, 9, n).astype(np.int64) * 13 - 5
        s = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.4).astype(np.float32)
        out.append((ids, s, t))
    return out


class _ClusterMixin:
    N_HOSTS = 3

    def setUp(self):
        obs.reset()
        self.root = tempfile.mkdtemp(prefix="tpu_split_test_")
        self.daemons, self.servers = [], []
        for _ in range(self.N_HOSTS):
            daemon = EvalDaemon(evict_dir=self.root).start()
            server = EvalServer(daemon)
            self.daemons.append(daemon)
            self.servers.append(server)
            self.addCleanup(daemon.stop)
            self.addCleanup(server.close)
        self.router = EvalRouter(
            [s.endpoint for s in self.servers],
            request_timeout_s=10.0,
            connect_timeout_s=1.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.router.close)

    def _kill_host(self, endpoint):
        idx = [s.endpoint for s in self.servers].index(endpoint)
        self.servers[idx].close()
        self.daemons[idx].stop()

    def _daemon_for(self, endpoint):
        return self.daemons[
            [s.endpoint for s in self.servers].index(endpoint)
        ]


class TestSplitMechanics(_ClusterMixin, unittest.TestCase):
    def test_split_validation(self):
        self.router.attach("ten", SPEC)
        for bad in (1, 0, -2, True, 2.0):
            with self.assertRaises(ValueError):
                self.router.split_tenant("ten", replicas=bad)
        self.router.split_tenant("ten", replicas=2)
        with self.assertRaises(ServeError) as ctx:
            self.router.split_tenant("ten", replicas=2)
        self.assertEqual(ctx.exception.reason, "split_tenant")
        with self.assertRaises(ServeError) as ctx:
            self.router.split_tenant("ten@r1", replicas=2)
        self.assertEqual(ctx.exception.reason, "split_tenant")

    def test_split_spreads_replicas_and_counts(self):
        obs.enable()
        self.addCleanup(obs.disable)
        self.router.attach("ten", SPEC)
        placed = self.router.split_tenant("ten", replicas=3)
        self.assertEqual(
            sorted(placed), ["ten", "ten@r1", "ten@r2"]
        )
        # replica spreading prefers hosts the tenant does not occupy
        self.assertEqual(len(set(placed.values())), 3)
        snap = obs.snapshot()
        self.assertEqual(
            snap["counters"].get("serve.router.splits{tenant=ten}"), 1.0
        )

    def test_fan_out_reaches_every_replica(self):
        self.router.attach("ten", SPEC)
        placed = self.router.split_tenant("ten", replicas=3)
        for i in range(30):
            self.router.submit("ten", *_batch(i))
        self.router.flush("ten")  # drain the async ingest queues
        processed = {
            rid: self._daemon_for(ep).health()["tenants"][rid][
                "processed"
            ]
            for rid, ep in placed.items()
        }
        self.assertEqual(sum(processed.values()), 30)
        for rid, count in processed.items():
            self.assertGreater(count, 0, f"{rid} got no batches")

    def test_flush_and_detach_cover_all_replicas(self):
        self.router.attach("ten", SPEC)
        placed = self.router.split_tenant("ten", replicas=2)
        for i in range(6):
            self.router.submit("ten", *_batch(i))
        flushed = self.router.flush("ten")
        self.assertEqual(sorted(flushed), sorted(placed))
        for out in flushed.values():
            self.assertIn("path", out)
        self.router.detach("ten")
        self.assertEqual(self.router.placement(), {})
        with self.assertRaises(ServeError):
            self.router.compute("ten")

    def test_more_replicas_than_hosts_still_splits(self):
        self.router.attach("ten", SPEC)
        placed = self.router.split_tenant("ten", replicas=5)
        self.assertEqual(len(placed), 5)
        for i in range(10):
            self.router.submit("ten", *_batch(i))
        self.assertEqual(
            float(np.asarray(self.router.compute("ten")["acc"])),
            _oracle([_batch(i) for i in range(10)]),
        )


class TestMergedCompute(_ClusterMixin, unittest.TestCase):
    def test_merged_compute_matches_single_stream_oracle(self):
        self.router.attach("ten", SPEC)
        self.router.split_tenant("ten", replicas=3)
        batches = [_batch(i) for i in range(24)]
        for b in batches:
            self.router.submit("ten", *b)
        got = float(np.asarray(self.router.compute("ten")["acc"]))
        self.assertEqual(got, _oracle(batches))
        # compute is repeatable (flush/restore does not consume state)
        again = float(np.asarray(self.router.compute("ten")["acc"]))
        self.assertEqual(again, got)

    def test_split_sliced_tenant_merges_bit_identical(self):
        """The marquee demo: a SLICED tenant (per-cohort state) split
        across hosts merges via ``merge_collections`` — cohorts re-keyed
        by original id — bit-identical to one daemon that saw the whole
        stream in order."""
        batches = _sliced_batches(seed=7)
        with EvalDaemon() as local:
            h = local.attach(
                "ref",
                {"acc": BinaryAccuracy(), "auroc": BinaryAUROC()},
                approx=1024,
                slices={"capacity": 4},
            )
            for b in batches:
                h.submit(*b)
            want = h.compute()
        self.router.attach(
            "ten", SLICED_SPEC, approx=1024, slices={"capacity": 4}
        )
        self.router.split_tenant("ten", replicas=3)
        for b in batches:
            self.router.submit("ten", *b)
        got = self.router.compute("ten")
        # cohort REGISTRATION order differs between one stream and a
        # sharded one (ids intern in arrival order per replica), so the
        # bit-identical claim is per cohort: align both results by slice
        # id, then every value must match exactly
        for key in ("acc", "auroc"):
            got_ids = np.asarray(got[key]["slice_ids"])
            want_ids = np.asarray(want[key]["slice_ids"])
            np.testing.assert_array_equal(
                np.sort(got_ids), np.sort(want_ids)
            )
            got_vals = np.asarray(got[key]["values"])
            want_vals = np.asarray(want[key]["values"])
            np.testing.assert_array_equal(
                got_vals[np.argsort(got_ids)],
                want_vals[np.argsort(want_ids)],
            )


class TestSplitSurvivesReplicaHostDeath(_ClusterMixin, unittest.TestCase):
    def test_replica_host_killed_mid_stream_stays_exactly_once(self):
        """ISSUE 19 satellite 3: one replica's host dies mid-stream with
        a durable batch AND an un-durable tail; the per-replica
        migration (checkpoint restore + replay) carries both; the merged
        compute is bit-identical to the fault-free oracle with zero
        duplicate applications."""
        obs.enable()
        self.addCleanup(obs.disable)
        self.router.attach("ten", SPEC)
        placed = self.router.split_tenant("ten", replicas=2)
        batches = [_batch(i) for i in range(12)]
        for b in batches[:6]:
            self.router.submit("ten", *b)
        self.router.flush("ten")  # all replicas durable
        for b in batches[6:9]:
            self.router.submit("ten", *b)  # un-durable tails
        victim_ep = placed["ten@r1"]
        self._kill_host(victim_ep)
        # the next submits hit the dead replica host -> transport
        # failure -> that REPLICA migrates (its own checkpoint + replay);
        # the sibling replica is untouched
        for b in batches[9:]:
            self.router.submit("ten", *b)
        got = float(np.asarray(self.router.compute("ten")["acc"]))
        self.assertEqual(got, _oracle(batches))
        placement = self.router.placement()
        self.assertNotEqual(placement["ten@r1"], victim_ep)
        # zero dupes on every surviving daemon (the oracle equality
        # above already rules out loss: a count-valued metric changes on
        # any lost or doubled batch). "processed" counts only post-attach
        # applications, so checkpoint-restored batches don't appear here.
        for rid, ep in placement.items():
            health = self._daemon_for(ep).health()
            self.assertEqual(health["tenants"][rid]["dupes"], 0, rid)
        snap = obs.snapshot()
        migrations = [
            v
            for k, v in snap["counters"].items()
            if k.startswith("serve.router.migrations{")
        ]
        self.assertEqual(sum(migrations), 1.0)

    def test_sliced_split_survives_replica_death_bit_identical(self):
        """The marquee claim under fault: the split SLICED tenant keeps
        its per-cohort bit-identity through a replica's host dying
        mid-stream (ISSUE 19 acceptance)."""
        batches = _sliced_batches(seed=11, n_batches=9)
        with EvalDaemon() as local:
            h = local.attach(
                "ref",
                {"acc": BinaryAccuracy(), "auroc": BinaryAUROC()},
                approx=1024,
                slices={"capacity": 4},
            )
            for b in batches:
                h.submit(*b)
            want = h.compute()
        self.router.attach(
            "ten", SLICED_SPEC, approx=1024, slices={"capacity": 4}
        )
        placed = self.router.split_tenant("ten", replicas=2)
        for b in batches[:4]:
            self.router.submit("ten", *b)
        self.router.flush("ten")  # durable point on every replica
        for b in batches[4:6]:
            self.router.submit("ten", *b)  # un-durable tails
        self._kill_host(placed["ten@r1"])
        for b in batches[6:]:
            self.router.submit("ten", *b)  # rides migration + replay
        got = self.router.compute("ten")
        for key in ("acc", "auroc"):
            got_ids = np.asarray(got[key]["slice_ids"])
            want_ids = np.asarray(want[key]["slice_ids"])
            np.testing.assert_array_equal(
                np.sort(got_ids), np.sort(want_ids)
            )
            np.testing.assert_array_equal(
                np.asarray(got[key]["values"])[np.argsort(got_ids)],
                np.asarray(want[key]["values"])[np.argsort(want_ids)],
            )
        self.assertNotEqual(
            self.router.placement()["ten@r1"], placed["ten@r1"]
        )


if __name__ == "__main__":
    unittest.main()

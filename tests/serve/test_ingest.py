"""Zero-copy overlapped ingest (ISSUE 11): pooled staging buffers,
zero-allocation npz decode, coalesced H2D, and the aliasing contract.

Four contracts pinned here:

* **Zero-copy decode** — `unpack_tree` returns leaf VIEWS over the
  payload buffer on the steady path (aligned, uncompressed npz): no
  per-leaf heap allocation (tracemalloc, mirroring
  tests/obs/test_host_overhead.py), shared memory proven directly, and
  the copy fallback (compressed archives) plus the `allow_pickle=False`
  object-array rejection both intact.
* **Pool aliasing safety** — a buffer released under a still-in-flight
  anchor is NOT recycled: the next acquire comes from a fresh slot
  (`result=grow`), and only the anchor's retirement frees the old one.
  Pool shrinks under idle; release is idempotent.
* **No leak across quarantine** — a quarantined tenant's queued batches
  release their staged buffers (the drop paths in the daemon), proven by
  the pool's in-flight census returning to zero.
* **Coalesced H2D + ownership** — one `device_put` per signature group
  per serving pass; identical host arrays share one device buffer and
  are demoted to ``owned=False`` (never donated), distinct ones keep
  ``owned=True``.
"""

import io
import time
import tracemalloc
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.serve.errors import WireError
from torcheval_tpu.serve.ingest import HostBufferPool, coalesce_h2d
from torcheval_tpu.serve.wire import pack_tree, unpack_tree

NUM_CLASSES = 5


def _payload(n=4096, c=NUM_CLASSES, seed=0):
    rng = np.random.default_rng(seed)
    scores = rng.random((n, c)).astype(np.float32)
    labels = rng.integers(0, c, n)
    spec, blob = pack_tree([scores, labels])
    return spec, blob, scores, labels


class _FakeAnchor:
    """Controllable execution anchor (the `.is_ready()` protocol)."""

    def __init__(self, ready=False):
        self.ready = ready

    def is_ready(self):
        return self.ready


class TestZeroCopyDecode(unittest.TestCase):
    def test_steady_path_leaves_are_views(self):
        spec, blob, scores, labels = _payload()
        out = unpack_tree(spec, blob)
        np.testing.assert_array_equal(out[0], scores)
        np.testing.assert_array_equal(out[1], labels)
        for leaf in out:
            self.assertFalse(leaf.flags.owndata, "leaf copied, not a view")
        # the views genuinely alias the payload bytes
        payload_arr = np.frombuffer(blob, dtype=np.uint8)
        for leaf in out:
            self.assertTrue(np.shares_memory(leaf, payload_arr))

    def test_memoryview_payload_decodes_zero_copy(self):
        # the pooled-receive shape: payload lands in a writable backing
        # store and decodes through a memoryview
        spec, blob, scores, _ = _payload(seed=1)
        backing = np.frombuffer(blob, dtype=np.uint8).copy()
        out = unpack_tree(spec, memoryview(backing))
        np.testing.assert_array_equal(out[0], scores)
        self.assertTrue(np.shares_memory(out[0], backing))

    def test_steady_decode_performs_no_per_leaf_allocation(self):
        # regression pin for the ISSUE 11 satellite: decoding a ~160 KB
        # payload must not allocate per-leaf data buffers — only O(100 B)
        # view/spec objects. Generous 8 KB/decode bound vs the 80 KB a
        # single leaf copy would show.
        spec, blob, *_ = _payload(n=8192)
        for _ in range(3):
            unpack_tree(spec, blob)  # warm caches off the measured window
        n_iters = 20
        tracemalloc.start()
        try:
            snap0 = tracemalloc.take_snapshot()
            keep = [unpack_tree(spec, blob) for _ in range(n_iters)]
            snap1 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        grown = sum(
            d.size_diff
            for d in snap1.compare_to(snap0, "filename")
            if d.size_diff > 0
        )
        self.assertGreater(len(keep), 0)
        self.assertLess(
            grown / n_iters,
            8192,
            f"decode allocated ~{grown // n_iters} B/iteration — a leaf "
            "is being copied on the steady path",
        )

    def test_compressed_payload_falls_back_to_copy(self):
        arr = np.arange(100, dtype=np.float64)
        buf = io.BytesIO()
        np.savez_compressed(buf, a0=arr)
        out = unpack_tree({"t": "arr", "i": "a0"}, buf.getvalue())
        np.testing.assert_array_equal(out, arr)
        self.assertTrue(out.flags.owndata)  # decompression must copy

    def test_object_arrays_still_reject(self):
        buf = io.BytesIO()
        np.savez(buf, a0=np.array([{"pickle": "bomb"}], dtype=object))
        with self.assertRaises(WireError):
            unpack_tree({"t": "arr", "i": "a0"}, buf.getvalue())

    def test_garbage_payload_still_rejects_as_protocol(self):
        with self.assertRaises(WireError):
            unpack_tree({"t": "arr", "i": "a0"}, b"not an npz archive !!")

    def test_fortran_order_round_trips(self):
        arr = np.asfortranarray(
            np.arange(12, dtype=np.int32).reshape(3, 4)
        )
        spec, blob = pack_tree([arr])
        out = unpack_tree(spec, blob)
        np.testing.assert_array_equal(out[0], arr)


class TestHostBufferPool(unittest.TestCase):
    def test_hit_miss_grow_counters(self):
        obs.enable()
        obs.reset()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        from torcheval_tpu.obs import registry as reg

        pool = HostBufferPool()
        a = pool.acquire(1000)  # miss: first of its class
        a.release()  # no anchor: straight to the free list
        b = pool.acquire(1000)  # hit
        anchor = _FakeAnchor(ready=False)
        b.release(anchor=anchor)  # in flight: cooling, not free
        c = pool.acquire(1000)  # grow: the class's slot is still cooling
        counters = reg.snapshot()["counters"]
        self.assertEqual(counters.get("serve.ingest.pool{result=miss}"), 1.0)
        self.assertEqual(counters.get("serve.ingest.pool{result=hit}"), 1.0)
        self.assertEqual(counters.get("serve.ingest.pool{result=grow}"), 1.0)
        self.assertIsNot(b, c)

    def test_inflight_buffer_not_recycled_until_anchor_retires(self):
        pool = HostBufferPool()
        buf = pool.acquire(2048)
        view = buf.view(16)
        view[:] = b"A" * 16
        anchor = _FakeAnchor(ready=False)
        buf.release(anchor=anchor)
        fresh = pool.acquire(2048)
        # aliasing contract: the in-flight buffer's memory is untouched
        self.assertIsNot(fresh, buf)
        self.assertEqual(bytes(view), b"A" * 16)
        self.assertEqual(pool.stats()["cooling"], 1)
        # retire the execution: the slot comes back
        anchor.ready = True
        fresh.release()
        again = pool.acquire(2048)
        self.assertEqual(pool.stats()["cooling"], 0)
        again.release()

    def test_shared_stage_frees_only_when_all_anchors_retire(self):
        # one submit_many frame's batches can ride DIFFERENT coalesced
        # transfers: the slot must stay cooling until every contributed
        # anchor retires, not just the last release's
        from torcheval_tpu.serve.ingest import SharedStage

        pool = HostBufferPool()
        buf = pool.acquire(1024)
        shared = SharedStage(buf, 3)
        slow = _FakeAnchor(ready=False)
        fast = _FakeAnchor(ready=True)
        shared.release(anchor=slow)
        shared.release(anchor=fast)
        buf.release()  # belt-and-braces direct release: no-op while split
        self.assertFalse(buf.released)
        shared.release()  # last share, NO anchor of its own
        self.assertTrue(buf.released)
        # still cooling: the slow transfer has not retired
        other = pool.acquire(1024)
        self.assertIsNot(other, buf)
        self.assertEqual(pool.stats()["cooling"], 1)
        slow.ready = True
        other.release()
        self.assertIs(pool.acquire(1024), buf)

    def test_release_is_idempotent(self):
        pool = HostBufferPool()
        buf = pool.acquire(100)
        buf.release()
        buf.release()
        self.assertEqual(pool.stats()["free"], 1)

    def test_pool_shrinks_under_idle(self):
        pool = HostBufferPool(idle_ttl_s=0.01)
        bufs = [pool.acquire(4096) for _ in range(3)]
        for b in bufs:
            b.release()
        self.assertEqual(pool.stats()["free"], 3)
        time.sleep(0.03)
        pool.shrink()
        self.assertEqual(pool.stats()["free"], 0)

    def test_size_classing_rounds_up(self):
        pool = HostBufferPool()
        buf = pool.acquire(5000)
        self.assertEqual(buf.nbytes, 8192)
        buf.release()
        # a smaller request of the same class reuses the slot
        self.assertIs(pool.acquire(8000), buf)


class TestCoalescedH2D(unittest.TestCase):
    def test_one_transfer_per_group_and_ownership(self):
        obs.enable()
        obs.reset()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        from torcheval_tpu.obs import registry as reg
        from torcheval_tpu.obs import trace as obs_trace

        rng = np.random.default_rng(2)
        shared = rng.random((8, 3)).astype(np.float32)
        distinct_a = rng.integers(0, 3, 8)
        distinct_b = rng.integers(0, 3, 8)
        obs_trace.clear()
        placed, owned = coalesce_h2d(
            [(shared, distinct_a), (shared, distinct_b)]
        )
        # 3 unique host arrays -> 3 device arrays in ONE transfer event
        transfers = [
            e
            for e in obs_trace.events()
            if e["name"] == "serve.ingest.transfer"
        ]
        self.assertEqual(len(transfers), 1)
        self.assertEqual(transfers[0]["labels"]["arrays"], 3)
        self.assertEqual(
            reg.snapshot()["counters"].get("serve.ingest.h2d_bytes"),
            float(
                shared.nbytes + distinct_a.nbytes + distinct_b.nbytes
            ),
        )
        # identical host arrays share ONE device buffer; sharers are not
        # donation-safe, exclusive batches are
        self.assertIs(placed[0][0], placed[1][0])
        self.assertIsNot(placed[0][1], placed[1][1])
        self.assertEqual(owned, [False, False])
        np.testing.assert_array_equal(np.asarray(placed[0][0]), shared)
        np.testing.assert_array_equal(np.asarray(placed[1][1]), distinct_b)

    def test_exclusive_batches_stay_owned(self):
        rng = np.random.default_rng(3)
        batches = [
            (
                rng.random((4, 2)).astype(np.float32),
                rng.integers(0, 2, 4),
            )
            for _ in range(3)
        ]
        placed, owned = coalesce_h2d(batches)
        self.assertEqual(owned, [True, True, True])
        for (hs, hl), (ds, dl) in zip(batches, placed):
            np.testing.assert_array_equal(np.asarray(ds), hs)
            np.testing.assert_array_equal(np.asarray(dl), hl)


class TestScatterSend(unittest.TestCase):
    def test_frame_with_more_parts_than_iov_max_round_trips(self):
        # Linux sendmsg rejects >IOV_MAX (1024) segments with EMSGSIZE;
        # _send_parts must chunk. 600 leaves -> ~1200 parts.
        import socket
        import threading

        from torcheval_tpu.serve.wire import (
            pack_tree_parts,
            recv_frame,
            send_frame_parts,
        )

        tree = [np.full((3,), i, dtype=np.int32) for i in range(600)]
        spec, parts, total = pack_tree_parts(tree)
        self.assertGreater(len(parts), 1024)
        a, b = socket.socketpair()
        self.addCleanup(a.close)
        self.addCleanup(b.close)
        box = {}
        t = threading.Thread(
            target=lambda: box.update(frame=recv_frame(b))
        )
        t.start()
        send_frame_parts(a, {"op": "x"}, parts, total)
        t.join(10.0)
        _hdr, payload = box["frame"]
        got = unpack_tree(spec, payload)
        for i, g in enumerate(got):
            self.assertEqual(int(g[0]), i)


class TestBufferedClientRecovery(unittest.TestCase):
    def test_failed_coalesced_drain_redelivers_before_compute(self):
        # a transport failure mid submit_many empties the send tail but
        # the batches stay booked in replay; compute() must see them all
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer

        rng = np.random.default_rng(7)
        batches = [
            (
                rng.random((16, NUM_CLASSES)).astype(np.float32),
                rng.integers(0, NUM_CLASSES, 16),
            )
            for _ in range(6)
        ]
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for s, l in batches:
            oracle.update(s, l)
        want = np.asarray(oracle.compute()).tobytes()
        with EvalDaemon() as daemon:
            server = EvalServer(daemon)
            self.addCleanup(server.close)
            client = EvalClient(
                server.endpoint, submit_buffer=3, max_attempts=1
            )
            self.addCleanup(client.close)
            client.attach(
                "t",
                {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]},
            )
            client.submit("t", *batches[0])
            client.submit("t", *batches[1])
            orig = client._call
            tripped = []

            def flaky(op, *a, **k):
                if op == "submit_many" and not tripped:
                    tripped.append(op)
                    raise WireError(
                        "transport", "injected", endpoint=client.endpoint
                    )
                return orig(op, *a, **k)

            client._call = flaky
            with self.assertRaises(WireError) as ctx:
                client.submit("t", *batches[2])  # drain of 3 fails
            self.assertTrue(getattr(ctx.exception, "batch_booked", False))
            client._call = orig
            for s, l in batches[3:]:
                client.submit("t", s, l)
            got = client.compute("t")
        self.assertEqual(np.asarray(got["acc"]).tobytes(), want)


class TestDaemonIngestLifecycle(unittest.TestCase):
    def test_quarantine_releases_staged_buffers(self):
        # a poisoned tenant's queued batches must hand their staging
        # slots back (TenantQuarantinedError never leaks pool memory)
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer
        from torcheval_tpu.serve.errors import TenantQuarantinedError

        rng = np.random.default_rng(4)
        scores = rng.random((16, NUM_CLASSES)).astype(np.float32)
        labels = rng.integers(0, NUM_CLASSES, 16)
        with EvalDaemon() as daemon:
            server = EvalServer(daemon)
            self.addCleanup(server.close)
            client = EvalClient(server.endpoint, max_attempts=1)
            self.addCleanup(client.close)
            spec = {
                "acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]
            }
            client.attach("t", spec)
            self.assertTrue(client.submit("t", scores, labels))
            # poison: mismatched batch lengths fail update validation on
            # the worker; anything queued behind it drops with the tenant
            try:
                client.submit("t", scores[:4], labels[:3])
            except TenantQuarantinedError:
                pass
            # the poison processes on the worker asynchronously: keep
            # submitting until the quarantine surfaces
            quarantined = False
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not quarantined:
                try:
                    client.submit("t", scores, labels)
                except TenantQuarantinedError:
                    quarantined = True
                else:
                    time.sleep(0.02)
            self.assertTrue(quarantined)
            # every staging slot is back (free or cooling-with-retired
            # anchor): acquiring the census shows nothing held
            pool = server._pool
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                pool.shrink(now=time.monotonic() - 1e6)  # force-sweep
                stats = pool.stats()
                if stats["cooling"] == 0:
                    break
                time.sleep(0.05)
            self.assertEqual(stats["cooling"], 0, stats)

    def test_wire_and_local_results_bit_identical(self):
        # the staged/coalesced path must be a physical change only: the
        # wire-fed tenant computes the exact bits the in-process path does
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer

        rng = np.random.default_rng(5)
        batches = [
            (
                rng.random((32, NUM_CLASSES)).astype(np.float32),
                rng.integers(0, NUM_CLASSES, 32),
            )
            for _ in range(6)
        ]
        with EvalDaemon() as daemon:
            server = EvalServer(daemon)
            self.addCleanup(server.close)
            client = EvalClient(server.endpoint)
            self.addCleanup(client.close)
            client.attach(
                "wire",
                {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]},
            )
            local = daemon.attach(
                "local", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
            )
            for s, l in batches:
                client.submit("wire", s, l)
                local.submit(s, l, block=True, timeout=60)
            wire_res = client.compute("wire")
            local_res = local.compute(timeout=60)
        self.assertEqual(
            np.asarray(wire_res["acc"]).tobytes(),
            np.asarray(local_res["acc"]).tobytes(),
        )

    def test_window_chunks_knob_reaches_the_collection(self):
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.serve import EvalDaemon

        with EvalDaemon() as daemon:
            h = daemon.attach(
                "t",
                {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
                window_chunks=4,
            )
            probe = daemon._tenants["t"].collection._defer_probe
            self.assertEqual(probe._DEFER_MAX_CHUNKS, 4)
            h.detach(timeout=60)
            with self.assertRaises(ValueError):
                daemon.attach(
                    "t2",
                    {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
                    window_chunks=0,
                )


class TestWindowOverlapHistogram(unittest.TestCase):
    def test_overlap_recorded_while_previous_step_in_flight(self):
        # deterministic double-buffer telemetry check: plant a fake
        # still-executing anchor as "window N's step", fill window N+1,
        # close it — the fill time must land in the overlap histogram
        from torcheval_tpu.metrics import MetricCollection, MulticlassAccuracy
        from torcheval_tpu.metrics import deferred as deferred_mod

        obs.enable()
        obs.reset()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        rng = np.random.default_rng(6)
        col = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
        )
        batches = [
            (
                rng.random((8, NUM_CLASSES)).astype(np.float32),
                rng.integers(0, NUM_CLASSES, 8),
            )
            for _ in range(3)
        ]
        prev = deferred_mod._last_window_anchor
        deferred_mod._last_window_anchor = _FakeAnchor(ready=False)
        try:
            for s, l in batches:
                col.update(s, l)
            col.compute()
        finally:
            deferred_mod._last_window_anchor = prev
        from torcheval_tpu.obs import registry as reg

        histos = reg.snapshot()["histograms"]
        self.assertIn("deferred.window.overlap_ms", histos)
        self.assertGreater(
            histos["deferred.window.overlap_ms"]["count"], 0
        )


if __name__ == "__main__":
    unittest.main()

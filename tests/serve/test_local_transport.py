"""Shared-memory same-host transport (ISSUE 18): the staging-pool slot
(or the client's own payload bytes) IS the buffer the daemon decodes —
no socket write+read copy pair.

Contracts pinned here:

* **Zero-copy decode** — a ``bytes`` submit payload crosses as the
  decode buffer itself: the daemon-side npz leaves are VIEWS
  (``owndata=False``, no staging slot), and repeated ``local_request``
  dispatch allocates ~nothing per call (tracemalloc, mirroring the
  PR 11 ``unpack_tree`` pin). A scatter-gather ``submit_many`` payload
  is assembled ONCE into a ``HostBufferPool`` slot whose memory the
  decoded leaves share.
* **Byte-identical semantics** — the same batches through the local
  path and the forced-TCP path produce identical metric results, and
  structured rejects surface identically (same dispatch).
* **Automatic selection + fallback** — the in-process endpoint registry
  picks the local path only while the server lives there; deregistered
  (closed, or a genuinely remote endpoint), the SAME client falls back
  to the TCP wire transparently.
* **Accounting** — ``serve.ingest.local_copies_avoided_bytes`` counts
  exactly the payload bytes that skipped the socket copy pair.

All sockets bind port 0 (OS-assigned).
"""

import tracemalloc
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import (
    EvalClient,
    EvalDaemon,
    EvalServer,
    metric_spec,
)
from torcheval_tpu.serve.wire import local_server, pack_tree

NUM_CLASSES = 5
SPEC = {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)}


def _batch(seed=0, n=256):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(n_batches, n=256):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for i in range(n_batches):
        m.update(*_batch(seed=i, n=n))
    return float(np.asarray(m.compute()))


class _SpyHandle:
    """Stands in for the server's TenantHandle: captures the decoded
    args + stage the dispatch hands over (releasing the stage like the
    daemon would) so the test can inspect the aliasing directly."""

    def __init__(self):
        self.captured = []
        self._tenant = type(
            "T", (), {"durable_seq": 0, "last_seq": 0}
        )()

    def submit(self, *args, seq=None, stage=None, **kw):
        self.captured.append((args, stage))
        if stage is not None:
            stage.release()
        return True


class _PairMixin:
    def _pair(self, **client_kw):
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(server.endpoint, **client_kw)
        self.addCleanup(client.close)
        return daemon, server, client


class TestEndpointRegistry(_PairMixin, unittest.TestCase):
    def test_registered_while_running_gone_after_close(self):
        daemon = EvalDaemon().start()
        self.addCleanup(daemon.stop)
        server = EvalServer(daemon)
        self.assertIs(local_server(server.endpoint), server)
        server.close()
        self.assertIsNone(local_server(server.endpoint))

    def test_closed_server_raises_oserror_locally(self):
        daemon = EvalDaemon().start()
        self.addCleanup(daemon.stop)
        server = EvalServer(daemon)
        server.close()
        with self.assertRaises(OSError):
            server.local_request({"op": "submit", "tenant": "t"}, b"")


class TestZeroCopyLocalDecode(_PairMixin, unittest.TestCase):
    def test_bytes_payload_decodes_as_views_no_stage(self):
        _, server, client = self._pair()
        client.attach("t", SPEC)
        spy = _SpyHandle()
        with server._lock:
            server._handles["t"] = spy
        scores, labels = _batch()
        self.assertTrue(client.submit("t", scores, labels))
        (args, stage), = spy.captured
        # immutable bytes cross AS the decode buffer: leaf views, no
        # staging slot to recycle
        self.assertIsNone(stage)
        for leaf in args:
            self.assertFalse(leaf.flags.owndata, "leaf was copied")
        np.testing.assert_array_equal(args[0], scores)
        np.testing.assert_array_equal(args[1], labels)

    def test_scatter_gather_payload_lands_in_one_pool_slot(self):
        # the coalesced client ships (parts, total): local transport
        # assembles the parts ONCE into a staging-pool slot, and the
        # decoded leaves alias that slot's memory — the slot IS the
        # buffer the daemon decodes
        _, server, client = self._pair(submit_buffer=4)
        client.attach("t", SPEC)
        spy = _SpyHandle()
        with server._lock:
            server._handles["t"] = spy
        batches = [_batch(seed=i) for i in range(4)]
        for scores, labels in batches:
            self.assertTrue(client.submit("t", scores, labels))
        self.assertEqual(len(spy.captured), 4)
        from torcheval_tpu.serve.ingest import SharedStage

        stages = {id(stage) for _args, stage in spy.captured}
        self.assertEqual(len(stages), 1, "one slot shared by the group")
        shared = spy.captured[0][1]
        self.assertIsInstance(shared, SharedStage)
        for (args, _stage), (scores, labels) in zip(
            spy.captured, batches
        ):
            np.testing.assert_array_equal(args[0], scores)
            np.testing.assert_array_equal(args[1], labels)
            for leaf in args:
                self.assertFalse(leaf.flags.owndata, "leaf was copied")

    def test_local_dispatch_allocates_nothing_per_call(self):
        # the PR 11 pin, moved to the transport seam: dispatching a
        # pre-packed ~160 KB bytes payload through local_request must
        # not allocate per-leaf buffers — the decode is views over the
        # caller's own bytes. Generous 8 KB/call bound vs the ~80 KB a
        # single leaf copy (or a socket round trip's recv buffer)
        # would show.
        _, server, client = self._pair()
        client.attach("t", SPEC)
        spy = _SpyHandle()
        with server._lock:
            server._handles["t"] = spy
        scores, labels = _batch(n=8192)
        spec, blob = pack_tree([scores, labels])
        header = {"op": "submit", "tenant": "t", "seq": 1, "args": spec}
        for _ in range(3):
            server.local_request(dict(header), blob)  # warm caches
        spy.captured.clear()
        n_iters = 20
        tracemalloc.start()
        try:
            snap0 = tracemalloc.take_snapshot()
            for _ in range(n_iters):
                server.local_request(dict(header), blob)
            snap1 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        grown = sum(
            d.size_diff
            for d in snap1.compare_to(snap0, "filename")
            if d.size_diff > 0
        )
        self.assertEqual(len(spy.captured), n_iters)
        self.assertLess(
            grown / n_iters,
            8192,
            f"local dispatch allocated ~{grown // n_iters} B/call — the "
            "payload is being copied on the same-host path",
        )


class TestLocalVsTcpSemantics(unittest.TestCase):
    def _run_stream(self, client, tenant, n=6):
        client.attach(tenant, SPEC)
        for i in range(n):
            self.assertTrue(client.submit(tenant, *_batch(seed=i)))
        return float(np.asarray(client.compute(tenant)["acc"]))

    def test_bit_identical_results_and_accounting(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        local = EvalClient(server.endpoint)  # local_transport defaults on
        tcp = EvalClient(server.endpoint, local_transport=False)
        self.addCleanup(local.close)
        self.addCleanup(tcp.close)
        n = 6
        got_local = self._run_stream(local, "t-local", n)
        avoided = obs.snapshot()["counters"].get(
            "serve.ingest.local_copies_avoided_bytes", 0.0
        )
        self.assertGreater(avoided, 0.0, "local path never selected")
        got_tcp = self._run_stream(tcp, "t-tcp", n)
        self.assertEqual(got_local, got_tcp)
        self.assertEqual(got_local, _oracle(n))
        # the forced-TCP stream moved no additional local bytes
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "serve.ingest.local_copies_avoided_bytes", 0.0
            ),
            avoided,
        )
        # both streams applied fully, exactly once
        tenants = local.health()["tenants"]
        for tid in ("t-local", "t-tcp"):
            self.assertEqual(tenants[tid]["processed"], n)
            self.assertEqual(tenants[tid]["dupes"], 0)

    def test_structured_rejects_identical_across_transports(self):
        from torcheval_tpu.serve import ServeError

        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        for kw in ({}, {"local_transport": False}):
            client = EvalClient(server.endpoint, max_attempts=1, **kw)
            self.addCleanup(client.close)
            with self.assertRaises(ServeError) as ctx:
                client.submit("ghost", *_batch())
            self.assertEqual(ctx.exception.reason, "unknown_tenant", kw)

    def test_tcp_fallback_when_endpoint_not_local(self):
        # deregister the endpoint (a genuinely remote server's shape):
        # the SAME client must silently use the TCP wire and produce
        # identical results — then pick the local path back up
        from torcheval_tpu.serve import wire as _wire

        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        daemon = EvalDaemon().start()
        server = EvalServer(daemon)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        client = EvalClient(server.endpoint)
        self.addCleanup(client.close)
        client.attach("t", SPEC)
        with _wire._LOCAL_SERVERS_LOCK:
            del _wire._LOCAL_SERVERS[server.endpoint]
        try:
            self.assertTrue(client.submit("t", *_batch(seed=0)))
            self.assertEqual(
                obs.snapshot()["counters"].get(
                    "serve.ingest.local_copies_avoided_bytes", 0.0
                ),
                0.0,
                "local path used while endpoint was deregistered",
            )
        finally:
            with _wire._LOCAL_SERVERS_LOCK:
                _wire._LOCAL_SERVERS[server.endpoint] = server
        self.assertTrue(client.submit("t", *_batch(seed=1)))
        self.assertGreater(
            obs.snapshot()["counters"].get(
                "serve.ingest.local_copies_avoided_bytes", 0.0
            ),
            0.0,
        )
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(2))


if __name__ == "__main__":
    unittest.main()

"""End-to-end serve fault containment in 4 REAL processes (ISSUE 8
acceptance).

Two worlds, one worker (``mp_serve_worker.py``), the acceptance claims:

* **poison containment** — chaos corrupts one tenant's batch to NaN at the
  queue boundary on rank 1; that tenant (and only that tenant, and only on
  that rank) surfaces a structured ``TenantQuarantinedError``; every other
  tenant's computed results — on EVERY rank, the poisoned one included —
  are bit-identical to a fault-free oracle, and the daemon never crashes.
* **eviction resume** — a tenant evicted mid-stream (atomic checkpoint)
  reattaches with ``resume="require"`` and finishes bit-identically.
* **sync degradation through the daemon** — with rank 2 killed (kill
  world) or straggling (delay world) mid-collective, the surviving
  daemons' ``sync_compute(timeout_s=, on_failure="local")`` returns each
  rank's LOCAL value within the deadline; the healthy sync before the
  fault returned the true global value.

Workers write per-tenant obs snapshots and daemon health snapshots next to
their results; CI uploads the directory on every run.
"""

import json
import os
import socket
import subprocess
import sys
import unittest

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_serve_worker.py")
WORLD = 4

sys.path.insert(0, _HERE)
from mp_serve_worker import (  # noqa: E402
    CHAOS_EXIT_CODE,
    FAULT_RANK,
    NUM_CLASSES,
    POISON_RANK,
    TIMEOUT_S,
    tenant_stream,
)

STRAGGLE_S = 20.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _global_mean(batches) -> float:
    scores = np.concatenate([s for s, _ in batches])
    labels = np.concatenate([l for _, l in batches])
    return float((scores.argmax(1) == labels).mean())


def _oracle(rank: int, tenant: str, phases=(0,)) -> float:
    """Fault-free oracle: the library's own metric, driven with the same
    per-phase compute cadence the daemon used, so the fold grouping — and
    therefore the float32 summation order — is identical and the
    comparison is exact, not approximate."""
    from torcheval_tpu.metrics import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    val = None
    for ph in phases:
        for s, l in tenant_stream(rank, tenant, phases=(ph,)):
            m.update(s, l)
        val = float(np.asarray(m.compute()))
    return val


def _artifact_dir(scenario: str) -> str:
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, f"serve_faults_{scenario}")
        os.makedirs(out, exist_ok=True)
        return out
    import tempfile

    return tempfile.mkdtemp(prefix=f"tpu_serve_{scenario}_")


def _launch_world(tmpdir: str, action: str):
    port = _free_port()
    base = dict(os.environ)
    base["PYTHONPATH"] = _REPO + os.pathsep + base.get("PYTHONPATH", "")
    base.pop("XLA_FLAGS", None)
    for k in list(base):
        if k.startswith("TORCHEVAL_TPU_CHAOS"):
            del base[k]
    procs = []
    for r in range(WORLD):
        env = dict(base)
        if r == POISON_RANK:
            # the queue-boundary fault: bob's 2nd batch becomes all-NaN
            env.update(
                {
                    "TORCHEVAL_TPU_CHAOS": "1",
                    "TORCHEVAL_TPU_CHAOS_ACTION": "poison",
                    "TORCHEVAL_TPU_CHAOS_TENANT": "bob",
                    "TORCHEVAL_TPU_CHAOS_STEP": "2",
                    "TORCHEVAL_TPU_CHAOS_POISON": "nan",
                }
            )
        elif r == FAULT_RANK:
            # the collective-funnel fault: die/straggle entering sync B
            env.update(
                {
                    "TORCHEVAL_TPU_CHAOS": "1",
                    "TORCHEVAL_TPU_CHAOS_ACTION": action,
                    "TORCHEVAL_TPU_CHAOS_RANK": str(FAULT_RANK),
                    "TORCHEVAL_TPU_CHAOS_ROUND": "3",
                    "TORCHEVAL_TPU_CHAOS_DELAY_S": str(STRAGGLE_S),
                    "TORCHEVAL_TPU_CHAOS_EXIT_CODE": str(CHAOS_EXIT_CODE),
                }
            )
        if action == "delay":
            env["TORCHEVAL_TPU_CHAOS_HOLD_S"] = str(
                STRAGGLE_S - TIMEOUT_S + 8.0
            )
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER, str(r), str(WORLD), str(port), tmpdir],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    return procs, outs


class _ServeWorldMixin:
    ACTION = "kill"

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = _artifact_dir(cls.ACTION)
        procs, outs = _launch_world(cls.tmpdir, cls.ACTION)
        cls.returncodes = [p.returncode for p in procs]
        cls.outs = outs
        cls.results = {}
        for r in range(WORLD):
            path = os.path.join(cls.tmpdir, f"rank{r}.json")
            if os.path.exists(path):
                with open(path) as f:
                    cls.results[r] = json.load(f)

    def _survivors(self):
        if self.ACTION == "kill":
            return [r for r in range(WORLD) if r != FAULT_RANK]
        return list(range(WORLD))

    # ------------------------------------------------- poison containment
    def test_poisoned_tenant_quarantined_with_structured_error(self):
        res = self.results[POISON_RANK]
        self.assertIn("bob_quarantined", res, f"rank {POISON_RANK}: {res}")
        self.assertEqual(res["bob_quarantined"]["reason"], "nan_policy")
        self.assertEqual(res["bob_quarantined"]["tenant"], "bob")

    def test_other_ranks_bob_unaffected(self):
        for r in self._survivors():
            if r == POISON_RANK:
                continue
            want = _oracle(r, "bob")
            self.assertEqual(self.results[r].get("bob_phase0"), want)

    def test_other_tenants_bit_identical_to_fault_free_oracle(self):
        # the poisoned rank INCLUDED: quarantining bob must not perturb
        # alice or carol anywhere
        for r in self._survivors():
            res = self.results[r]
            self.assertEqual(res["alice_phase0"], _oracle(r, "alice"))
            self.assertEqual(
                res["carol_resumed"], _oracle(r, "carol", phases=(0, 1))
            )

    # --------------------------------------------------- eviction resume
    def test_evicted_tenant_resumed_from_checkpoint(self):
        for r in self._survivors():
            self.assertTrue(self.results[r]["carol_ckpt_exists"])

    # ------------------------------------------------------- sync legs
    def test_healthy_sync_returned_global_value(self):
        all_batches = []
        for r in range(WORLD):
            all_batches.extend(tenant_stream(r, "alice"))
        want = _global_mean(all_batches)
        for r in self._survivors():
            self.assertAlmostEqual(
                self.results[r]["alice_syncA"], want, places=6
            )

    def test_faulted_sync_degraded_to_local_within_deadline(self):
        for r in self._survivors():
            if r == FAULT_RANK:
                continue
            res = self.results[r]
            self.assertEqual(res["alice_syncB"], res["alice_local_post"])
            self.assertEqual(
                res["alice_syncB"], _oracle(r, "alice", phases=(0, 1))
            )
            self.assertLess(res["syncB_elapsed_s"], TIMEOUT_S + 30.0)
            self.assertEqual(res["timeouts_local"], 1.0)

    # ------------------------------------------------------- plumbing
    def test_survivors_exited_cleanly(self):
        for r in self._survivors():
            self.assertEqual(
                self.returncodes[r],
                0,
                f"rank {r} exited {self.returncodes[r]}:\n{self.outs[r][-4000:]}",
            )

    def test_per_tenant_obs_and_health_snapshots_written(self):
        for r in self._survivors():
            with open(os.path.join(self.tmpdir, f"rank{r}.obs.json")) as f:
                snap = json.load(f)
            ingest = [
                k
                for k in snap["counters"]
                if k.startswith("serve.ingest.batches{")
            ]
            self.assertTrue(ingest, f"rank {r}: no per-tenant ingest counters")
            if r == POISON_RANK:
                quarantines = [
                    k
                    for k in snap["counters"]
                    if k.startswith("serve.quarantines{")
                ]
                self.assertTrue(quarantines)
            with open(
                os.path.join(self.tmpdir, f"rank{r}.health.json")
            ) as f:
                health = json.load(f)
            self.assertIn("tenants", health)
            self.assertIn("alice", health["tenants"])


class TestServeKillWorld(_ServeWorldMixin, unittest.TestCase):
    """Rank 2 hard-dies (os._exit) inside the daemon worker's collective."""

    ACTION = "kill"

    def test_killed_rank_died_with_injected_exit_code(self):
        self.assertEqual(self.returncodes[FAULT_RANK], CHAOS_EXIT_CODE)
        self.assertNotIn(FAULT_RANK, self.results)


class TestServeStragglerWorld(_ServeWorldMixin, unittest.TestCase):
    """Rank 2 sleeps past the whole sync budget: its peers' collective
    genuinely hangs, and the survivors' return IS the watchdog firing at
    ``timeout_s`` — through the serve front end."""

    ACTION = "delay"

    def test_straggler_also_degraded_and_survived(self):
        res = self.results[FAULT_RANK]
        self.assertEqual(res["alice_syncB"], res["alice_local_post"])
        self.assertGreaterEqual(res["syncB_elapsed_s"], STRAGGLE_S - 0.5)

    def test_survivors_waited_out_the_full_deadline(self):
        for r in self._survivors():
            if r == FAULT_RANK:
                continue
            elapsed = self.results[r]["syncB_elapsed_s"]
            self.assertGreaterEqual(elapsed, TIMEOUT_S - 0.5)


if __name__ == "__main__":
    unittest.main()

"""Per-tenant ``approx`` knob (ROADMAP 4(c) / ISSUE 14 satellite): one
attach-time switch opting a tenant's curve/cache metrics into
bounded-memory sketch state, threaded identically through
``daemon.attach()``, the wire attach header, and ``EvalClient.attach()``;
unsupported specs reject with the structured
``AdmissionError(reason="bad_metrics")`` on every path."""

import unittest

import numpy as np

from torcheval_tpu.metrics import (
    BinaryAUROC,
    HitRate,
    MulticlassAccuracy,
    Quantile,
)
from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer
from torcheval_tpu.serve.errors import AdmissionError

RNG = np.random.default_rng(9)
N = 4096
SCORES = RNG.random(N).astype(np.float32)
TARGETS = (RNG.random(N) < 0.4).astype(np.float32)


def _oracle(approx):
    m = BinaryAUROC(approx=approx)
    m.update(SCORES, TARGETS)
    return float(m.compute())


class TestDaemonApproxKnob(unittest.TestCase):
    def test_attach_approx_matches_constructor_approx(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("t", {"auroc": BinaryAUROC()}, approx=4096)
            member = h._tenant.collection.metrics["auroc"]
            self.assertTrue(member._sketch_enabled())
            h.submit(SCORES, TARGETS, block=True, timeout=120)
            got = float(np.asarray(h.compute(timeout=120)["auroc"]))
        self.assertEqual(got, _oracle(4096))

    def test_non_capable_members_pass_through_beside_capable(self):
        # mixed spec: the curve metric sketches, the counter metric (its
        # state is already bounded) passes through untouched
        with EvalDaemon() as daemon:
            h = daemon.attach(
                "t",
                {
                    "auroc": BinaryAUROC(),
                    "acc": MulticlassAccuracy(num_classes=2),
                },
                approx=True,
            )
            members = h._tenant.collection.metrics
            self.assertTrue(members["auroc"]._sketch_enabled())
            self.assertFalse(hasattr(members["acc"], "_sketch_enabled"))

    def test_value_cache_metric_switches(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("t", {"hr": HitRate(k=3)}, approx=True)
            self.assertTrue(
                h._tenant.collection.metrics["hr"]._sketch_enabled()
            )

    def test_always_approx_metric_satisfies_knob(self):
        with EvalDaemon() as daemon:
            daemon.attach("t", {"q": Quantile(0.5)}, approx=True)

    def test_no_capable_member_rejects_bad_metrics(self):
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach(
                    "t", {"acc": MulticlassAccuracy(num_classes=2)},
                    approx=True,
                )
            self.assertEqual(ctx.exception.reason, "bad_metrics")
            # the reject is structured load-shedding, not a crash: the
            # daemon keeps admitting
            daemon.attach("t2", {"acc": MulticlassAccuracy(num_classes=2)})

    def test_streamed_metric_rejects_bad_metrics(self):
        streamed = BinaryAUROC()
        streamed.update(SCORES, TARGETS)
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("t", {"auroc": streamed}, approx=True)
            self.assertEqual(ctx.exception.reason, "bad_metrics")

    def test_fully_compacted_metric_rejects_bad_metrics(self):
        # the sneaky already-streamed shape: a compacted curve metric has
        # EMPTY raw caches (inputs=[] / _cached_samples=0) with every
        # sample living in summary_* state — switching it would silently
        # drop real data, so it must reject like the raw-cache case
        compacted = BinaryAUROC(compaction_threshold=64)
        compacted.update(SCORES, TARGETS)
        compacted._compact()
        self.assertFalse(compacted.inputs)  # the scenario premise
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("t", {"auroc": compacted}, approx=True)
            self.assertEqual(ctx.exception.reason, "bad_metrics")

    def test_rejected_admission_leaves_members_unswitched(self):
        # validate-then-commit: one bad member must not leave the GOOD
        # member half-switched into a changed state schema
        good = BinaryAUROC()
        bad = BinaryAUROC()
        bad.update(SCORES, TARGETS)  # already streamed → cannot switch
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError):
                daemon.attach(
                    "t", {"good": good, "bad": bad}, approx=True
                )
        self.assertFalse(good._sketch_enabled())
        self.assertIn("summary_scores", good.state_names)
        # and the untouched metric still attaches/serves exactly
        good.update(SCORES, TARGETS)
        self.assertEqual(float(good.compute()), _oracle(None))

    def test_approx_false_is_a_no_op(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("t", {"auroc": BinaryAUROC()}, approx=False)
            self.assertFalse(
                h._tenant.collection.metrics["auroc"]._sketch_enabled()
            )


class TestWireApproxKnob(unittest.TestCase):
    def test_wire_attach_threads_approx_and_value_matches(self):
        with EvalDaemon() as daemon:
            server = EvalServer(daemon)
            client = EvalClient(server.endpoint, request_timeout_s=120.0)
            try:
                client.attach(
                    "w", {"auroc": ["BinaryAUROC", {}]}, approx=4096
                )
                client.submit("w", SCORES, TARGETS)
                got = float(np.asarray(client.compute("w")["auroc"]))
                self.assertEqual(got, _oracle(4096))
            finally:
                client.close()
                server.close()

    def test_wire_reject_decodes_as_structured_admission_error(self):
        with EvalDaemon() as daemon:
            server = EvalServer(daemon)
            client = EvalClient(server.endpoint, request_timeout_s=120.0)
            try:
                with self.assertRaises(AdmissionError) as ctx:
                    client.attach(
                        "w",
                        {"acc": ["MulticlassAccuracy", {"num_classes": 2}]},
                        approx=True,
                    )
                self.assertEqual(ctx.exception.reason, "bad_metrics")
            finally:
                client.close()
                server.close()


if __name__ == "__main__":
    unittest.main()

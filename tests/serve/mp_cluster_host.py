"""One eval-service host process for the cluster host-kill drill
(ISSUE 10 acceptance).

Runs an ``EvalDaemon`` (evict_dir = the SHARED checkpoint root every host
in the drill mounts) behind an ``EvalServer`` bound to port 0, publishes
the OS-assigned port atomically (``<tag>.port.tmp`` -> ``<tag>.port``),
then parks. Chaos is armed per-host through the environment the launcher
sets (``host_kill`` / ``ack_drop`` / ``host_partition`` fire inside the
server's request dispatch); a killed host leaves nothing behind — its
tenants' only survivors are the shared-root checkpoints and the router's
client-side replay buffers, which is the point of the drill.

Run:  python mp_cluster_host.py <outdir> <tag> <ckpt_root>
"""

import os
import sys
import time


def main() -> None:
    outdir, tag, ckpt_root = sys.argv[1], sys.argv[2], sys.argv[3]
    import jax

    jax.config.update("jax_platforms", "cpu")

    from torcheval_tpu import obs
    from torcheval_tpu.serve import EvalDaemon, EvalServer

    obs.enable()
    daemon = EvalDaemon(evict_dir=ckpt_root).start()
    server = EvalServer(daemon)  # port 0: OS-assigned, CI-lane safe

    os.makedirs(outdir, exist_ok=True)
    port_path = os.path.join(outdir, f"{tag}.port")
    tmp = port_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(server.address[1]))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, port_path)  # readers never see a partial port

    # park until the launcher terminates us (or chaos kills us first);
    # the stop file is the graceful path so CI teardown is deterministic
    stop_path = os.path.join(outdir, f"{tag}.stop")
    while not os.path.exists(stop_path):
        time.sleep(0.05)
    server.close()
    daemon.stop()
    os._exit(0)


if __name__ == "__main__":
    main()

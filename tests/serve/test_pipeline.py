"""Deferred-ack submit pipelining (ISSUE 18): negotiation, exactly-once
edges, and the chaos ack actions.

Contracts pinned here:

* **Negotiation degrades, never breaks** — the attach-time ``pipeline``
  ask rides the PR 12 codec-handshake exchange: a server that never
  grants (``pipeline_depth=0``, the old-peer model) leaves the client
  lock-step with no protocol error; a granted-then-rejected
  ``pipeline_open`` flips the client's remembered ``unsupported`` flag,
  and ``WireError("protocol")`` NEVER surfaces to the producer.
* **Exactly-once under overlap** — out-of-order acks fold through the
  same ``acked_seq`` max/prune arithmetic the lock-step path uses; a
  full replay buffer triggers the server-side ``flush`` valve
  mid-pipeline; a migration exports the deep un-acked in-flight tail
  and the adopting host replays it without duplicates; the daemon's
  gapless admission refuses a seq past a still-unadmitted hole so the
  dedup watermark can never ratchet over a shed batch.
* **Chaos ack actions** — ``ack_delay`` / ``ack_reorder`` fire at the
  server's deferred-ack writer (the exact surface a slow or reordered
  ack presents) and the stream stays bit-identical with zero duplicate
  application.

All sockets bind port 0 (OS-assigned).
"""

import os
import threading
import time
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.resilience import chaos
from torcheval_tpu.serve import (
    BackpressureError,
    EvalClient,
    EvalDaemon,
    EvalServer,
    WireError,
    metric_spec,
)
from torcheval_tpu.serve.client import _ClientTenant, _PipelinedChannel

NUM_CLASSES = 5
SPEC = {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)}


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(n_batches):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for i in range(n_batches):
        m.update(*_batch(seed=i))
    return float(np.asarray(m.compute()))


class _PairMixin:
    def _pair(self, *, server_kw=None, daemon_kw=None, **client_kw):
        daemon = EvalDaemon(**(daemon_kw or {})).start()
        server = EvalServer(daemon, **(server_kw or {}))
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        # local_transport=False: these tests pin the CHANNEL (the
        # in-process local fast path would bypass it by design)
        client_kw.setdefault("local_transport", False)
        client = EvalClient(server.endpoint, **client_kw)
        self.addCleanup(client.close)
        return daemon, server, client


class TestNegotiation(_PairMixin, unittest.TestCase):
    def test_grant_is_min_of_ask_and_server_cap(self):
        _, _, client = self._pair(
            server_kw={"pipeline_depth": 4}, pipeline_depth=8
        )
        client.attach("t", SPEC)
        self.assertEqual(client._pipeline_granted, 4)
        self.assertTrue(client.submit("t", *_batch()))
        ch = client._channel
        self.assertIsNotNone(ch)
        self.assertEqual(ch.depth, 4)

    def test_never_granting_server_degrades_to_lock_step(self):
        # the old-peer model: a server that does not speak pipelining
        # ignores the attach ask; the wire silently stays lock-step and
        # per-batch applied verdicts keep their request-response meaning
        _, _, client = self._pair(
            server_kw={"pipeline_depth": 0}, pipeline_depth=8
        )
        client.attach("t", SPEC)
        self.assertEqual(client._pipeline_granted, 0)
        for i in range(3):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        self.assertIsNone(client._channel)
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(3))

    def test_pipeline_open_protocol_reject_never_surfaces(self):
        # a peer that granted at attach but rejects the channel open
        # (rolled back mid-deploy): the client remembers `unsupported`,
        # runs lock-step, and WireError("protocol") never reaches the
        # producer
        _, _, client = self._pair(
            server_kw={"pipeline_depth": 0}, pipeline_depth=8
        )
        client.attach("t", SPEC)
        client._pipeline_granted = 8  # simulate the stale grant
        for i in range(3):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        self.assertTrue(client._pipeline_unsupported)
        self.assertIsNone(client._channel)
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(3))

    def test_depth_knob_validated(self):
        for bad in (0, -1, 1.5, "4"):
            with self.assertRaises(ValueError):
                EvalClient("127.0.0.1:1", pipeline_depth=bad)
        # server-side knob: negative rejected, 0 = never grant
        daemon = EvalDaemon().start()
        self.addCleanup(daemon.stop)
        with self.assertRaises(ValueError):
            EvalServer(daemon, pipeline_depth=-1)


class TestPipelinedExactlyOnce(_PairMixin, unittest.TestCase):
    def test_stream_matches_oracle_with_deferred_acks(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        _, server, client = self._pair(pipeline_depth=8)
        client.attach("t", SPEC)
        n = 20
        for i in range(n):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(n))
        health = client.health()["tenants"]["t"]
        self.assertEqual(health["processed"], n)
        self.assertEqual(health["dupes"], 0)
        snap = obs.snapshot()
        self.assertGreaterEqual(
            snap["counters"].get("serve.wire.acks_deferred", 0), n
        )
        self.assertTrue(
            any(
                k.startswith("serve.client.inflight{")
                for k in snap["histograms"]
            ),
            sorted(snap["histograms"]),
        )

    def test_out_of_order_acks_fold_through_the_watermark(self):
        # acks are matched by (tenant, seqs) echo, not arrival order: a
        # shuffled batch of ok acks must fold to the max durable
        # watermark and prune exactly the covered prefix
        state = _ClientTenant(0)
        for seq in range(1, 8):
            state.replay.append((seq, ("b%d" % seq,)))
        acks = [
            {"ok": True, "acked_seq": 5},
            {"ok": True, "acked_seq": 2},
            {"ok": True, "acked_seq": 7},
            {"ok": True, "acked_seq": 3},
        ]
        _PipelinedChannel._fold_acks(state, acks, dirty=False)
        self.assertEqual(state.durable_seq, 7)
        self.assertEqual(list(state.replay), [])
        self.assertFalse(state.needs_resend)
        # an error ack anywhere in the pile flags the resend catch-up;
        # a dirty (channel-death) fold does the same
        state2 = _ClientTenant(0)
        state2.replay.append((1, ("b1",)))
        _PipelinedChannel._fold_acks(
            state2,
            [{"ok": False, "error": {"reason": "queue_full"}}],
            dirty=False,
        )
        self.assertTrue(state2.needs_resend)
        state3 = _ClientTenant(0)
        _PipelinedChannel._fold_acks(state3, [], dirty=True)
        self.assertTrue(state3.needs_resend)

    def test_full_replay_buffer_flushes_mid_pipeline(self):
        import tempfile

        root = tempfile.mkdtemp(prefix="tpu_pipeline_flush_")
        _, _, client = self._pair(
            daemon_kw={"evict_dir": root},
            pipeline_depth=4,
            replay_capacity=4,
        )
        client.attach("t", SPEC)
        n = 12
        for i in range(n):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        state = client._tenant_state("t")
        # the valve fired: the durable watermark moved off zero (flush
        # published checkpoints) and the buffer never exceeded capacity
        self.assertGreater(state.durable_seq, 0)
        self.assertLessEqual(len(state.replay), 4)
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(n))
        self.assertEqual(client.health()["tenants"]["t"]["dupes"], 0)

    def test_migration_replays_deep_unacked_tail(self):
        # nothing was flushed, so every streamed batch is un-durable:
        # the export carries the WHOLE pipelined tail and the adopting
        # host replays it in order, exactly once
        _, _, client_a = self._pair(pipeline_depth=8)
        client_a.attach("t", SPEC)
        n = 10
        for i in range(n):
            self.assertTrue(client_a.submit("t", *_batch(seed=i)))
        exported = client_a.export_tenant("t")
        self.assertEqual(exported["durable_seq"], 0)
        self.assertEqual(len(exported["replay"]), n)
        _, _, client_b = self._pair(pipeline_depth=8)
        attach_b = client_b.attach("t", SPEC)
        replayed = client_b.adopt_tenant(
            "t", exported, restored_seq=attach_b["last_seq"]
        )
        self.assertEqual(replayed, n)
        got = float(np.asarray(client_b.compute("t")["acc"]))
        self.assertEqual(got, _oracle(n))
        self.assertEqual(client_b.health()["tenants"]["t"]["dupes"], 0)

    def test_gapless_admission_refuses_seq_past_a_hole(self):
        # the ONE new invariant pipelining rides on: a pipelined frame
        # sequenced past a still-unadmitted hole (an earlier shed) must
        # reject retryably instead of ratcheting the dedup watermark
        # over the lost batch
        daemon = EvalDaemon().start()
        self.addCleanup(daemon.stop)
        handle = daemon.attach(
            "t", MulticlassAccuracy(num_classes=NUM_CLASSES)
        )
        scores, labels = _batch()
        self.assertTrue(handle.submit(scores, labels, seq=1, gapless=True))
        with self.assertRaises(BackpressureError) as ctx:
            handle.submit(scores, labels, seq=3, gapless=True)
        self.assertEqual(ctx.exception.reason, "seq_gap")
        self.assertTrue(ctx.exception.retryable)
        # in-order redelivery heals the hole
        self.assertTrue(handle.submit(scores, labels, seq=2, gapless=True))
        self.assertTrue(handle.submit(scores, labels, seq=3, gapless=True))
        # the non-gapless path keeps its lenient contract (migration
        # replays against a fresh daemon start above last_seq+1)
        self.assertTrue(handle.submit(scores, labels, seq=9))

    def test_channel_death_falls_back_and_resends(self):
        # sever the channel socket mid-stream: the next submit folds the
        # dirty flag into needs_resend, replays lock-step, and the
        # stream stays exactly-once
        _, _, client = self._pair(pipeline_depth=8)
        client.attach("t", SPEC)
        n_before = 5
        for i in range(n_before):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        ch = client._channel
        self.assertIsNotNone(ch)
        ch._fail(WireError("transport", "test-severed"))
        for i in range(n_before, n_before + 3):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(n_before + 3))
        health = client.health()["tenants"]["t"]
        # every batch applied exactly once; the dedup counter absorbing
        # the lock-step resend of already-landed frames IS the recovery
        # working (dupes counts deduped resends, not double application)
        self.assertEqual(health["processed"], n_before + 3)


class _AckChaosMixin(_PairMixin):
    ACTION = "ack_delay"
    EXTRA_ENV = {}

    def setUp(self):
        chaos.reset_for_tests()
        self._saved = {
            k: os.environ.get(k)
            for k in (
                "TORCHEVAL_TPU_CHAOS",
                "TORCHEVAL_TPU_CHAOS_ACTION",
                "TORCHEVAL_TPU_CHAOS_TENANT",
                "TORCHEVAL_TPU_CHAOS_STEP",
                "TORCHEVAL_TPU_CHAOS_DELAY_S",
            )
        }
        os.environ.update(
            {
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": self.ACTION,
                "TORCHEVAL_TPU_CHAOS_TENANT": "*",
                "TORCHEVAL_TPU_CHAOS_STEP": "2",
                **self.EXTRA_ENV,
            }
        )

    def tearDown(self):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        chaos.reset_for_tests()

    def test_stream_survives_the_ack_fault_bit_identically(self):
        _, _, client = self._pair(pipeline_depth=4)
        client.attach("t", SPEC)
        n = 8
        for i in range(n):
            self.assertTrue(client.submit("t", *_batch(seed=i)))
        got = float(np.asarray(client.compute("t")["acc"]))
        self.assertEqual(got, _oracle(n))
        health = client.health()["tenants"]["t"]
        self.assertEqual(health["processed"], n)
        self.assertEqual(health["dupes"], 0)
        self.assertTrue(chaos._ack_fired, "chaos ack action never fired")


class TestAckDelayChaos(_AckChaosMixin, unittest.TestCase):
    """One ack stalls for the delay while LATER frames keep streaming —
    the client's window, not the ack latency, paces the producer."""

    ACTION = "ack_delay"
    EXTRA_ENV = {"TORCHEVAL_TPU_CHAOS_DELAY_S": "0.3"}


class TestAckReorderChaos(_AckChaosMixin, unittest.TestCase):
    """Two consecutive acks swap on the wire: folding is keyed by the
    seq echo and a max over ``acked_seq``, so order cannot matter."""

    ACTION = "ack_reorder"


class TestPipelinedConcurrency(_PairMixin, unittest.TestCase):
    def test_many_producers_one_channel(self):
        # the Podracer shape: several producer threads, disjoint
        # tenants, ONE shared channel window — per-tenant ack folding
        # under each tenant's own lock must not cross wires
        _, _, client = self._pair(pipeline_depth=8)
        tenants = [f"t{i}" for i in range(3)]
        for t in tenants:
            client.attach(t, SPEC)
        n = 10
        errors = []

        def producer(t):
            try:
                for i in range(n):
                    client.submit(t, *_batch(seed=i))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors.append(e)

        threads = [
            threading.Thread(target=producer, args=(t,)) for t in tenants
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        self.assertEqual(errors, [])
        for t in tenants:
            got = float(np.asarray(client.compute(t)["acc"]))
            self.assertEqual(got, _oracle(n), t)
            self.assertEqual(client.health()["tenants"][t]["dupes"], 0, t)


if __name__ == "__main__":
    unittest.main()

"""The disposable router process for the router-restart drill
(ISSUE 20 acceptance).

Runs a JOURNALED ``EvalRouter`` over the drill's real host processes:
attaches a plain tenant and a split-by-2 tenant, streams phase-1 batches
through both, flushes (so every pre-kill update is durable), publishes
its pre-kill view atomically (``driver.state.json.tmp`` ->
``driver.state.json``), then drains the plain tenant's host. The
environment arms ``router_kill`` at ``migrate_exported`` — this process
dies by ``os._exit`` inside the drain's first live migration, in the
nastiest window: the tenant's wire state is exported and adopted
nowhere. The test process then restarts the router from the journal and
finishes both streams; bit-identity against the fault-free oracle is
the drill's verdict on the recovery.

Run:  python mp_router_driver.py <outdir> <journal_dir> <ep1,ep2,...>
"""

import json
import os
import sys
import zlib

PHASE1 = 6
NUM_CLASSES = 5
BATCH = 32
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}


def make_batch(tenant: str, idx: int):
    # crc32, not hash(): the seed must match across driver/test processes
    import numpy as np

    seed = 1000 * (zlib.crc32(tenant.encode()) % 97) + idx
    rng = np.random.default_rng(seed)
    return (
        rng.random((BATCH, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, BATCH),
    )


def main() -> None:
    outdir, journal_dir, eps = sys.argv[1], sys.argv[2], sys.argv[3]
    import jax

    jax.config.update("jax_platforms", "cpu")

    from torcheval_tpu import obs
    from torcheval_tpu.serve import EvalRouter

    obs.enable()
    router = EvalRouter(
        eps.split(","),
        journal_dir=journal_dir,
        request_timeout_s=10.0,
        connect_timeout_s=5.0,
        max_attempts=2,
        backoff_base_s=0.05,
    )
    router.attach("solo", SPEC)
    router.attach("fan", SPEC)
    router.split_tenant("fan", replicas=2)
    for i in range(PHASE1):
        router.submit("solo", *make_batch("solo", i))
        router.submit("fan", *make_batch("fan", i))
    router.flush("solo")
    router.flush("fan")

    state = {
        "placement": router.placement(),
        "submitted": PHASE1,
        "victim": router.placement()["solo"],
    }
    path = os.path.join(outdir, "driver.state.json")
    with open(path + ".tmp", "w") as f:
        json.dump(state, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(path + ".tmp", path)

    # chaos (router_kill @ migrate_exported) fires inside this call
    router.drain(state["victim"])
    os._exit(99)  # unreachable when the drill is armed correctly


if __name__ == "__main__":
    main()

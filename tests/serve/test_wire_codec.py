"""Negotiated cluster-wire codecs (ISSUE 12).

Three layers:

* the codec primitives (``utils/quant.py``): delta/narrow integer
  round-trips are LOSSLESS across dtypes and value shapes; q8 block
  quantization honors its documented error bound
  (``|x - dec(x)| <= max|block| / 254`` per element) and refuses
  non-finite / too-small inputs;
* ``pack_tree``/``pack_tree_parts``/``unpack_tree`` with a codec: specs
  stay self-describing (decode needs no codec argument), integer leaves
  survive bit-identically, f32 leaves within the bound, payloads shrink;
* the capability exchange: a codec-capable client against a raw-only
  server (and the reverse) negotiates down to raw with NO protocol error
  and bit-identical results — the mixed-version interop contract — while
  two codec-capable peers compress and still match the local oracle
  bit-for-bit on the lossless codec.
"""

import unittest

import numpy as np

from torcheval_tpu.utils import quant


def _assemble(parts):
    return b"".join(bytes(memoryview(p).cast("B")) for p in parts)


class TestQuantPrimitives(unittest.TestCase):
    def test_delta_int_lossless_across_dtypes_and_shapes(self):
        rng = np.random.default_rng(0)
        cases = [
            rng.integers(0, 5, 257).astype(np.int64),
            rng.integers(-3, 3, (16, 33)).astype(np.int32),
            np.cumsum(rng.integers(0, 9, 1000)).astype(np.int64),  # sorted
            np.arange(100, dtype=np.uint32) * 7 + 3,
            rng.integers(0, 100, 64).astype(np.int16),
        ]
        for arr in cases:
            parts = quant.delta_int_parts(arr)
            self.assertIsNotNone(parts, arr.dtype)
            offset, data = parts
            out = quant.delta_int_from_parts(
                data, offset, arr.dtype, arr.shape
            )
            np.testing.assert_array_equal(out, arr)
            self.assertEqual(out.dtype, arr.dtype)
            self.assertLess(data.nbytes, arr.nbytes)
            # the bytes-level wrapper round-trips identically
            enc = quant.delta_int_encode(arr)
            np.testing.assert_array_equal(
                quant.delta_int_decode(enc, arr.dtype, arr.shape), arr
            )

    def test_narrow_int_lossless_and_fold_exact(self):
        rng = np.random.default_rng(1)
        arr = rng.integers(10_000, 10_900, 4096).astype(np.int64)
        enc = quant.narrow_int_encode(arr)
        self.assertIsNotNone(enc)
        # span < 2^16 -> u16 data (+ the fixed 9-byte header)
        self.assertLess(len(enc), arr.nbytes // 4 + 16)
        out = quant.narrow_int_decode(enc, arr.dtype, arr.shape)
        np.testing.assert_array_equal(out, arr)
        # widened accumulation: summing decoded wide values across 8
        # simulated ranks is bit-exact vs summing the originals
        self.assertEqual(int(out.sum() * 8), int(arr.sum() * 8))

    def test_int_encoders_refuse_no_win(self):
        # already-narrow dtype: nothing to gain
        self.assertIsNone(
            quant.narrow_int_encode(np.arange(100, dtype=np.uint8))
        )
        # span too wide for a narrower width
        wide = np.asarray([0, 2**40], dtype=np.int64)
        self.assertIsNone(quant.narrow_int_encode(wide))
        self.assertIsNone(quant.delta_int_encode(np.zeros(0, np.int64)))
        # floats never take the integer codecs
        self.assertIsNone(quant.delta_int_parts(np.zeros(10, np.float32)))

    def test_q8_error_bound_and_exact_zero_blocks(self):
        rng = np.random.default_rng(2)
        arr = (rng.standard_normal(10_000) * 50).astype(np.float32)
        arr[:256] = 0.0  # a zero block must decode exactly
        scales, q = quant.q8_parts(arr)
        out = quant.q8_from_parts(scales, q, arr.shape)
        np.testing.assert_array_equal(out[:256], 0.0)
        nblocks = -(-arr.size // quant.Q8_BLOCK)
        pad = np.zeros(nblocks * quant.Q8_BLOCK - arr.size, np.float32)
        blocks = np.concatenate([arr, pad]).reshape(nblocks, quant.Q8_BLOCK)
        bound = np.abs(blocks).max(axis=1, keepdims=True) / 254.0
        err = np.abs(
            np.concatenate([out, pad]).reshape(nblocks, -1) - blocks
        )
        self.assertTrue((err <= bound * (1 + 1e-6)).all())

    def test_q8_refuses_small_and_nonfinite(self):
        self.assertIsNone(quant.q8_parts(np.ones(8, np.float32)))
        bad = np.ones(1024, np.float32)
        bad[7] = np.inf
        self.assertIsNone(quant.q8_parts(bad))
        bad[7] = np.nan
        self.assertIsNone(quant.q8_parts(bad))
        self.assertIsNone(quant.q8_parts(np.ones(1024, np.float64)))

    def test_q8_bytes_roundtrip_and_ratio(self):
        arr = np.linspace(-9.0, 9.0, 4096).astype(np.float32)
        enc = quant.q8_encode(arr)
        self.assertLessEqual(len(enc), arr.nbytes // 3)  # ~3.94x
        out = quant.q8_decode(enc, arr.shape)
        self.assertLessEqual(np.abs(out - arr).max(), 9.0 / 254 * 1.000001)


class TestPackTreeCodecs(unittest.TestCase):
    def _roundtrip(self, obj, codec):
        from torcheval_tpu.serve.wire import pack_tree, unpack_tree

        spec, blob = pack_tree(obj, codec=codec)
        return unpack_tree(spec, blob), len(blob)

    def test_delta_tree_bit_identical_and_smaller(self):
        from torcheval_tpu.serve.wire import pack_tree

        rng = np.random.default_rng(3)
        labels = rng.integers(0, 5, 4096).astype(np.int64)
        scores = rng.random((4096, 5)).astype(np.float32)
        obj = {"batch": [scores, labels], "meta": (1, "x", None)}
        out, enc_len = self._roundtrip(obj, "delta")
        np.testing.assert_array_equal(out["batch"][0], scores)  # floats raw
        np.testing.assert_array_equal(out["batch"][1], labels)
        self.assertEqual(out["batch"][1].dtype, labels.dtype)
        self.assertEqual(out["meta"], (1, "x", None))
        _, raw_blob = pack_tree(obj)
        self.assertLess(enc_len, len(raw_blob))

    def test_qblk_tree_bounded_floats_exact_ints(self):
        rng = np.random.default_rng(4)
        scores = (rng.random((512, 8)) * 3).astype(np.float32)
        labels = rng.integers(0, 8, 512)
        out, _ = self._roundtrip([scores, labels], "qblk")
        np.testing.assert_array_equal(out[1], labels)
        self.assertLessEqual(
            np.abs(out[0] - scores).max(),
            np.abs(scores).max() / 254 * 1.000001,
        )
        self.assertEqual(out[0].dtype, scores.dtype)
        self.assertEqual(out[0].shape, scores.shape)

    def test_small_and_nonfinite_leaves_stay_raw_and_exact(self):
        # scalars, tiny arrays and NaN-bearing floats must survive a
        # qblk-coded tree bit-identically (per-leaf raw fallback)
        tiny = np.asarray([1.25, -2.5], dtype=np.float32)
        nan = np.full(1024, np.nan, dtype=np.float32)
        out, _ = self._roundtrip([tiny, nan], "qblk")
        np.testing.assert_array_equal(out[0], tiny)
        np.testing.assert_array_equal(out[1], nan)

    def test_malformed_codec_nodes_classify_as_protocol_error(self):
        # a codec node whose decode recipe disagrees with the member's
        # element count must raise the structured WireError("protocol")
        # every other malformed-node path raises — never a bare
        # ValueError that loses the retryability classification
        from torcheval_tpu.serve.wire import WireError, pack_tree, unpack_tree

        spec, blob = pack_tree(
            [np.arange(100, dtype=np.int64)], codec="delta"
        )
        spec["v"][0]["sh"] = [999_999]  # shape vs member size mismatch
        with self.assertRaises(WireError) as ctx:
            unpack_tree(spec, blob)
        self.assertEqual(ctx.exception.reason, "protocol")

    def test_pack_tree_parts_matches_pack_tree(self):
        from torcheval_tpu.serve.wire import (
            pack_tree,
            pack_tree_parts,
            unpack_tree,
        )

        rng = np.random.default_rng(5)
        obj = [
            (rng.random((128, 5)).astype(np.float32),
             rng.integers(0, 5, 128)),
            (rng.random((128, 5)).astype(np.float32),
             rng.integers(0, 5, 128)),
        ]
        for codec in ("delta", "qblk"):
            spec_p, parts, total = pack_tree_parts(obj, codec=codec)
            blob = _assemble(parts)
            self.assertEqual(len(blob), total)
            via_parts = unpack_tree(spec_p, blob)
            spec_b, blob_b = pack_tree(obj, codec=codec)
            via_bytes = unpack_tree(spec_b, blob_b)
            for (ap, bp) in zip(via_parts, via_bytes):
                np.testing.assert_array_equal(ap[0], bp[0])
                np.testing.assert_array_equal(ap[1], bp[1])


class TestWireCodecNegotiation(unittest.TestCase):
    """Live server/client worlds: negotiation, interop, bit-identity."""

    NUM_CLASSES = 5
    SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": 5}]}

    @classmethod
    def setUpClass(cls):
        rng = np.random.default_rng(6)
        cls.batches = [
            (
                rng.random((64, cls.NUM_CLASSES)).astype(np.float32),
                rng.integers(0, cls.NUM_CLASSES, 64),
            )
            for _ in range(6)
        ]

    def _oracle(self):
        from torcheval_tpu.metrics import MulticlassAccuracy

        m = MulticlassAccuracy(num_classes=self.NUM_CLASSES)
        for s, l in self.batches:
            m.update(s, l)
        return float(np.asarray(m.compute()))

    def _run(self, server_codecs, client_codec, submit_buffer=1):
        from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer

        with EvalDaemon() as daemon:
            server = EvalServer(daemon, codecs=server_codecs)
            client = EvalClient(
                server.endpoint,
                codec=client_codec,
                submit_buffer=submit_buffer,
            )
            try:
                ack = client.attach("t", self.SPEC)
                for s, l in self.batches:
                    client.submit("t", s, l)
                result = client.compute("t")["acc"]
            finally:
                client.close()
                server.close()
        return ack, float(np.asarray(result))

    def test_codec_client_vs_raw_only_server_negotiates_down(self):
        # the mixed-version hard case: a new client offering codecs to a
        # server that knows none — raw wire, zero protocol errors,
        # bit-identical results
        ack, value = self._run((), "qblk")
        self.assertEqual(ack["codec"], "raw")
        self.assertEqual(value, self._oracle())

    def test_raw_client_vs_codec_server_stays_raw(self):
        from torcheval_tpu.serve.wire import WIRE_CODECS

        ack, value = self._run(WIRE_CODECS, "raw")
        self.assertEqual(ack["codec"], "raw")
        self.assertEqual(value, self._oracle())

    def test_delta_negotiated_and_bit_identical(self):
        from torcheval_tpu.serve.wire import WIRE_CODECS

        ack, value = self._run(WIRE_CODECS, "delta")
        self.assertEqual(ack["codec"], "delta")
        self.assertEqual(value, self._oracle())

    def test_qblk_on_delta_only_server_takes_second_choice(self):
        # a qblk client implies delta as second offer, so a delta-only
        # server still gets the lossless compressed wire
        ack, value = self._run(("delta",), "qblk")
        self.assertEqual(ack["codec"], "delta")
        self.assertEqual(value, self._oracle())

    def test_qblk_submit_many_within_documented_drift(self):
        from torcheval_tpu.serve.wire import WIRE_CODECS

        ack, value = self._run(WIRE_CODECS, "qblk", submit_buffer=3)
        self.assertEqual(ack["codec"], "qblk")
        # oracle on DEQUANTIZED batches: the wire's only effect is the
        # documented per-leaf quantization, nothing else
        from torcheval_tpu.metrics import MulticlassAccuracy

        m = MulticlassAccuracy(num_classes=self.NUM_CLASSES)
        for s, l in self.batches:
            scales, q = quant.q8_parts(s)
            m.update(quant.q8_from_parts(scales, q, s.shape), l)
        self.assertEqual(value, float(np.asarray(m.compute())))

    def test_codec_obs_counters(self):
        from torcheval_tpu import obs
        from torcheval_tpu.serve.wire import WIRE_CODECS

        obs.enable()
        try:
            obs.reset()
            self._run(WIRE_CODECS, "delta")
            counters = obs.snapshot()["counters"]
            self.assertGreaterEqual(
                counters.get("serve.wire.codec{codec=delta}", 0), 1
            )
            raw = counters["serve.client.payload_raw_bytes{codec=delta}"]
            enc = counters["serve.client.payload_bytes{codec=delta}"]
            self.assertGreater(raw, 0)
            self.assertLess(enc, raw + 4096)  # npz overhead bounded
            self.assertGreaterEqual(
                counters["serve.wire.rx_bytes{codec=delta}"], enc
            )
        finally:
            obs.disable()
            obs.reset()


if __name__ == "__main__":
    unittest.main()

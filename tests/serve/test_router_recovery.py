"""Router crash recovery from the control-plane journal (ISSUE 20
tentpole). In-process drills for every reconciliation outcome the
recovery pass can produce: adopt-in-place (including a split tenant whose
fan-out ordinal is re-derived from replica watermarks), re-place off a
dead host via checkpoint + resume, orphan adoption, stale double-attach
resolution, torn-split rollback, drain persistence — and the
corrupt-newest-checkpoint drill (``ckpt_corrupt`` chaos + lineage
fallback). Every streaming scenario ends with the recovered stream
bit-identical to a fault-free oracle with zero duplicate application.
The real-process variant (router killed with ``os._exit`` mid-migration)
lives in ``test_router_restart_mp.py``."""

import glob
import os
import tempfile
import unittest
from unittest import mock

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.resilience import chaos
from torcheval_tpu.serve import EvalDaemon, EvalRouter, EvalServer
from torcheval_tpu.serve.journal import RouterJournal

NUM_CLASSES = 5
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(batches):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for s, l in batches:
        m.update(s, l)
    return float(np.asarray(m.compute()))


class _RecoveryMixin:
    """Three-host fleet with a shared checkpoint root and a journal dir.
    Routers are managed per-test (the whole point is replacing one)."""

    N_HOSTS = 3

    def setUp(self):
        obs.reset()
        self.root = tempfile.mkdtemp(prefix="tpu_recovery_ckpt_")
        self.journal_dir = tempfile.mkdtemp(prefix="tpu_recovery_journal_")
        self.daemons, self.servers = [], []
        for _ in range(self.N_HOSTS):
            daemon = EvalDaemon(evict_dir=self.root).start()
            server = EvalServer(daemon)
            self.daemons.append(daemon)
            self.servers.append(server)
            self.addCleanup(daemon.stop)
            self.addCleanup(server.close)
        self.endpoints = [s.endpoint for s in self.servers]

    def _router(self, *, journal=True, endpoints=None):
        r = EvalRouter(
            endpoints or self.endpoints,
            journal_dir=self.journal_dir if journal else None,
            request_timeout_s=10.0,
            connect_timeout_s=1.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(r.close)
        return r

    def _kill_host(self, endpoint):
        idx = self.endpoints.index(endpoint)
        self.servers[idx].close()
        self.daemons[idx].stop()

    def _daemon_for(self, endpoint):
        return self.daemons[self.endpoints.index(endpoint)]

    def _total_dupes(self):
        total = 0
        for d in self.daemons:
            try:
                tenants = d.health()["tenants"]
            except RuntimeError:  # a host this test killed
                continue
            total += sum(t.get("dupes", 0) for t in tenants.values())
        return total


class TestAdoptRecovery(_RecoveryMixin, unittest.TestCase):
    def test_adoption_preserves_placement_and_bit_identity(self):
        # Smoke 1 as a regression test: crash with a plain tenant AND a
        # split-by-3 tenant mid-stream; the recovered router must route
        # both to completion bit-identically with zero re-application.
        obs.enable()
        self.addCleanup(obs.disable)
        batches = [_batch(i) for i in range(24)]
        r1 = self._router()
        r1.attach("solo", SPEC)
        r1.attach("fan", SPEC)
        r1.split_tenant("fan", replicas=3)
        for b in batches[:12]:
            r1.submit("solo", *b)
            r1.submit("fan", *b)
        r1.flush("solo")
        r1.flush("fan")
        placement_before = r1.placement()
        r1.close()  # the crash: routing table + client cursors gone

        r2 = self._router()
        self.assertEqual(r2.last_recovery["outcomes"], {"adopted": 4})
        self.assertEqual(r2.placement(), placement_before)
        for b in batches[12:]:
            r2.submit("solo", *b)
            r2.submit("fan", *b)
        oracle = _oracle(batches)
        self.assertEqual(float(np.asarray(r2.compute("solo")["acc"])), oracle)
        self.assertEqual(float(np.asarray(r2.compute("fan")["acc"])), oracle)
        self.assertEqual(self._total_dupes(), 0)
        counters = obs.snapshot()["counters"]
        self.assertEqual(
            counters.get("serve.router.recoveries{outcome=adopted}"), 4.0
        )
        # the recovery pass folds the reconciled table into a snapshot
        self.assertGreaterEqual(
            counters.get("serve.router.journal_compactions", 0), 1.0
        )

    def test_blackout_is_measured_and_bounded(self):
        r1 = self._router()
        r1.attach("ten", SPEC)
        r1.close()
        r2 = self._router()
        rec = r2.last_recovery
        self.assertGreater(rec["duration_s"], 0.0)
        self.assertLess(rec["duration_s"], 30.0)
        self.assertEqual(rec["tenants"], 1)
        self.assertEqual(sorted(rec["alive"]), sorted(self.endpoints))


class TestReplaceRecovery(_RecoveryMixin, unittest.TestCase):
    def test_dead_host_tenant_replaced_from_checkpoint(self):
        # Smoke 3: the tenant's host dies WHILE the router is down, so
        # failover can't see it — recovery must re-place from the shared
        # checkpoint root and resume at the durable watermark.
        obs.enable()
        self.addCleanup(obs.disable)
        batches = [_batch(i) for i in range(16)]
        r1 = self._router()
        victim_ep = r1.attach("vic", SPEC)
        for b in batches[:8]:
            r1.submit("vic", *b)
        r1.flush("vic")  # durable watermark: seq 8
        r1.close()
        self._kill_host(victim_ep)

        r2 = self._router()
        self.assertEqual(r2.last_recovery["outcomes"], {"replaced": 1})
        new_ep = r2.placement()["vic"]
        self.assertNotEqual(new_ep, victim_ep)
        # everything at or below the restored watermark is durable; the
        # producer resubmits the tail above it
        restored = r2._clients[new_ep]._tenants["vic"].durable_seq
        self.assertEqual(restored, 8)
        for b in batches[8:]:
            r2.submit("vic", *b)
        self.assertEqual(
            float(np.asarray(r2.compute("vic")["acc"])), _oracle(batches)
        )
        self.assertEqual(self._total_dupes(), 0)
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "serve.router.recoveries{outcome=replaced}"
            ),
            1.0,
        )

    def test_unplaceable_tenant_is_dropped_not_fatal(self):
        r1 = self._router()
        r1.attach("ten", SPEC)  # never flushed: no checkpoint anywhere
        victim_ep = r1.placement()["ten"]
        r1.close()
        self._kill_host(victim_ep)
        r2 = self._router()
        # resume="auto" on a fresh host admits with empty state — the
        # tenant is replaced, just without its pre-crash updates (they
        # were never durable). Either replaced or dropped is survivable;
        # the router itself must come up.
        self.assertIn(
            list(r2.last_recovery["outcomes"]), [["replaced"], ["dropped"]]
        )


class TestOrphanAndStale(_RecoveryMixin, unittest.TestCase):
    def test_live_unjournaled_tenant_is_adopted_with_its_spec(self):
        # A tenant attached before its journal record landed (the
        # attach/journal crash gap) is found live with the host-recorded
        # spec and adopted.
        obs.enable()
        self.addCleanup(obs.disable)
        batches = [_batch(i) for i in range(10)]
        r0 = self._router(journal=False)
        r0.attach("ghost", SPEC)
        for b in batches[:5]:
            r0.submit("ghost", *b)
        r0.flush("ghost")
        r0.close()

        r2 = self._router()  # journal is empty: "ghost" is an orphan
        self.assertEqual(
            r2.last_recovery["outcomes"], {"orphan_adopted": 1}
        )
        for b in batches[5:]:
            r2.submit("ghost", *b)
        self.assertEqual(
            float(np.asarray(r2.compute("ghost")["acc"])), _oracle(batches)
        )
        self.assertEqual(self._total_dupes(), 0)

    def test_double_attached_tenant_keeps_the_advanced_copy(self):
        # Mid-migration crash: the tenant exists on two hosts. Recovery
        # keeps the copy with the higher watermark and drops the stale
        # one WITHOUT a checkpoint.
        r1 = self._router()
        ep_new = r1.attach("twin", SPEC)
        for i in range(6):
            r1.submit("twin", *_batch(i))
        r1.flush("twin")
        # plant the stale copy on another host, behind by construction
        # (resume="never": it must NOT restore the advanced copy's
        # checkpoint from the shared root)
        ep_stale = next(e for e in self.endpoints if e != ep_new)
        stale_client = r1._clients[ep_stale]
        stale_client.attach("twin", SPEC, resume="never")
        stale_client.submit("twin", *_batch(0))
        stale_client.flush("twin")
        r1.close()

        r2 = self._router()
        outcomes = r2.last_recovery["outcomes"]
        self.assertEqual(outcomes.get("stale_dropped"), 1)
        self.assertEqual(outcomes.get("adopted"), 1)
        self.assertEqual(r2.placement()["twin"], ep_new)
        self.assertNotIn(
            "twin", self._daemon_for(ep_stale).health()["tenants"]
        )

    def test_torn_split_replica_rolled_back(self):
        # A replica journaled (place with parent=) whose parent never
        # committed the split record is mid-split debris: recovery
        # detaches it, matching split_tenant's crash-free rollback.
        r0 = self._router(journal=False)
        r0.attach("ten", SPEC)
        r0.attach("ten@r1", SPEC)
        ep_parent = r0.placement()["ten"]
        ep_replica = r0.placement()["ten@r1"]
        r0.close()
        j = RouterJournal(self.journal_dir)
        j.append(
            "place", tenant="ten", endpoint=ep_parent, spec=SPEC,
            knobs={}, parent=None,
        )
        j.append(
            "place", tenant="ten@r1", endpoint=ep_replica, spec=SPEC,
            knobs={}, parent="ten",
        )  # and no "split" record: the crash hit between the two
        j.close()

        r2 = self._router()
        outcomes = r2.last_recovery["outcomes"]
        self.assertEqual(outcomes.get("split_rolled_back"), 1)
        self.assertEqual(outcomes.get("adopted"), 1)
        self.assertEqual(list(r2.placement()), ["ten"])
        self.assertNotIn(
            "ten@r1", self._daemon_for(ep_replica).health()["tenants"]
        )


class TestDrainAndHosts(_RecoveryMixin, unittest.TestCase):
    def test_explicit_drain_survives_recovery(self):
        r1 = self._router()
        r1.attach("ten", SPEC)
        drained_ep = next(
            e for e in self.endpoints if e != r1.placement()["ten"]
        )
        r1.drain(drained_ep)
        r1.close()
        r2 = self._router()
        self.assertEqual(r2.last_recovery["drained"], [drained_ep])
        self.assertNotIn(drained_ep, r2.alive)
        # new placements must avoid the drained host
        for i in range(6):
            ep = r2.attach(f"t{i}", SPEC)
            self.assertNotEqual(ep, drained_ep)

    def test_runtime_added_host_is_reminted_at_recovery(self):
        extra_daemon = EvalDaemon(evict_dir=self.root).start()
        extra_server = EvalServer(extra_daemon)
        self.addCleanup(extra_daemon.stop)
        self.addCleanup(extra_server.close)
        r1 = self._router(endpoints=self.endpoints[:1])
        r1.add_host(extra_server.endpoint)
        r1.close()
        # the new router is constructed WITHOUT the runtime host; the
        # journal's host_add record restores it
        r2 = self._router(endpoints=self.endpoints[:1])
        self.assertIn(extra_server.endpoint, r2.endpoints)
        self.assertIn(extra_server.endpoint, r2.alive)


class TestCorruptCheckpointDrill(_RecoveryMixin, unittest.TestCase):
    def tearDown(self):
        chaos.reset_for_tests()

    def test_corrupt_newest_falls_back_and_replay_heals(self):
        # The acceptance drill: ckpt_corrupt flips a byte of the newest
        # generation; attach(resume="auto") during recovery quarantines
        # it (rename, never delete), restores the previous valid
        # generation, and producer resubmission heals to bit-identity.
        obs.enable()
        self.addCleanup(obs.disable)
        batches = [_batch(i) for i in range(16)]
        env = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_ACTION": "ckpt_corrupt",
            "TORCHEVAL_TPU_CHAOS_TENANT": "/vic/",
            "TORCHEVAL_TPU_CHAOS_STEP": "2",
        }
        with mock.patch.dict(os.environ, env):
            chaos.reset_for_tests()
            r1 = self._router()
            victim_ep = r1.attach("vic", SPEC)
            for b in batches[:8]:
                r1.submit("vic", *b)
            r1.flush("vic")  # generation 1: intact
            for b in batches[8:12]:
                r1.submit("vic", *b)
            r1.flush("vic")  # generation 2: chaos flips one payload byte
            r1.close()
            self._kill_host(victim_ep)

            r2 = self._router()
        self.assertEqual(r2.last_recovery["outcomes"], {"replaced": 1})
        new_ep = r2.placement()["vic"]
        # generation 2 held seqs 1..12 but is corrupt: the restored
        # watermark must be generation 1's
        restored = r2._clients[new_ep]._tenants["vic"].durable_seq
        self.assertEqual(restored, 8)
        for b in batches[8:]:
            r2.submit("vic", *b)
        self.assertEqual(
            float(np.asarray(r2.compute("vic")["acc"])), _oracle(batches)
        )
        self.assertEqual(self._total_dupes(), 0)
        # quarantined — renamed, not deleted — and counted
        tenant_dir = os.path.join(self.root, "vic")
        quarantined = glob.glob(os.path.join(tenant_dir, "corrupt-ckpt-*"))
        self.assertEqual(len(quarantined), 1)
        counters = obs.snapshot()["counters"]
        self.assertEqual(
            counters.get("resilience.checkpoint.corrupt_quarantined"), 1.0
        )
        self.assertGreaterEqual(
            counters.get("resilience.checkpoint.fallback_restores", 0), 1.0
        )


if __name__ == "__main__":
    unittest.main()

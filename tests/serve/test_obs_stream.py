"""Obs push channel over the serve wire (ISSUE 16 tentpole legs 2-3):
``subscribe_obs`` push delivery, degradation against old peers, final
flush on drain/stop, publisher retirement, and the router's fleet fold
— all over real loopback sockets (port 0, OS-assigned)."""

import tempfile
import threading
import time
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.serve import (
    EvalClient,
    EvalDaemon,
    EvalRouter,
    EvalServer,
    WireError,
    metric_spec,
)

NUM_CLASSES = 4


def _batch(n=8):
    return (
        np.zeros(n, np.int64),
        np.zeros(n, np.int64),
    )


def _wait(predicate, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _no_obs_threads():
    return not [
        t.name
        for t in threading.enumerate()
        if "torcheval-tpu-obs-" in t.name
    ]


class _OldServer(EvalServer):
    """A pre-ISSUE-16 peer: ``subscribe_obs`` is an unknown op, rejected
    structurally (the PR 12 negotiation discipline under test)."""

    def _handle(self, op, header, payload, stage_box=None):
        if op == "subscribe_obs":
            raise WireError("protocol", f"unknown wire op {op!r}.")
        return super()._handle(op, header, payload, stage_box)


class _StreamMixin:
    server_cls = EvalServer

    def setUp(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.reset)
        self.addCleanup(obs.disable)
        self.daemon = EvalDaemon().start()
        self.server = self.server_cls(self.daemon)
        self.client = EvalClient(
            self.server.endpoint,
            request_timeout_s=30.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.daemon.stop)
        self.addCleanup(self.server.close)
        self.addCleanup(self.client.close)

    def _attach(self, tenant="t1"):
        self.client.attach(
            tenant,
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )


class TestPushChannel(_StreamMixin, unittest.TestCase):
    def test_push_delivers_deltas_and_load_report(self):
        self._attach()
        pushes = []
        sub = self.client.subscribe_obs(0.1, on_push=pushes.append)
        self.addCleanup(sub.stop)
        self.assertEqual(sub.mode, "push")
        self.client.submit("t1", *_batch())
        self.assertTrue(_wait(lambda: sub.received >= 2))
        msg = sub.last
        self.assertEqual(msg["op"], "obs_push")
        self.assertEqual(msg["endpoint"], self.server.endpoint)
        self.assertEqual(msg["delta"]["v"], 1)
        self.assertEqual(msg["load_report"]["schema"], 1)
        # seqs on the channel are monotonic
        seqs = [p["push_seq"] for p in pushes]
        self.assertEqual(seqs, sorted(seqs))
        # the first push is a full baseline, later ones are diffs
        self.assertTrue(pushes[0]["delta"]["full"])

    def test_deltas_fold_to_the_host_registry(self):
        from torcheval_tpu.obs.stream import DeltaAccumulator

        self._attach()
        acc = DeltaAccumulator()
        sub = self.client.subscribe_obs(0.05, on_push=lambda m: acc.apply(m["delta"]))
        self.addCleanup(sub.stop)
        for _ in range(3):
            self.client.submit("t1", *_batch())
        self.assertTrue(
            _wait(
                lambda: acc.snapshot()["counters"].get(
                    "serve.ingest.batches{tenant=t1}"
                )
                == 3.0
            ),
            f"accumulated: {acc.snapshot()['counters']}",
        )

    def test_drain_final_flush_reaches_subscriber(self):
        self._attach()
        sub = self.client.subscribe_obs(30.0)  # no timer tick in this test
        self.addCleanup(sub.stop)
        self.client.submit("t1", *_batch())
        self.client.drain()
        # the daemon's flush hook pushed synchronously at drain: the
        # subscriber sees the final state without waiting 30s
        self.assertTrue(_wait(lambda: sub.received >= 1))
        counters = sub.last["delta"]["counters"]
        self.assertIn("serve.ingest.batches{tenant=t1}", counters)

    def test_stop_retires_publisher_and_reader_threads(self):
        sub = self.client.subscribe_obs(0.05)
        self.assertTrue(_wait(lambda: sub.received >= 1))
        sub.stop()
        self.assertFalse(sub.alive)
        self.assertTrue(_wait(_no_obs_threads), "obs threads leaked")

    def test_client_close_stops_subscriptions(self):
        sub = self.client.subscribe_obs(0.05)
        self.client.close()
        self.assertTrue(_wait(lambda: not sub.alive))
        self.assertTrue(_wait(_no_obs_threads), "obs threads leaked")

    def test_server_close_final_flushes_then_severs(self):
        sub = self.client.subscribe_obs(30.0)
        self.addCleanup(sub.stop)
        self.server.close()
        # close() flushes each publisher before severing: one last push
        self.assertTrue(_wait(lambda: sub.received >= 1))
        self.assertTrue(_wait(lambda: not sub.alive))

    def test_push_counters_recorded_on_host(self):
        sub = self.client.subscribe_obs(0.05)
        self.addCleanup(sub.stop)
        self.assertTrue(_wait(lambda: sub.received >= 2))
        counters = obs.snapshot()["counters"]
        self.assertGreaterEqual(counters.get("obs.stream.pushes", 0), 2)

    def test_bad_interval_rejected_at_the_boundary(self):
        for bad in (0.0, -1.0, float("nan"), float("inf")):
            with self.assertRaises(ValueError):
                self.client.subscribe_obs(bad)

    def test_pushes_add_zero_collective_rounds(self):
        self._attach()
        rounds_before = obs.snapshot()["counters"].get(
            "toolkit.sync.rounds", 0
        )
        sub = self.client.subscribe_obs(0.05)
        self.addCleanup(sub.stop)
        self.client.submit("t1", *_batch())
        self.assertTrue(_wait(lambda: sub.received >= 3))
        rounds_after = obs.snapshot()["counters"].get(
            "toolkit.sync.rounds", 0
        )
        self.assertEqual(rounds_before, rounds_after)


class TestOldPeerDegradation(_StreamMixin, unittest.TestCase):
    server_cls = _OldServer

    def test_old_server_degrades_to_polling(self):
        self._attach()
        polls = []
        sub = self.client.subscribe_obs(0.1, on_push=polls.append)
        self.addCleanup(sub.stop)
        self.assertEqual(sub.mode, "poll")
        self.assertTrue(_wait(lambda: sub.received >= 1))
        msg = sub.last
        self.assertEqual(msg["op"], "obs_poll")
        # the poll fallback still carries the structured load report
        self.assertEqual(msg["load_report"]["schema"], 1)
        self.assertIn("health", msg)

    def test_fallback_raise_surfaces_the_protocol_error(self):
        with self.assertRaises(WireError) as ctx:
            self.client.subscribe_obs(0.1, fallback="raise")
        self.assertEqual(ctx.exception.reason, "protocol")

    def test_bad_fallback_rejected(self):
        with self.assertRaises(ValueError):
            self.client.subscribe_obs(0.1, fallback="maybe")


class TestRouterFleet(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.reset)
        self.addCleanup(obs.disable)
        self.root = tempfile.mkdtemp(prefix="tpu_fleet_test_")
        self.d1 = EvalDaemon(evict_dir=self.root).start()
        self.d2 = EvalDaemon(evict_dir=self.root).start()
        self.s1 = EvalServer(self.d1)
        self.s2 = _OldServer(self.d2)
        self.router = EvalRouter(
            [self.s1.endpoint, self.s2.endpoint],
            request_timeout_s=30.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.d1.stop)
        self.addCleanup(self.d2.stop)
        self.addCleanup(self.s1.close)
        self.addCleanup(self.s2.close)
        self.addCleanup(self.router.close)

    def test_fleet_status_folds_mixed_version_hosts(self):
        modes = self.router.subscribe_obs(0.1)
        self.assertEqual(modes[self.s1.endpoint], "push")
        self.assertEqual(modes[self.s2.endpoint], "poll")
        self.assertTrue(
            _wait(
                lambda: all(
                    not h["stale"]
                    for h in self.router.fleet_status()["hosts"].values()
                )
            ),
            f"still stale: {self.router.fleet_status()['hosts']}",
        )
        fs = self.router.fleet_status()
        for ep in (self.s1.endpoint, self.s2.endpoint):
            host = fs["hosts"][ep]
            self.assertTrue(host["alive"])
            self.assertEqual(host["load_report"]["schema"], 1)
        self.assertEqual(fs["hosts"][self.s1.endpoint]["mode"], "push")
        self.assertEqual(fs["hosts"][self.s2.endpoint]["mode"], "poll")

    def test_fleet_status_reflects_ingest_within_one_interval(self):
        self.router.subscribe_obs(0.1)
        ep = self.router.attach(
            "t1",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )
        for _ in range(3):
            self.router.submit("t1", *_batch())

        def sees_ingest():
            host = self.router.fleet_status()["hosts"][ep]
            lr = host["load_report"]
            # the report reflects the traffic: the tenant's queue shows
            # up per-tenant and the submit-latency EWMA left zero
            return (
                lr is not None
                and "t1" in lr["queue"]["per_tenant"]
                and lr["latency"]["submit_ewma_s"] > 0.0
            )

        self.assertTrue(
            _wait(sees_ingest),
            f"fleet never saw the ingest: {self.router.fleet_status()}",
        )

    def test_killed_host_goes_stale_within_horizon(self):
        self.router.subscribe_obs(0.1, stale_after_s=0.5)
        self.assertTrue(
            _wait(
                lambda: not self.router.fleet_status()["hosts"][
                    self.s1.endpoint
                ]["stale"]
            )
        )
        # kill the push host without telling the router
        self.s1.close()
        self.d1.stop()
        self.assertTrue(
            _wait(
                lambda: self.router.fleet_status()["hosts"][
                    self.s1.endpoint
                ]["stale"],
                timeout_s=5.0,
            ),
            "killed host never went stale",
        )
        # the stream going stale did NOT evict the host: the failure
        # detector (health probe / tenant op) stays authoritative
        self.assertIn(self.s1.endpoint, self.router.alive)

    def test_unsubscribe_stops_all_stream_threads(self):
        self.router.subscribe_obs(0.05)
        self.assertTrue(
            _wait(
                lambda: any(
                    h["pushes"] > 0
                    for h in self.router.fleet_status()["hosts"].values()
                )
            )
        )
        self.router.unsubscribe_obs()
        self.assertTrue(_wait(_no_obs_threads), "obs threads leaked")

    def test_fleet_chrome_trace_tags_events_per_host(self):
        import json

        self.router.subscribe_obs(0.1)
        self.router.attach(
            "t1",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )
        self.router.submit("t1", *_batch())

        def host_events_arrived():
            trace = json.loads(self.router.fleet_chrome_trace())
            pids = {e.get("pid") for e in trace["traceEvents"]}
            return self.s1.endpoint in pids

        self.assertTrue(
            _wait(host_events_arrived),
            "pushed events never appeared under the host's pid",
        )

    def test_resubscribe_is_idempotent(self):
        self.router.subscribe_obs(0.1)
        self.router.subscribe_obs(0.1)  # drops + replaces the streams
        self.assertTrue(
            _wait(
                lambda: any(
                    not h["stale"]
                    for h in self.router.fleet_status()["hosts"].values()
                )
            )
        )
        self.router.unsubscribe_obs()
        self.assertTrue(_wait(_no_obs_threads))


if __name__ == "__main__":
    unittest.main()

"""SLO breach drill (ISSUE 16 acceptance): a chaos ``ingest_delay``
stalls one submit, the stall lands in ``serve.submit.latency``, the
publisher tick evaluates the registered SLO against it, and EXACTLY ONE
``slo.breach`` alarm callback fires — asserted from the obs snapshot
written to test-artifacts, the same evidence trail the cluster drills
leave."""

import json
import os
import tempfile
import threading
import time
import unittest
from unittest import mock

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.obs import slo as slo_mod
from torcheval_tpu.resilience import chaos
from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer, metric_spec

NUM_CLASSES = 4
DELAY_S = 0.5


def _artifact_dir() -> str:
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, "slo_breach_drill")
        os.makedirs(out, exist_ok=True)
        return out
    return tempfile.mkdtemp(prefix="tpu_slo_breach_")


class _ChaosEnv:
    def __init__(self, **env):
        self.env = {k: str(v) for k, v in env.items()}

    def __enter__(self):
        self._patch = mock.patch.dict(os.environ, self.env)
        self._patch.__enter__()
        chaos.reset_for_tests()

    def __exit__(self, *exc):
        self._patch.__exit__(*exc)
        chaos.reset_for_tests()


class TestSloBreachDrill(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()
        slo_mod._reset_for_tests()
        self.addCleanup(slo_mod._reset_for_tests)
        self.addCleanup(obs.reset)
        self.addCleanup(obs.disable)

    def test_ingest_delay_fires_exactly_one_breach_alarm(self):
        alarms = []
        alarm_lock = threading.Lock()

        def on_breach(payload):
            with alarm_lock:
                alarms.append(payload)

        obs.on_alarm(on_breach)
        obs.register_slo(
            obs.Slo(
                "submit_p99",
                instrument="serve.submit.latency",
                threshold_s=DELAY_S / 4.0,
                window_s=60.0,
                budget=0.01,
            )
        )
        with _ChaosEnv(
            TORCHEVAL_TPU_CHAOS="1",
            TORCHEVAL_TPU_CHAOS_ACTION="ingest_delay",
            TORCHEVAL_TPU_CHAOS_TENANT="t1",
            TORCHEVAL_TPU_CHAOS_STEP="2",
            TORCHEVAL_TPU_CHAOS_DELAY_S=str(DELAY_S),
        ):
            daemon = EvalDaemon().start()
            server = EvalServer(daemon)
            client = EvalClient(server.endpoint, request_timeout_s=60.0)
            self.addCleanup(daemon.stop)
            self.addCleanup(server.close)
            self.addCleanup(client.close)
            client.attach(
                "t1",
                {
                    "acc": metric_spec(
                        "MulticlassAccuracy", num_classes=NUM_CLASSES
                    )
                },
            )
            # the publisher tick IS the SLO evaluator in production:
            # subscribing arms it
            sub = client.subscribe_obs(0.1)
            self.addCleanup(sub.stop)
            for _ in range(4):  # step 2 eats the chaos stall
                client.submit(
                    "t1", np.zeros(8, np.int64), np.zeros(8, np.int64)
                )
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                with alarm_lock:
                    if alarms:
                        break
                time.sleep(0.05)
            # a few more publisher ticks: edge-triggering must hold
            time.sleep(0.5)

        snapshot = obs.snapshot()
        outdir = _artifact_dir()
        with open(os.path.join(outdir, "obs_snapshot.json"), "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        with alarm_lock:
            fired = list(alarms)
        with open(os.path.join(outdir, "alarms.json"), "w") as f:
            json.dump(fired, f, indent=2, default=str)

        # --- assertions read from the artifacts, drill-style ---
        with open(os.path.join(outdir, "alarms.json")) as f:
            fired = json.load(f)
        self.assertEqual(
            len(fired), 1, f"expected exactly one alarm, got {fired}"
        )
        self.assertEqual(fired[0]["kind"], "slo.breach")
        self.assertEqual(fired[0]["objective"], "submit_p99")
        self.assertIn("t1", fired[0]["series"])
        self.assertGreaterEqual(fired[0]["burn_rate"], 1.0)
        with open(os.path.join(outdir, "obs_snapshot.json")) as f:
            snap = json.load(f)
        self.assertEqual(
            snap["counters"].get(
                "slo.breach{objective=submit_p99,tenant=t1}"
            ),
            1.0,
        )
        self.assertIn(
            "slo.burn_rate{objective=submit_p99}", snap["gauges"]
        )
        # the stall itself is visible where the SLO looked: the latency
        # histogram's max-side tail crossed the threshold
        lat = snap["histograms"].get(
            "serve.submit.latency{tenant=t1}"
        )
        self.assertIsNotNone(lat)
        self.assertGreaterEqual(lat["p99"], DELAY_S / 4.0)


if __name__ == "__main__":
    unittest.main()

"""Batch coalescing: tenants with identical batch signatures share ONE
compiled window-step program (ISSUE 8 tentpole, leg 2).

The enabler is the canonical-positional-key refactor in
``metrics/deferred.py``: member names never reach the jitted program's
static specs or its states pytree, so N tenants running the same metric
classes/configs over the same batch shape hit one trace however they named
their members and however many collections wrap them. The recompile
watchdog's per-entry signature counts make that an observable; these tests
pin it, plus the correctness of the name↔canonical mapping and the
control-first fallback lane.

Signature counts are asserted RELATIVE to a warmed baseline, never as
absolutes (ISSUE 14 satellite): ``recompile.reset()`` clears the watchdog's
bookkeeping but NOT jax's compiled-program cache, so when another test file
(e.g. ``test_wire.py``) has already compiled the same window-step signature
in this process, the fleet's run records zero fresh traces and an absolute
``== 1`` assertion miscounts. The baseline owner drives the exact batch
signature once first — paying the compile iff the cache is cold — and the
assertion is "the fleet added ZERO signatures beyond the baseline's", which
holds under any test-file ordering.
"""

import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
)
from torcheval_tpu.obs import recompile
from torcheval_tpu.serve import EvalDaemon


def _batches(n_batches, seed, n=16, c=5):
    rng = np.random.default_rng(seed)
    return [
        (rng.random((n, c)).astype(np.float32), rng.integers(0, c, n))
        for _ in range(n_batches)
    ]


class TestProgramSharingAcrossOwners(unittest.TestCase):
    def setUp(self):
        obs.enable()
        obs.reset()
        recompile.reset()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        self.addCleanup(recompile.reset)

    def _window_step_signatures(self):
        return (
            recompile.trace_counts()
            .get("deferred.window_step", {})
            .get("distinct_signatures", 0)
        )

    def _drive_baseline(self, batches):
        """Run ONE owner through the exact batch signature under test and
        return the signature count after it — the jit-cache-state-proof
        baseline the fleet assertions count against (module docstring)."""
        base = MetricCollection({"base": MulticlassAccuracy(num_classes=5)})
        for s, l in batches:
            base.update(s, l)
        base.compute()
        return self._window_step_signatures()

    def test_differently_named_collections_share_one_program(self):
        batches = _batches(4, seed=0)
        baseline = self._drive_baseline(batches)
        cols = [
            MetricCollection({name: MulticlassAccuracy(num_classes=5)})
            for name in ("alpha", "beta", "gamma")
        ]
        for col in cols:
            for s, l in batches:
                col.update(s, l)
            col.compute()
        # zero new programs beyond the baseline owner's: the member name is
        # not part of the compiled program's identity
        self.assertEqual(self._window_step_signatures(), baseline)

    def test_100_tenants_compile_like_one(self):
        batches = _batches(3, seed=1)
        baseline = self._drive_baseline(batches)
        with EvalDaemon(max_tenants=128) as daemon:
            handles = [
                daemon.attach(
                    f"tenant-{i}", {f"m{i}": MulticlassAccuracy(num_classes=5)}
                )
                for i in range(100)
            ]
            for s, l in batches:
                for h in handles:
                    h.submit(s, l)
            values = [
                float(np.asarray(h.compute(timeout=120)[f"m{i}"]))
                for i, h in enumerate(handles)
            ]
        # every tenant computed the same stream: identical values, and the
        # whole fleet shares the baseline's window-step program (zero new
        # signatures for 100 tenants)
        self.assertEqual(len(set(values)), 1)
        self.assertEqual(self._window_step_signatures(), baseline)

    def test_canonical_mapping_lands_results_under_the_right_names(self):
        # two collections with the same two metric classes under SWAPPED
        # names: the canonical (positional) program keys must map back to
        # each owner's own names, never leak across
        scores = np.float32([[0.9, 0.1], [0.2, 0.8]])
        labels = np.int64([0, 0])
        a = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=2), "mse": MeanSquaredError()}
        )
        b = MetricCollection(
            {"mse": MulticlassAccuracy(num_classes=2), "acc": MeanSquaredError()}
        )
        preds = np.float32([1.0, 0.0])
        target = np.float32([1.0, 3.0])  # mse 4.5, distinct from acc 0.5
        # feed the classification pair to the classification members and
        # the regression pair to the regression members, via direct member
        # update (mixed-signature collections route per member)
        a.metrics["acc"].update(scores, labels)
        a.metrics["mse"].update(preds, target)
        b.metrics["mse"].update(scores, labels)
        b.metrics["acc"].update(preds, target)
        ra, rb = a.compute(), b.compute()
        self.assertEqual(
            float(np.asarray(ra["acc"])), float(np.asarray(rb["mse"]))
        )
        self.assertEqual(
            float(np.asarray(ra["mse"])), float(np.asarray(rb["acc"]))
        )
        self.assertNotEqual(
            float(np.asarray(ra["acc"])), float(np.asarray(ra["mse"]))
        )

    def test_mixed_signatures_fall_back_per_tenant(self):
        # two tenants with DIFFERENT batch shapes still both complete (the
        # scheduler groups by signature; a lone signature is its own group
        # and never waits) — values match their oracles
        b16 = _batches(3, seed=2, n=16)
        b32 = _batches(3, seed=3, n=32)
        with EvalDaemon() as daemon:
            h16 = daemon.attach("t16", MulticlassAccuracy(num_classes=5))
            h32 = daemon.attach("t32", MulticlassAccuracy(num_classes=5))
            for (s16, l16), (s32, l32) in zip(b16, b32):
                h16.submit(s16, l16)
                h32.submit(s32, l32)
            got16 = float(np.asarray(h16.compute(timeout=60)))
            got32 = float(np.asarray(h32.compute(timeout=60)))
        for got, batches in ((got16, b16), (got32, b32)):
            oracle = MulticlassAccuracy(num_classes=5)
            for s, l in batches:
                oracle.update(s, l)
            self.assertEqual(got, float(np.asarray(oracle.compute())))


if __name__ == "__main__":
    unittest.main()

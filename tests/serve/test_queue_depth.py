"""``serve.queue_depth{tenant=}`` dequeue-side fix (ISSUE 16 satellite).

Before this PR the histogram was recorded only on the submit path, so a
drained queue kept reporting its high-water mark forever: dashboards
showed phantom backlog after the daemon had caught up. The worker now
records depth 0 when it pops a tenant's whole queue, so the series'
LATEST observation reaches 0 after a drain."""

import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import EvalDaemon

NUM_CLASSES = 4


def _depth_histo(tenant):
    return obs.snapshot()["histograms"].get(
        f"serve.queue_depth{{tenant={tenant}}}"
    )


class TestQueueDepthReachesZero(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.reset)
        self.addCleanup(obs.disable)

    def test_depth_series_reaches_zero_after_drain(self):
        with EvalDaemon() as daemon:
            handle = daemon.attach(
                "t1", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
            )
            for _ in range(6):
                handle.submit(
                    np.zeros(8, np.int64),
                    np.zeros(8, np.int64),
                    timeout=60,
                )
            handle.compute(timeout=60)  # forces the queue to drain
            h = _depth_histo("t1")
            self.assertIsNotNone(h, "depth histogram never recorded")
            # the submit path records exactly one observation per submit
            # (6 here) — any further observations are the dequeue-side
            # zeros this PR adds, and zeros land in the lowest bucket
            self.assertGreater(h["count"], 6)
            from torcheval_tpu.obs import registry as _registry

            for kind, name, lb, value in (
                _registry.default_registry._items()
            ):
                if kind == "histo" and name == "serve.queue_depth":
                    buckets = value[0]
                    self.assertGreater(
                        buckets[0], 0, "no zero-depth observations"
                    )

    def test_dequeue_record_is_gated_when_disabled(self):
        obs.disable()
        with EvalDaemon() as daemon:
            handle = daemon.attach(
                "t1", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
            )
            handle.submit(
                np.zeros(8, np.int64), np.zeros(8, np.int64), timeout=60
            )
            handle.compute(timeout=60)
        self.assertIsNone(_depth_histo("t1"))


if __name__ == "__main__":
    unittest.main()

"""Unit tests for the router's durable control-plane WAL (serve/journal.py,
ISSUE 20). The contracts under test: append/replay round-trip with
monotonic seqs, torn-tail drop + truncate-then-heal, snapshot compaction
with exactly-once replay across the crash window (seq watermark), and the
degrade-never-crash path for an unreadable snapshot."""

import json
import os
import tempfile
import unittest
import zlib

from torcheval_tpu import obs
from torcheval_tpu.serve.journal import RouterJournal


def _wal(directory):
    return os.path.join(directory, "wal.log")


def _snap(directory):
    return os.path.join(directory, "snapshot.json")


class TestJournalRoundTrip(unittest.TestCase):
    def setUp(self):
        obs.reset()
        self.dir = tempfile.mkdtemp(prefix="tpu_journal_test_")

    def test_append_replay_round_trip(self):
        j = RouterJournal(self.dir)
        j.append("place", tenant="a", endpoint="e1")
        j.append("move", tenant="a", endpoint="e2")
        j.append("remove", tenant="a")
        j.close()
        j2 = RouterJournal(self.dir)
        snapshot, records = j2.replay()
        j2.close()
        self.assertIsNone(snapshot)
        self.assertEqual(
            [(r["kind"], r.get("endpoint")) for r in records],
            [("place", "e1"), ("move", "e2"), ("remove", None)],
        )

    def test_seqs_are_monotonic_across_reopens(self):
        j = RouterJournal(self.dir)
        s1 = j.append("place", tenant="a")
        s2 = j.append("place", tenant="b")
        j.close()
        j2 = RouterJournal(self.dir)
        s3 = j2.append("place", tenant="c")
        j2.close()
        self.assertEqual([s1, s2, s3], sorted([s1, s2, s3]))
        self.assertLess(s2, s3)

    def test_append_on_closed_journal_raises(self):
        j = RouterJournal(self.dir)
        j.close()
        with self.assertRaises(ValueError):
            j.append("place", tenant="a")
        with self.assertRaises(ValueError):
            j.compact({})
        j.close()  # idempotent

    def test_empty_directory_replays_empty(self):
        j = RouterJournal(self.dir)
        snapshot, records = j.replay()
        j.close()
        self.assertIsNone(snapshot)
        self.assertEqual(records, [])

    def test_records_counter_labeled_by_kind(self):
        obs.enable()
        self.addCleanup(obs.disable)
        j = RouterJournal(self.dir)
        j.append("place", tenant="a")
        j.append("place", tenant="b")
        j.append("split", tenant="a", replicas=["a@r1"])
        j.close()
        counters = obs.snapshot()["counters"]
        self.assertEqual(
            counters.get("serve.router.journal_records{kind=place}"), 2.0
        )
        self.assertEqual(
            counters.get("serve.router.journal_records{kind=split}"), 1.0
        )


class TestTornTail(unittest.TestCase):
    def setUp(self):
        obs.reset()
        self.dir = tempfile.mkdtemp(prefix="tpu_journal_torn_")

    def _seed(self, *tenants):
        j = RouterJournal(self.dir)
        for t in tenants:
            j.append("place", tenant=t)
        j.close()

    def test_torn_tail_dropped_and_counted_not_raised(self):
        self._seed("x", "y")
        with open(_wal(self.dir), "ab") as f:
            f.write(b"deadbeef {torn mid-wri")  # no newline: torn write
        obs.enable()
        self.addCleanup(obs.disable)
        j = RouterJournal(self.dir)
        _, records = j.replay()
        j.close()
        self.assertEqual([r["tenant"] for r in records], ["x", "y"])
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "serve.router.journal_torn_tails{reason=wal}"
            ),
            1.0,
        )

    def test_crc_mismatch_dropped(self):
        self._seed("x")
        body = b'{"kind":"place","seq":99,"tenant":"evil"}'
        with open(_wal(self.dir), "ab") as f:
            f.write(b"%08x %s\n" % (0x12345678, body))  # wrong CRC
        j = RouterJournal(self.dir)
        _, records = j.replay()
        j.close()
        self.assertEqual([r["tenant"] for r in records], ["x"])

    def test_append_after_tear_heals(self):
        # Regression: the reopen must TRUNCATE the torn bytes before
        # appending, or the new record glues onto the garbage and is
        # dropped with it at the next replay.
        self._seed("x", "y")
        with open(_wal(self.dir), "ab") as f:
            f.write(b"deadbeef {torn")
        j = RouterJournal(self.dir)
        j.append("place", tenant="z")
        j.close()
        j2 = RouterJournal(self.dir)
        _, records = j2.replay()
        j2.close()
        self.assertEqual([r["tenant"] for r in records], ["x", "y", "z"])

    def test_everything_after_a_tear_is_dropped(self):
        # Order is the journal's one integrity guarantee: a good-looking
        # record PAST a corrupt one is not trusted.
        self._seed("x")
        good = json.dumps(
            {"kind": "place", "seq": 50, "tenant": "late"},
            sort_keys=True,
            separators=(",", ":"),
        ).encode()
        with open(_wal(self.dir), "ab") as f:
            f.write(b"nothexxx not-a-record\n")
            f.write(b"%08x %s\n" % (zlib.crc32(good) & 0xFFFFFFFF, good))
        j = RouterJournal(self.dir)
        _, records = j.replay()
        j.close()
        self.assertEqual([r["tenant"] for r in records], ["x"])


class TestCompaction(unittest.TestCase):
    def setUp(self):
        obs.reset()
        self.dir = tempfile.mkdtemp(prefix="tpu_journal_compact_")

    def test_compact_publishes_snapshot_and_truncates_wal(self):
        obs.enable()
        self.addCleanup(obs.disable)
        j = RouterJournal(self.dir)
        j.append("place", tenant="a")
        j.append("place", tenant="b")
        j.compact({"tenants": {"a": {}, "b": {}}})
        j.append("place", tenant="c")
        j.close()
        self.assertEqual(os.path.getsize(_wal(self.dir)) > 0, True)
        j2 = RouterJournal(self.dir)
        snapshot, records = j2.replay()
        j2.close()
        self.assertEqual(snapshot, {"tenants": {"a": {}, "b": {}}})
        self.assertEqual([r["tenant"] for r in records], ["c"])
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "serve.router.journal_compactions"
            ),
            1.0,
        )

    def test_replay_skips_records_folded_into_snapshot(self):
        # Crash window: snapshot published but WAL NOT yet truncated.
        # Replay must skip WAL records at or below the snapshot's seq
        # watermark — each mutation applies exactly once.
        j = RouterJournal(self.dir)
        j.append("place", tenant="a")
        j.append("place", tenant="b")
        j.close()
        with open(_wal(self.dir), "rb") as f:
            stale_wal = f.read()
        j2 = RouterJournal(self.dir)
        j2.compact({"folded": True})
        j2.close()
        # simulate the crash: restore the pre-compaction WAL alongside
        # the published snapshot
        with open(_wal(self.dir), "wb") as f:
            f.write(stale_wal)
        j3 = RouterJournal(self.dir)
        snapshot, records = j3.replay()
        j3.close()
        self.assertEqual(snapshot, {"folded": True})
        self.assertEqual(records, [])

    def test_auto_compaction_via_snapshot_fn(self):
        j = RouterJournal(
            self.dir, snapshot_fn=lambda: {"auto": True}, compact_every=3
        )
        j.append("place", tenant="a")
        j.append("place", tenant="b")
        self.assertFalse(os.path.exists(_snap(self.dir)))
        j.append("place", tenant="c")  # third record: auto-compact
        self.assertTrue(os.path.exists(_snap(self.dir)))
        j.append("place", tenant="d")
        j.close()
        j2 = RouterJournal(self.dir)
        snapshot, records = j2.replay()
        j2.close()
        self.assertEqual(snapshot, {"auto": True})
        self.assertEqual([r["tenant"] for r in records], ["d"])

    def test_unreadable_snapshot_degrades_to_wal(self):
        obs.enable()
        self.addCleanup(obs.disable)
        j = RouterJournal(self.dir)
        j.append("place", tenant="a")
        j.compact({"fine": 1})
        j.append("place", tenant="b")
        j.close()
        with open(_snap(self.dir), "wb") as f:
            f.write(b"{not json at all")
        j2 = RouterJournal(self.dir)
        snapshot, records = j2.replay()
        # still appendable after the degraded load
        j2.append("place", tenant="c")
        j2.close()
        self.assertIsNone(snapshot)
        self.assertEqual([r["tenant"] for r in records], ["b"])
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "serve.router.journal_torn_tails{reason=snapshot}"
            ),
            1.0,
        )

    def test_tmp_snapshot_from_crashed_compaction_is_harmless(self):
        j = RouterJournal(self.dir)
        j.append("place", tenant="a")
        j.close()
        with open(_snap(self.dir) + ".tmp", "wb") as f:
            f.write(b"half-written garbage")
        j2 = RouterJournal(self.dir)
        snapshot, records = j2.replay()
        j2.close()
        self.assertIsNone(snapshot)
        self.assertEqual([r["tenant"] for r in records], ["a"])


if __name__ == "__main__":
    unittest.main()

"""Worker for the 4-process serve fault-containment test (ISSUE 8
acceptance).

Each process joins a real ``jax.distributed`` CPU world and runs ONE
EvalDaemon serving three tenants:

* ``alice`` — the healthy tenant whose results must come through every
  fault bit-identical, locally and over the sync legs;
* ``bob`` — the poison victim: on the POISON rank (per-rank env from the
  launcher) chaos corrupts bob's 2nd batch to all-NaN at the queue
  boundary, and bob's ``nan_policy="reject"`` quarantines him there;
* ``carol`` — the eviction leg: explicitly evicted mid-stream
  (checkpoint via ``resilience.save``), re-attached with
  ``resume="require"``, and streamed to completion — her final value must
  be bit-identical to a fault-free oracle.

Then two sync legs through the daemon worker thread: sync A with every
rank alive (global value), and sync B during which chaos kills or delays
the FAULT rank mid-collective — survivors must degrade to LOCAL results
within the deadline (the PR 5 contract, exercised through the serve
front end).

Run:  python mp_serve_worker.py <rank> <world> <port> <outdir>
Writes <outdir>/rank<r>.json, rank<r>.obs.json (per-tenant serve counters)
and rank<r>.health.json (daemon health snapshot) — uploaded as CI
artifacts. A killed rank writes nothing: it is dead.
"""

import json
import os
import sys
import time

import numpy as np

NUM_CLASSES = 5
BATCH = 48
PHASE0_BATCHES = 3
PHASE1_BATCHES = 2
TIMEOUT_S = 8.0
CHAOS_EXIT_CODE = 43
POISON_RANK = 1  # chaos poisons bob's batch 2 here (per-rank env)
FAULT_RANK = 2  # chaos kills/delays this rank at sync round 3
TENANTS = ("alice", "bob", "carol")


def make_shard(rank: int, tenant: str, phase: int, batch: int):
    seed = 10_000 * (TENANTS.index(tenant) + 1) + 100 * phase + 10 * batch + rank
    rng = np.random.default_rng(seed)
    scores = rng.random((BATCH, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, BATCH)
    return scores, labels


def tenant_stream(rank: int, tenant: str, phases=(0,)):
    out = []
    for phase in phases:
        n = PHASE0_BATCHES if phase == 0 else PHASE1_BATCHES
        out.extend(make_shard(rank, tenant, phase, b) for b in range(n))
    return out


def main() -> None:
    rank, world, port, outdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = port
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["RANK"] = str(rank)
    from torcheval_tpu.parallel import init_from_env

    got_rank, got_world = init_from_env()
    assert (got_rank, got_world) == (rank, world)

    from torcheval_tpu import obs
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.serve import EvalDaemon, TenantQuarantinedError

    obs.enable()
    results = {"rank": rank}

    daemon = EvalDaemon(
        evict_dir=os.path.join(outdir, f"evict_rank{rank}")
    ).start()
    handles = {
        t: daemon.attach(
            t,
            {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
            nan_policy="reject" if t == "bob" else "propagate",
        )
        for t in TENANTS
    }

    # --- phase 0: interleaved streams; on POISON_RANK chaos corrupts
    # bob's 2nd batch to NaN at the queue boundary
    for b in range(PHASE0_BATCHES):
        for t in TENANTS:
            try:
                handles[t].submit(*make_shard(rank, t, 0, b))
            except TenantQuarantinedError as e:
                results[f"{t}_submit_error"] = e.reason

    # --- local computes: alice/carol must be fault-free everywhere; bob is
    # quarantined exactly on the poison rank
    results["alice_phase0"] = float(
        np.asarray(handles["alice"].compute(timeout=120)["acc"])
    )
    try:
        results["bob_phase0"] = float(
            np.asarray(handles["bob"].compute(timeout=120)["acc"])
        )
    except TenantQuarantinedError as e:
        results["bob_quarantined"] = {
            "reason": e.reason,
            "tenant": e.tenant,
            "cause": type(e.__cause__).__name__ if e.__cause__ else None,
        }

    # --- carol: evict mid-stream (atomic checkpoint), reattach, resume
    ckpt = daemon.evict("carol", timeout=120)
    results["carol_ckpt_exists"] = os.path.isdir(ckpt)
    carol2 = daemon.attach(
        "carol",
        {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
        resume="require",
    )
    for b in range(PHASE1_BATCHES):
        carol2.submit(*make_shard(rank, "carol", 1, b))
    results["carol_resumed"] = float(
        np.asarray(carol2.compute(timeout=120)["acc"])
    )

    # --- sync A (rounds 1-2): every rank alive — the global alice value
    rA = handles["alice"].sync_compute(
        timeout_s=60.0, on_failure="local", timeout=180
    )
    results["alice_syncA"] = float(np.asarray(rA["acc"]))

    # --- phase 1 for alice, then sync B (rounds 3-4): chaos kills/delays
    # FAULT_RANK entering round 3; survivors must degrade to LOCAL within
    # the deadline, through the daemon worker thread
    for b in range(PHASE1_BATCHES):
        handles["alice"].submit(*make_shard(rank, "alice", 1, b))
    t0 = time.monotonic()
    rB = handles["alice"].sync_compute(
        timeout_s=TIMEOUT_S, on_failure="local", timeout=240
    )
    results["alice_syncB"] = float(np.asarray(rB["acc"]))
    results["syncB_elapsed_s"] = time.monotonic() - t0
    results["alice_local_post"] = float(
        np.asarray(handles["alice"].compute(timeout=120)["acc"])
    )

    snap = obs.snapshot()
    results["timeouts_local"] = snap["counters"].get(
        "toolkit.sync.timeouts{policy=local}", 0.0
    )

    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"rank{rank}.obs.json"), "w") as f:
        json.dump(snap, f, indent=2)
    with open(os.path.join(outdir, f"rank{rank}.health.json"), "w") as f:
        json.dump(daemon.health(), f, indent=2)
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)
        f.flush()
        os.fsync(f.fileno())
    # straggler world: the coordination-service leader (rank 0) must
    # outlive the delayed rank's sleep or the runtime SIGABRTs it
    hold_s = float(os.environ.get("TORCHEVAL_TPU_CHAOS_HOLD_S", "0"))
    if rank == 0 and hold_s > 0:
        time.sleep(hold_s)
    # hard exit: peers of a dead rank must not wedge in teardown
    os._exit(0)


if __name__ == "__main__":
    main()

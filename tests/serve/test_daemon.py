"""EvalDaemon basics: admission control, bounded-queue backpressure, the
tenant lifecycle, and result parity against plain collections.

The contract under test (ISSUE 8 tentpole, legs 1 and 4): the daemon is an
*async front end over the exact same metric machinery* — a tenant's
``compute()`` must be bit-identical to driving an identically-configured
``MetricCollection`` by hand — and every refusal is structured
(``AdmissionError``/``BackpressureError`` with a machine-readable
``reason``), never an unbounded queue or a bare crash.
"""

import threading
import time
import unittest

import numpy as np

from torcheval_tpu.metrics import (
    MetricCollection,
    MulticlassAccuracy,
    MulticlassF1Score,
)
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.serve import (
    AdmissionError,
    BackpressureError,
    EvalDaemon,
    ServeError,
    TenantStatus,
)

RNG = np.random.default_rng(11)


def _batch(n=32, c=5, rng=RNG):
    return (
        rng.random((n, c)).astype(np.float32),
        rng.integers(0, c, n),
    )


class GateMetric(Metric):
    """Eager metric whose update blocks on an event — the deterministic way
    to wedge the worker so queue-capacity behavior can be asserted."""

    def __init__(self, gate, started, *, device=None):
        super().__init__(device=device)
        self.gate = gate
        self.started = started

    def update(self, *args):
        self.started.set()
        self.gate.wait(30)
        return self

    def compute(self):
        return 0.0

    def merge_state(self, metrics):
        return self


class TestLifecycleAndParity(unittest.TestCase):
    def test_compute_matches_plain_collection_bit_identical(self):
        batches = [_batch(rng=np.random.default_rng(s)) for s in range(12)]
        oracle = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=5),
                "f1": MulticlassF1Score(num_classes=5, average="macro"),
            }
        )
        for s, l in batches:
            oracle.update(s, l)
        want = {
            k: np.asarray(v) for k, v in oracle.compute().items()
        }
        with EvalDaemon() as daemon:
            h = daemon.attach(
                "parity",
                {
                    "acc": MulticlassAccuracy(num_classes=5),
                    "f1": MulticlassF1Score(num_classes=5, average="macro"),
                },
            )
            for s, l in batches:
                h.submit(s, l)
            got = h.compute(timeout=60)
        for k in want:
            np.testing.assert_array_equal(np.asarray(got[k]), want[k])

    def test_compute_then_more_batches_then_compute(self):
        with EvalDaemon() as daemon:
            h = daemon.attach("t", MulticlassAccuracy(num_classes=5))
            oracle = MulticlassAccuracy(num_classes=5)
            for seed in range(3):
                s, l = _batch(rng=np.random.default_rng(seed))
                h.submit(s, l)
                oracle.update(s, l)
            first = h.compute(timeout=60)
            self.assertEqual(
                float(np.asarray(first)), float(np.asarray(oracle.compute()))
            )
            for seed in range(3, 6):
                s, l = _batch(rng=np.random.default_rng(seed))
                h.submit(s, l)
                oracle.update(s, l)
            second = h.compute(timeout=60)
            self.assertEqual(
                float(np.asarray(second)), float(np.asarray(oracle.compute()))
            )

    def test_detach_frees_slot_and_handle_dies(self):
        with EvalDaemon(max_tenants=1) as daemon:
            h = daemon.attach("a", MulticlassAccuracy(num_classes=5))
            h.submit(*_batch())
            self.assertIsNone(h.detach(timeout=60))
            self.assertIs(h.status, TenantStatus.DETACHED)
            with self.assertRaises(ServeError):
                h.submit(*_batch())
            # the slot is free again
            h2 = daemon.attach("b", MulticlassAccuracy(num_classes=5))
            self.assertIs(h2.status, TenantStatus.ACTIVE)

    def test_prebuilt_collection_accepted(self):
        col = MetricCollection({"acc": MulticlassAccuracy(num_classes=5)})
        with EvalDaemon() as daemon:
            h = daemon.attach("pre", col)
            s, l = _batch()
            h.submit(s, l)
            got = h.compute(timeout=60)
            self.assertIn("acc", got)

    def test_health_snapshot_shape(self):
        with EvalDaemon(max_tenants=3) as daemon:
            h = daemon.attach("h1", MulticlassAccuracy(num_classes=5))
            h.submit(*_batch())
            h.compute(timeout=60)
            health = daemon.health()
        self.assertTrue(health["worker_alive"])
        self.assertEqual(health["capacity"]["max_tenants"], 3)
        self.assertEqual(health["capacity"]["active_tenants"], 1)
        t = health["tenants"]["h1"]
        self.assertEqual(t["status"], "active")
        self.assertEqual(t["ingested"], 1)
        self.assertEqual(t["processed"], 1)
        self.assertEqual(t["queue_depth"], 0)
        self.assertEqual(health["totals"]["attached"], 1)


class TestAdmissionControl(unittest.TestCase):
    def test_duplicate_tenant_rejected(self):
        with EvalDaemon() as daemon:
            daemon.attach("dup", MulticlassAccuracy(num_classes=5))
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("dup", MulticlassAccuracy(num_classes=5))
            self.assertEqual(ctx.exception.reason, "duplicate_tenant")

    def test_capacity_rejected_with_reason(self):
        with EvalDaemon(max_tenants=2) as daemon:
            daemon.attach("a", MulticlassAccuracy(num_classes=5))
            daemon.attach("b", MulticlassAccuracy(num_classes=5))
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("c", MulticlassAccuracy(num_classes=5))
            self.assertEqual(ctx.exception.reason, "capacity")

    def test_stopped_daemon_rejects(self):
        daemon = EvalDaemon()
        with self.assertRaises(AdmissionError) as ctx:
            daemon.attach("x", MulticlassAccuracy(num_classes=5))
        self.assertEqual(ctx.exception.reason, "daemon_stopped")

    def test_bad_metrics_rejected(self):
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach("bad", {})
            self.assertEqual(ctx.exception.reason, "bad_metrics")

    def test_resume_require_without_checkpoint_rejected(self):
        with EvalDaemon() as daemon:
            with self.assertRaises(AdmissionError) as ctx:
                daemon.attach(
                    "ghost",
                    MulticlassAccuracy(num_classes=5),
                    resume="require",
                )
            self.assertEqual(ctx.exception.reason, "no_checkpoint")

    def test_bad_knobs_raise_valueerror(self):
        with self.assertRaises(ValueError):
            EvalDaemon(max_tenants=0)
        with self.assertRaises(ValueError):
            EvalDaemon(queue_capacity=0)
        with EvalDaemon() as daemon:
            with self.assertRaises(ValueError):
                daemon.attach(
                    "x", MulticlassAccuracy(num_classes=5), nan_policy="drop"
                )
            with self.assertRaises(ValueError):
                daemon.attach(
                    "x", MulticlassAccuracy(num_classes=5), resume="maybe"
                )

    def test_degenerate_tenant_timeouts_rejected_at_attach(self):
        # a bad deadline must reject ADMISSION — firing later inside the
        # worker would masquerade as tenant poison ('poisoned_batch'), and
        # nan would silently disarm the idle watchdog (nan >= never)
        with EvalDaemon() as daemon:
            for knob in ("watchdog_timeout_s", "step_timeout_s"):
                for bad in (0, -1.0, float("nan"), float("inf")):
                    with self.assertRaisesRegex(ValueError, knob):
                        daemon.attach(
                            "x",
                            MulticlassAccuracy(num_classes=5),
                            **{knob: bad},
                        )
            # a rejected attach leaves no tenant behind
            daemon.attach("x", MulticlassAccuracy(num_classes=5))

    def test_per_tenant_queue_capacity_validated(self):
        with EvalDaemon() as daemon:
            for bad in (0, -1):
                with self.assertRaisesRegex(ValueError, "queue_capacity"):
                    daemon.attach(
                        "x",
                        MulticlassAccuracy(num_classes=5),
                        queue_capacity=bad,
                    )
            h = daemon.attach(
                "x", MulticlassAccuracy(num_classes=5), queue_capacity=1
            )
            self.assertEqual(h._tenant.capacity, 1)


class TestBackpressure(unittest.TestCase):
    def test_full_queue_sheds_with_reason_and_block_waits(self):
        gate, started = threading.Event(), threading.Event()
        try:
            with EvalDaemon() as daemon:
                h = daemon.attach(
                    "bp",
                    {"gate": GateMetric(gate, started)},
                    queue_capacity=2,
                )
                # batch 1 wedges the worker inside update(); the queue is
                # then free to fill behind it
                h.submit(np.float32([1.0]))
                self.assertTrue(started.wait(10))
                h.submit(np.float32([2.0]))
                h.submit(np.float32([3.0]))
                # queue is now at capacity 2: the shed is immediate and
                # structured, never an unbounded append
                with self.assertRaises(BackpressureError) as ctx:
                    h.submit(np.float32([4.0]))
                self.assertEqual(ctx.exception.reason, "queue_full")
                self.assertEqual(ctx.exception.tenant, "bp")
                # block=True with a timeout sheds only after the wait
                t0 = time.monotonic()
                with self.assertRaises(BackpressureError):
                    h.submit(np.float32([5.0]), block=True, timeout=0.3)
                self.assertGreaterEqual(time.monotonic() - t0, 0.25)
                # a blocked submit goes through once the worker drains
                box = {}

                def _blocked_submit():
                    h.submit(np.float32([6.0]), block=True, timeout=20)
                    box["ok"] = True

                t = threading.Thread(target=_blocked_submit)
                t.start()
                gate.set()
                t.join(20)
                self.assertTrue(box.get("ok"))
                self.assertGreaterEqual(
                    daemon.health()["tenants"]["bp"]["sheds"], 2
                )
        finally:
            gate.set()


if __name__ == "__main__":
    unittest.main()

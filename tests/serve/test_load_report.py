"""Structured load reports (ISSUE 16 tentpole leg 3): the
``EvalDaemon.load_report()`` schema is a WIRE contract — routers,
dashboards and the ``/health`` endpoint all parse it — so this test pins
every key of schema 1. Adding a key is fine (extend the pin); renaming
or removing one requires a schema bump and a deliberate edit here."""

import json
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import EvalDaemon

NUM_CLASSES = 4

# schema 1, frozen: every (path, type) a consumer may rely on
_SCHEMA_1 = {
    "schema": int,
    "ts": float,
    "uptime_s": float,
    "running": bool,
    "draining": bool,
    "capacity.max_tenants": int,
    "capacity.active_tenants": int,
    "queue.depth": int,
    "queue.capacity": int,
    "queue.per_tenant": dict,
    "ingest.backlog_bytes": int,
    "totals.attached": int,
    "totals.quarantined": int,
    "totals.evicted": int,
    "latency.submit_ewma_s": float,
    "latency.step_ewma_s": float,
    "latency.submit_p99_s": float,
    "latency.step_p99_s": float,
    "window.occupancy_mean": float,
    "window.samples": int,
    "hbm.bytes_max_entry": float,
    "hbm.bytes_sum": float,
}


def _lookup(report, path):
    node = report
    for part in path.split("."):
        node = node[part]
    return node


class TestLoadReportSchema(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.reset)
        self.addCleanup(obs.disable)
        self.daemon = EvalDaemon().start()
        self.addCleanup(self.daemon.stop)

    def test_schema_1_keys_and_types_are_stable(self):
        report = self.daemon.load_report()
        self.assertEqual(report["schema"], 1)
        for path, typ in _SCHEMA_1.items():
            node = _lookup(report, path)
            self.assertIsInstance(
                node, typ, f"{path} is {type(node).__name__}, want {typ.__name__}"
            )
        # no key drift within the pinned sections either: a consumer
        # iterating a section must not meet a stranger without a bump
        self.assertEqual(
            sorted(report.keys()),
            sorted(
                {
                    "schema",
                    "ts",
                    "uptime_s",
                    "running",
                    "draining",
                    "capacity",
                    "queue",
                    "ingest",
                    "totals",
                    "latency",
                    "window",
                    "hbm",
                }
            ),
        )

    def test_report_is_json_serialisable(self):
        json.dumps(self.daemon.load_report())

    def test_report_reflects_traffic(self):
        handle = self.daemon.attach(
            "t1", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
        )
        handle.submit(
            np.zeros(8, np.int64), np.zeros(8, np.int64), block=True, timeout=60
        )
        handle.compute(timeout=60)
        report = self.daemon.load_report()
        self.assertEqual(report["capacity"]["active_tenants"], 1)
        self.assertEqual(report["totals"]["attached"], 1)
        self.assertIn("t1", report["queue"]["per_tenant"])
        self.assertGreater(report["latency"]["submit_ewma_s"], 0.0)
        self.assertGreater(report["latency"]["step_ewma_s"], 0.0)
        self.assertGreater(report["latency"]["submit_p99_s"], 0.0)

    def test_health_embeds_the_load_report(self):
        health = self.daemon.health()
        self.assertEqual(health["load_report"]["schema"], 1)

    def test_report_works_with_obs_disabled(self):
        # the report must degrade, not crash, when the registry is off
        # (latency p99s and hbm read zeros; the daemon-native EWMAs and
        # queue walk still report)
        obs.disable()
        report = self.daemon.load_report()
        self.assertEqual(report["schema"], 1)
        self.assertTrue(report["running"])

    def test_draining_flag_flips(self):
        self.daemon.drain()
        self.assertTrue(self.daemon.load_report()["draining"])


if __name__ == "__main__":
    unittest.main()

"""Elastic eval fleet (ISSUE 19): load-aware placement, the hysteretic
rebalancer, runtime ``add_host``/``remove_host``, and the pluggable
scaling policy. The real multi-process scale-up drill lives in
``test_elastic_mp.py``; here the hosts are in-process servers and load
reports are injected directly into the router's folded fleet state, so
every decision path runs deterministically. All sockets bind port 0.
"""

import tempfile
import time
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import (
    EvalDaemon,
    EvalRouter,
    EvalServer,
    HeadroomScalingPolicy,
    ScalingPolicy,
    ServeError,
)

NUM_CLASSES = 5
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(batches):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for s, l in batches:
        m.update(s, l)
    return float(np.asarray(m.compute()))


def _report(p99_s=0.0, draining=False):
    """A minimal schema-1 load report carrying one latency pressure."""
    return {
        "schema": 1,
        "draining": draining,
        "capacity": {"max_tenants": 0, "active_tenants": 0},
        "queue": {"depth": 0, "capacity": 0},
        "latency": {"submit_p99_s": p99_s, "submit_ewma_s": p99_s},
        "hbm": {},
    }


def _inject(router, endpoint, report, *, age_s=0.0):
    """Plant a folded load report for ``endpoint`` as if the obs stream
    delivered it ``age_s`` seconds ago."""
    with router._fleet_lock:
        router._fleet[endpoint] = {
            "acc": None,
            "events": [],
            "events_trimmed": 0,
            "report": report,
            "received_at": time.monotonic() - age_s,
            "mode": "push",
            "pushes": 1,
        }


class _ClusterMixin:
    N_HOSTS = 2

    def setUp(self):
        obs.reset()
        self.root = tempfile.mkdtemp(prefix="tpu_elastic_test_")
        self.daemons, self.servers = [], []
        for _ in range(self.N_HOSTS):
            self._start_host()
        self.router = EvalRouter(
            [s.endpoint for s in self.servers],
            request_timeout_s=10.0,
            connect_timeout_s=1.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.router.close)

    def _start_host(self):
        daemon = EvalDaemon(evict_dir=self.root).start()
        server = EvalServer(daemon)
        self.daemons.append(daemon)
        self.servers.append(server)
        self.addCleanup(daemon.stop)
        self.addCleanup(server.close)
        return server.endpoint


class TestWeightedPlacement(_ClusterMixin, unittest.TestCase):
    def test_no_load_signal_is_classic_rendezvous(self):
        # with every weight equal the -w/ln(u) argmax is a monotone
        # transform of the classic highest-random-weight draw: a fresh
        # router (no fleet state at all) must agree with a loaded one
        # that has heard nothing
        router2 = EvalRouter([s.endpoint for s in self.servers])
        self.addCleanup(router2.close)
        for i in range(64):
            tid = f"t{i}"
            self.assertEqual(
                self.router._place(tid), router2._place(tid), tid
            )

    def test_hot_host_repels_new_tenants(self):
        eps = self.router.endpoints
        hot, cold = eps[0], eps[1]
        _inject(self.router, hot, _report(p99_s=10.0))  # load -> 0.999
        _inject(self.router, cold, _report(p99_s=0.0))
        placed = [self.router._place(f"t{i}") for i in range(100)]
        on_hot = sum(1 for ep in placed if ep == hot)
        # weight ratio 1e-3 : 1 — essentially everything goes cold-side
        self.assertLessEqual(on_hot, 5, f"{on_hot}/100 landed hot")
        # and the skewed placement is still deterministic
        self.assertEqual(
            placed, [self.router._place(f"t{i}") for i in range(100)]
        )

    def test_stale_report_carries_no_weight(self):
        eps = self.router.endpoints
        _inject(self.router, eps[0], _report(p99_s=10.0), age_s=999.0)
        router2 = EvalRouter([s.endpoint for s in self.servers])
        self.addCleanup(router2.close)
        for i in range(32):
            tid = f"t{i}"
            self.assertEqual(
                self.router._place(tid), router2._place(tid), tid
            )

    def test_draining_host_ineligible_for_new_tenants(self):
        eps = self.router.endpoints
        _inject(self.router, eps[0], _report(draining=True))
        for i in range(32):
            self.assertEqual(self.router._place(f"t{i}"), eps[1])
        # unless that would empty the candidate set entirely
        _inject(self.router, eps[1], _report(draining=True))
        self.assertIn(self.router._place("t0"), eps)

    def test_silent_subscribed_host_is_suspect(self):
        eps = self.router.endpoints
        # a host whose subscribed stream delivered then went quiet past
        # the horizon is suspect -> ineligible for NEW tenants
        _inject(self.router, eps[0], _report(), age_s=999.0)
        with self.router._fleet_lock:
            self.router._obs_subs[eps[0]] = object()
        try:
            for i in range(32):
                self.assertEqual(self.router._place(f"t{i}"), eps[1])
        finally:
            with self.router._fleet_lock:
                self.router._obs_subs.pop(eps[0], None)


class TestFleetHeadroom(_ClusterMixin, unittest.TestCase):
    def test_headroom_none_without_reports(self):
        status = self.router.fleet_status()
        self.assertEqual(status["schema"], 1)
        self.assertIsNone(status["headroom"])
        for host in status["hosts"].values():
            self.assertIn("load", host)

    def test_headroom_folds_fresh_loads(self):
        eps = self.router.endpoints
        _inject(self.router, eps[0], _report(p99_s=0.6))
        _inject(self.router, eps[1], _report(p99_s=0.2))
        status = self.router.fleet_status()
        self.assertAlmostEqual(status["headroom"], 1.0 - 0.4, places=6)
        self.assertAlmostEqual(
            status["hosts"][eps[0]]["load"], 0.6, places=6
        )

    def test_headroom_gauge_emitted(self):
        obs.enable()
        self.addCleanup(obs.disable)
        _inject(self.router, self.router.endpoints[0], _report(p99_s=0.5))
        self.router.fleet_status()
        snap = obs.snapshot()
        self.assertIn("serve.fleet.headroom", snap["gauges"])


class TestRebalance(_ClusterMixin, unittest.TestCase):
    def _load_skew(self, hot_ep, cold_ep, hot=0.9, cold=0.1):
        _inject(self.router, hot_ep, _report(p99_s=hot))
        _inject(self.router, cold_ep, _report(p99_s=cold))

    def test_rebalance_moves_off_hot_host_exactly_once(self):
        obs.enable()
        self.addCleanup(obs.disable)
        self.router.attach("ten", SPEC)
        src = self.router.placement()["ten"]
        dst = next(ep for ep in self.router.endpoints if ep != src)
        stream = [_batch(1), _batch(2), _batch(3)]
        self.router.submit("ten", *stream[0])
        self.router.flush("ten")  # durable
        self.router.submit("ten", *stream[1])  # un-durable tail
        self._load_skew(src, dst)
        moved = self.router.rebalance(min_dwell_s=0.0)
        self.assertEqual(moved, ["ten"])
        self.assertEqual(self.router.placement()["ten"], dst)
        # exactly-once across the live move: durable batch via the
        # flushed checkpoint, the tail via the adopt replay, nothing
        # doubled
        self.router.submit("ten", *stream[2])
        got = float(np.asarray(self.router.compute("ten")["acc"]))
        self.assertEqual(got, _oracle(stream))
        health = self.daemons[
            [s.endpoint for s in self.servers].index(dst)
        ].health()
        self.assertEqual(health["tenants"]["ten"]["dupes"], 0)
        snap = obs.snapshot()
        self.assertEqual(
            snap["counters"].get(
                "serve.router.migrations{reason=rebalance}"
            ),
            1.0,
        )
        self.assertEqual(
            snap["counters"].get(
                "serve.router.rebalances{endpoint=%s}" % src
            ),
            1.0,
        )
        # hysteresis: the dwell clock restarted at the move, the load
        # picture is unchanged — repeated passes must NOT bounce it back
        for _ in range(5):
            self.assertEqual(
                self.router.rebalance(min_dwell_s=60.0), []
            )
        self.assertEqual(self.router.placement()["ten"], dst)

    def test_improvement_threshold_blocks_marginal_moves(self):
        self.router.attach("ten", SPEC)
        src = self.router.placement()["ten"]
        dst = next(ep for ep in self.router.endpoints if ep != src)
        self._load_skew(src, dst, hot=0.8, cold=0.7)
        self.assertEqual(
            self.router.rebalance(min_dwell_s=0.0, improvement=0.15), []
        )
        self.assertEqual(self.router.placement()["ten"], src)

    def test_max_moves_bounds_one_pass(self):
        counts = {ep: 0 for ep in self.router.endpoints}
        for i in range(256):
            if min(counts.values()) >= 3:
                break
            tid = f"t{i}"
            ep = self.router._place(tid)
            if counts[ep] >= 3:
                continue
            self.router.attach(tid, SPEC)
            counts[ep] += 1
        src = self.router.endpoints[0]
        dst = self.router.endpoints[1]
        self._load_skew(src, dst)
        moved = self.router.rebalance(min_dwell_s=0.0, max_moves=2)
        self.assertLessEqual(len(moved), 2)
        self.assertGreaterEqual(len(moved), 1)

    def test_no_fresh_loads_means_no_moves(self):
        self.router.attach("ten", SPEC)
        self.assertEqual(self.router.rebalance(min_dwell_s=0.0), [])

    def test_background_rebalancer_thread_lifecycle(self):
        import threading

        self.router.start_rebalancer(interval_s=0.05, min_dwell_s=0.0)
        names = [t.name for t in threading.enumerate()]
        self.assertIn("torcheval-tpu-router-rebalance", names)
        # a running pass with zero load data is a no-op, not a crash
        time.sleep(0.2)
        self.router.stop_rebalancer()
        time.sleep(0.05)
        names = [t.name for t in threading.enumerate()]
        self.assertNotIn("torcheval-tpu-router-rebalance", names)


class TestElasticHosts(_ClusterMixin, unittest.TestCase):
    def test_add_host_joins_placement(self):
        new_ep = self._start_host()
        self.assertNotIn(new_ep, self.router.endpoints)
        self.router.add_host(new_ep)
        self.assertIn(new_ep, self.router.alive)
        # the joined host is immediately placeable: some tenant ids must
        # rendezvous onto it (1/3 of draws in expectation)
        landed = any(
            self.router._place(f"j{i}") == new_ep for i in range(64)
        )
        self.assertTrue(landed)
        # and it actually serves
        for i in range(64):
            tid = f"j{i}"
            if self.router._place(tid) == new_ep:
                self.assertEqual(self.router.attach(tid, SPEC), new_ep)
                b = _batch(i)
                self.router.submit(tid, *b)
                got = float(
                    np.asarray(self.router.compute(tid)["acc"])
                )
                self.assertEqual(got, _oracle([b]))
                break

    def test_add_live_host_twice_rejected(self):
        with self.assertRaisesRegex(ValueError, "already in the fleet"):
            self.router.add_host(self.router.endpoints[0])

    def test_remove_host_drains_and_forgets(self):
        self.router.attach("ten", SPEC)
        src = self.router.placement()["ten"]
        b1, b2 = _batch(1), _batch(2)
        self.router.submit("ten", *b1)
        out = self.router.remove_host(src)
        self.assertIn("ten", out["migrated"])
        self.assertNotIn(src, self.router.endpoints)
        self.assertNotIn(src, self.router.alive)
        self.router.submit("ten", *b2)
        got = float(np.asarray(self.router.compute("ten")["acc"]))
        self.assertEqual(got, _oracle([b1, b2]))

    def test_autoscale_scales_up_on_low_headroom(self):
        for ep in self.router.endpoints:
            _inject(self.router, ep, _report(p99_s=0.95))
        policy = HeadroomScalingPolicy(
            scale_up_below=0.2, cooldown_s=0.0
        )
        provisioned = []

        def provision():
            ep = self._start_host()
            provisioned.append(ep)
            return ep

        delta = self.router.autoscale_step(policy, provision=provision)
        self.assertEqual(delta, 1)
        self.assertEqual(len(provisioned), 1)
        self.assertIn(provisioned[0], self.router.alive)

    def test_autoscale_scales_down_on_high_headroom(self):
        for ep in self.router.endpoints:
            _inject(self.router, ep, _report(p99_s=0.01))
        policy = HeadroomScalingPolicy(
            scale_down_above=0.8, min_hosts=1, cooldown_s=0.0
        )
        removed = []
        delta = self.router.autoscale_step(
            policy, decommission=removed.append
        )
        self.assertEqual(delta, -1)
        self.assertEqual(len(removed), 1)
        self.assertNotIn(removed[0], self.router.endpoints)
        self.assertEqual(len(self.router.alive), 1)


class TestScalingPolicy(unittest.TestCase):
    def test_base_policy_is_abstract(self):
        with self.assertRaises(NotImplementedError):
            ScalingPolicy().decide({})

    def test_knob_validation(self):
        with self.assertRaisesRegex(ValueError, "dead band"):
            HeadroomScalingPolicy(
                scale_up_below=0.8, scale_down_above=0.2
            )
        with self.assertRaisesRegex(ValueError, "min_hosts"):
            HeadroomScalingPolicy(min_hosts=0)
        with self.assertRaisesRegex(ValueError, "max_hosts"):
            HeadroomScalingPolicy(min_hosts=3, max_hosts=2)
        with self.assertRaisesRegex(ValueError, "cooldown_s"):
            HeadroomScalingPolicy(cooldown_s=-1)

    def test_no_signal_holds(self):
        policy = HeadroomScalingPolicy(cooldown_s=0.0)
        self.assertEqual(
            policy.decide({"headroom": None, "alive": ["a"]}), 0
        )

    def test_band_and_bounds(self):
        policy = HeadroomScalingPolicy(
            scale_up_below=0.2,
            scale_down_above=0.8,
            min_hosts=1,
            max_hosts=2,
            cooldown_s=0.0,
        )
        self.assertEqual(
            policy.decide({"headroom": 0.1, "alive": ["a"]}), 1
        )
        self.assertEqual(  # at max_hosts: hold even when starved
            policy.decide({"headroom": 0.1, "alive": ["a", "b"]}), 0
        )
        self.assertEqual(  # inside the dead band: hold
            policy.decide({"headroom": 0.5, "alive": ["a", "b"]}), 0
        )
        self.assertEqual(
            policy.decide({"headroom": 0.9, "alive": ["a", "b"]}), -1
        )
        self.assertEqual(  # at min_hosts: hold even when idle
            policy.decide({"headroom": 0.9, "alive": ["a"]}), 0
        )

    def test_cooldown_quiets_consecutive_decisions(self):
        policy = HeadroomScalingPolicy(cooldown_s=60.0)
        self.assertEqual(
            policy.decide({"headroom": 0.1, "alive": ["a"]}), 1
        )
        self.assertEqual(
            policy.decide({"headroom": 0.1, "alive": ["a"]}), 0
        )


class TestSyncComputeOnSplit(_ClusterMixin, unittest.TestCase):
    def test_sync_compute_refused_for_split_tenant(self):
        self.router.attach("ten", SPEC)
        self.router.split_tenant("ten", replicas=2)
        with self.assertRaises(ServeError) as ctx:
            self.router.sync_compute("ten")
        self.assertEqual(ctx.exception.reason, "split_tenant")


if __name__ == "__main__":
    unittest.main()

"""`EvalRouter` tests (ISSUE 10): placement, probe-driven failure
detection, host-death migration with checkpoint+replay exactness, and
graceful drain. The real multi-process host-kill drill lives in
``test_cluster_mp.py``; here the "dead host" is a closed server socket,
which exercises the identical client/router recovery machinery in one
process. All sockets bind port 0.
"""

import tempfile
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import (
    EvalDaemon,
    EvalRouter,
    EvalServer,
    ServeError,
)

NUM_CLASSES = 5
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}


def _batch(seed=0, n=8):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


def _oracle(batches):
    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for s, l in batches:
        m.update(s, l)
    return float(np.asarray(m.compute()))


class _ClusterMixin:
    N_HOSTS = 2

    def setUp(self):
        obs.reset()
        self.root = tempfile.mkdtemp(prefix="tpu_router_test_")
        self.daemons, self.servers = [], []
        for _ in range(self.N_HOSTS):
            daemon = EvalDaemon(evict_dir=self.root).start()
            server = EvalServer(daemon)
            self.daemons.append(daemon)
            self.servers.append(server)
            self.addCleanup(daemon.stop)
            self.addCleanup(server.close)
        self.router = EvalRouter(
            [s.endpoint for s in self.servers],
            request_timeout_s=10.0,
            connect_timeout_s=1.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.router.close)

    def _spread_tenants(self, per_host=3, prefix="t"):
        """Attach tenants chosen so EVERY host holds ``per_host`` of them.
        Rendezvous placement is deterministic but endpoint strings carry
        ephemeral ports, so fixed names could all land on one host —
        instead we consult the router's own placement function and pick
        ids until both hosts are covered."""
        counts = {ep: 0 for ep in self.router.endpoints}
        ids = []
        for i in range(256):
            if min(counts.values()) >= per_host:
                break
            tid = f"{prefix}{i}"
            ep = self.router._place(tid)
            if counts[ep] >= per_host:
                continue
            self.router.attach(tid, SPEC)
            counts[ep] += 1
            ids.append(tid)
        placement = self.router.placement()
        self.assertEqual(
            len(set(placement.values())),
            self.N_HOSTS,
            f"tenants all landed on one host: {placement}",
        )
        return ids

    def _kill_host(self, endpoint):
        idx = [s.endpoint for s in self.servers].index(endpoint)
        self.servers[idx].close()
        self.daemons[idx].stop()


class TestPlacement(_ClusterMixin, unittest.TestCase):
    def test_placement_is_deterministic(self):
        self._spread_tenants()
        p1 = self.router.placement()
        router2 = EvalRouter([s.endpoint for s in self.servers])
        self.addCleanup(router2.close)
        for tid, ep in p1.items():
            self.assertEqual(router2._place(tid), ep)

    def test_survivor_placement_unchanged_by_host_death(self):
        # minimal movement: killing host X never reshuffles tenants
        # already placed on host Y
        ids = self._spread_tenants()
        placement = self.router.placement()
        victim = placement[ids[0]]
        survivors_before = {
            t: ep for t, ep in placement.items() if ep != victim
        }
        self._kill_host(victim)
        self.router.health()  # probe detects, migrates
        after = self.router.placement()
        for t, ep in survivors_before.items():
            self.assertEqual(after[t], ep)

    def test_router_deadline_knobs_validated_at_construction(self):
        # the client kwargs a router fans out are validated by the same
        # _check_timeout_s boundary before any socket exists
        for bad in (0, -1.0, float("nan"), float("inf"), "5"):
            with self.assertRaisesRegex(ValueError, "request_timeout_s"):
                EvalRouter(["127.0.0.1:1"], request_timeout_s=bad)

    def test_duplicate_attach_rejected(self):
        self.router.attach("a", SPEC)
        with self.assertRaises(ServeError) as ctx:
            self.router.attach("a", SPEC)
        self.assertEqual(ctx.exception.reason, "duplicate_tenant")


class TestFailureMigration(_ClusterMixin, unittest.TestCase):
    def test_host_death_mid_stream_migrates_and_matches_oracle(self):
        """The core ISSUE 10 claim, in-process: host dies mid-window;
        its tenants finish on the survivor; every tenant's compute is
        bit-identical to a fault-free oracle — checkpointed batches come
        back through the shared root, un-durable ones through replay."""
        obs.enable()
        self.addCleanup(obs.disable)
        ids = self._spread_tenants()
        streams = {tid: [_batch(i), _batch(i + 100), _batch(i + 200)]
                   for i, tid in enumerate(ids)}
        for tid in ids:
            self.router.submit(tid, *streams[tid][0])
            self.router.flush(tid)  # batch 1 durable in the shared root
            self.router.submit(tid, *streams[tid][1])  # un-durable tail
        placement = self.router.placement()
        victim = placement[ids[0]]
        victims = [t for t, ep in placement.items() if ep == victim]
        self._kill_host(victim)
        # next submit hits the dead host -> transport failure -> the
        # router migrates ALL its tenants and replays the tail
        for tid in ids:
            self.router.submit(tid, *streams[tid][2])
        for tid in ids:
            got = float(np.asarray(self.router.compute(tid)["acc"]))
            self.assertEqual(got, _oracle(streams[tid]), tid)
        after = self.router.placement()
        for tid in victims:
            self.assertNotEqual(after[tid], victim)
        # zero duplicate application on the survivor: its per-tenant
        # processed counts equal the batches it actually owns (replayed
        # tail + post-migration) — the checkpointed batch is NOT re-run
        survivor = next(ep for ep in self.router.endpoints if ep != victim)
        sd = self.daemons[
            [s.endpoint for s in self.servers].index(survivor)
        ]
        health = sd.health()
        for tid in victims:
            self.assertEqual(health["tenants"][tid]["processed"], 2)
            self.assertEqual(health["tenants"][tid]["dupes"], 0)
        snap = obs.snapshot()
        migrations = [
            v
            for k, v in snap["counters"].items()
            if k.startswith("serve.router.migrations{")
        ]
        self.assertEqual(sum(migrations), float(len(victims)))
        # every victim replays its un-durable batch 2; the tenant whose
        # submit DETECTED the death additionally replays the in-flight
        # batch 3 it had booked (the router must not also resubmit it
        # fresh — that would double-apply)
        replays = [
            v
            for k, v in snap["counters"].items()
            if k.startswith("serve.router.replays{")
        ]
        self.assertEqual(sum(replays), float(len(victims) + 1))

    def test_probe_failure_detects_and_migrates(self):
        obs.enable()
        self.addCleanup(obs.disable)
        ids = self._spread_tenants()
        victim = self.router.placement()[ids[0]]
        self._kill_host(victim)
        report = self.router.health()
        self.assertIsNone(report["hosts"][victim])
        self.assertNotIn(victim, report["alive"])
        for tid, ep in self.router.placement().items():
            self.assertNotEqual(ep, victim)
        snap = obs.snapshot()
        self.assertTrue(
            any(
                k.startswith("serve.router.probe_failures{")
                for k in snap["counters"]
            )
        )

    def test_health_probe_fails_fast_on_silent_host(self):
        """A partitioned host (answers TCP, never replies) must not
        blind the failure detector for the full retry ladder: probes run
        single-attempt under probe_timeout_s."""
        import socket as _socket
        import time as _time

        silent = _socket.create_server(("127.0.0.1", 0))
        self.addCleanup(silent.close)
        silent_ep = f"127.0.0.1:{silent.getsockname()[1]}"
        router = EvalRouter(
            [self.servers[0].endpoint, silent_ep],
            probe_timeout_s=0.3,
            request_timeout_s=30.0,  # the probe must NOT use this
            connect_timeout_s=1.0,
        )
        self.addCleanup(router.close)
        t0 = _time.monotonic()
        report = router.health()
        elapsed = _time.monotonic() - t0
        self.assertIsNone(report["hosts"][silent_ep])
        self.assertIsNotNone(report["hosts"][self.servers[0].endpoint])
        self.assertLess(elapsed, 5.0)

    def test_all_hosts_dead_raises_no_hosts(self):
        self.router.attach("a", SPEC)
        for server in self.servers:
            self._kill_host(server.endpoint)
        self.router.health()
        with self.assertRaises(ServeError) as ctx:
            self.router.attach("b", SPEC)
        self.assertEqual(ctx.exception.reason, "no_hosts")


class TestDrain(_ClusterMixin, unittest.TestCase):
    def test_drain_migrates_with_empty_tail(self):
        """Graceful drain: the host checkpoints everything, so migration
        replays nothing and results stay oracle-exact."""
        obs.enable()
        self.addCleanup(obs.disable)
        ids = self._spread_tenants()
        streams = {tid: [_batch(i), _batch(i + 50)]
                   for i, tid in enumerate(ids)}
        for tid in ids:
            self.router.submit(tid, *streams[tid][0])
        placement = self.router.placement()
        victim = placement[ids[0]]
        victims = [t for t, ep in placement.items() if ep == victim]
        out = self.router.drain(victim)
        self.assertEqual(sorted(out["migrated"]), sorted(victims))
        self.assertEqual(sorted(out["drained"]), sorted(victims))
        self.assertNotIn(victim, self.router.alive)
        for tid in ids:
            self.router.submit(tid, *streams[tid][1])
            got = float(np.asarray(self.router.compute(tid)["acc"]))
            self.assertEqual(got, _oracle(streams[tid]), tid)
        snap = obs.snapshot()
        drain_migrations = snap["counters"].get(
            "serve.router.migrations{reason=drain}", 0.0
        )
        self.assertEqual(drain_migrations, float(len(victims)))
        # nothing was un-durable after a drain: zero replays
        self.assertFalse(
            any(
                k.startswith("serve.router.replays{")
                for k in snap["counters"]
            )
        )

    def test_migration_span_lands_in_timeline(self):
        obs.enable()
        self.addCleanup(obs.disable)
        ids = self._spread_tenants()
        victim = self.router.placement()[ids[0]]
        self.router.drain(victim)
        import json

        trace = json.loads(obs.chrome_trace())
        names = [e["name"] for e in trace["traceEvents"]]
        self.assertIn("serve.router.migrate", names)


if __name__ == "__main__":
    unittest.main()

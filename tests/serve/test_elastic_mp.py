"""Elastic scale-up drill: REAL multi-process hosts, chaos mid-scale-up
(ISSUE 19 acceptance).

One world: this test process runs the ``EvalRouter``; host processes
(``mp_cluster_host.py``) each own an ``EvalDaemon`` + ``EvalServer``
sharing ONE checkpoint root. The fleet starts at a single host A whose
environment arms a ``load_spike`` chaos on the "hot" tenant — every hot
batch pays a real ingest delay, so A's OWN load report (submit p99
against the router's latency target) reads saturated through the obs
stream, with no synthetic numbers injected anywhere. Then, end to end:

* the ``HeadroomScalingPolicy`` sees the starved headroom and scales up
  — ``provision()`` launches a REAL host B process and ``add_host``
  joins it into placement and the telemetry stream;
* one ``rebalance`` pass migrates load off hot A onto cold B using the
  live checkpoint+replay move (bounded by ``max_moves``);
* the hot tenant is SPLIT across the fleet and keeps streaming through
  the fan-out;
* chaos strikes mid-scale-up: a third host C joins armed with
  ``host_kill`` at its first submit — the router absorbs the death via
  failure migration and the interrupted batch arrives by replay;
* every tenant (including the split one, merged at compute) finishes
  BIT-IDENTICAL to its fault-free oracle, with zero sheds and drained
  queues — offered load beyond one host's capacity was absorbed by
  scaling, not by dropping.

Artifacts (fleet status/trace, router obs/trace, a drill summary) land
in test-artifacts. All sockets bind port 0 (OS-assigned).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import unittest

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_HOST = os.path.join(_HERE, "mp_cluster_host.py")

NUM_CLASSES = 5
BATCH = 32
PHASE1, PHASE2 = 2, 3
HOT_DELAY_S = 0.4
LATENCY_TARGET_S = 0.5
CHAOS_EXIT_CODE = 43
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
COLD_TENANTS = ("t0", "t1")


def _make_batch(tenant: str, idx: int):
    seed = 1000 * (hash(tenant) % 97) + idx
    rng = np.random.default_rng(seed)
    return (
        rng.random((BATCH, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, BATCH),
    )


def _oracle(tenant: str, n: int) -> float:
    from torcheval_tpu.metrics import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for i in range(n):
        m.update(*_make_batch(tenant, i))
    return float(np.asarray(m.compute()))


def _wait(predicate, timeout_s=15.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _artifact_dir() -> str:
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, "elastic_drill")
        os.makedirs(out, exist_ok=True)
        return out
    return tempfile.mkdtemp(prefix="tpu_elastic_drill_")


def _launch_host(outdir: str, tag: str, ckpt_root: str, chaos_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith("TORCHEVAL_TPU_CHAOS"):
            del env[k]
    if chaos_env:
        env.update(chaos_env)
    return subprocess.Popen(
        [sys.executable, _HOST, outdir, tag, ckpt_root],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _wait_port(outdir: str, tag: str, timeout_s: float = 90.0) -> int:
    path = os.path.join(outdir, f"{tag}.port")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return int(f.read())
        time.sleep(0.05)
    raise TimeoutError(f"host {tag} never published its port.")


class TestElasticScaleUpDrill(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        cls.procs = {}
        try:
            cls._run_world()
        except BaseException:
            for proc in cls.procs.values():
                if proc.poll() is None:
                    proc.kill()
            raise

    @classmethod
    def _launch(cls, tag, chaos_env=None):
        cls.procs[tag] = _launch_host(
            cls.outdir, tag, cls.ckpt_root, chaos_env=chaos_env
        )
        return f"127.0.0.1:{_wait_port(cls.outdir, tag)}"

    @classmethod
    def _run_world(cls):
        from torcheval_tpu import obs
        from torcheval_tpu.serve import (
            EvalClient,
            EvalRouter,
            HeadroomScalingPolicy,
        )

        cls.outdir = _artifact_dir()
        cls.ckpt_root = os.path.join(cls.outdir, "ckpt_root")
        os.makedirs(cls.ckpt_root, exist_ok=True)

        # host A: a REAL ingest stall on every "hot" batch — the load
        # signal the whole drill scales on comes from A's own clocks
        cls.ep_a = cls._launch(
            "hostA",
            chaos_env={
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": "load_spike",
                "TORCHEVAL_TPU_CHAOS_TENANT": "hot",
                "TORCHEVAL_TPU_CHAOS_STEP": "1",
                "TORCHEVAL_TPU_CHAOS_DELAY_S": str(HOT_DELAY_S),
            },
        )
        obs.reset()
        obs.enable()
        cls.router = EvalRouter(
            [cls.ep_a],
            request_timeout_s=10.0,
            connect_timeout_s=5.0,
            max_attempts=2,
            backoff_base_s=0.05,
            backoff_cap_s=0.2,
            latency_target_s=LATENCY_TARGET_S,
        )
        cls.fleet_modes = cls.router.subscribe_obs(
            0.25, stale_after_s=2.0
        )

        for t in COLD_TENANTS + ("hot",):
            cls.router.attach(t, SPEC)
        for i in range(PHASE1):
            for t in COLD_TENANTS + ("hot",):
                cls.router.submit(t, *_make_batch(t, i))
        for t in COLD_TENANTS + ("hot",):
            cls.router.flush(t)

        # the spike shows up in A's pushed load report: headroom starves
        cls.headroom_starved = _wait(
            lambda: (cls.router.fleet_status()["headroom"] or 1.0) < 0.55
        )
        cls.headroom_before = cls.router.fleet_status()["headroom"]

        # autoscale: the policy decides +1, provision() starts a REAL
        # host process and hands its endpoint to add_host
        policy = HeadroomScalingPolicy(
            scale_up_below=0.55, cooldown_s=0.0
        )
        cls.scale_delta = cls.router.autoscale_step(
            policy, provision=lambda: cls._launch("hostB")
        )
        cls.ep_b = next(
            ep for ep in cls.router.alive if ep != cls.ep_a
        )
        cls.b_fresh = _wait(
            lambda: not cls.router.fleet_status()["hosts"]
            .get(cls.ep_b, {"stale": True})["stale"]
        )

        # rebalance off the hot host (live move, bounded)
        cls.rebalance_moved = cls.router.rebalance(
            hot_load=0.5,
            improvement=0.2,
            min_dwell_s=0.0,
            max_moves=2,
        )
        # hysteresis immediately after: dwell clocks just restarted
        cls.rebalance_second_pass = cls.router.rebalance(
            hot_load=0.5, improvement=0.2, min_dwell_s=60.0, max_moves=2
        )

        # split the hot tenant across the fleet and keep streaming
        cls.split_placement = cls.router.split_tenant("hot", replicas=2)
        for i in range(PHASE1, PHASE1 + PHASE2):
            for t in COLD_TENANTS + ("hot",):
                cls.router.submit(t, *_make_batch(t, i))

        # chaos mid-scale-up: host C joins armed to die at its FIRST
        # submit; the router must absorb it like any host death
        cls.ep_c = cls._launch(
            "hostC",
            chaos_env={
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": "host_kill",
                "TORCHEVAL_TPU_CHAOS_TENANT": "*",
                "TORCHEVAL_TPU_CHAOS_STEP": "1",
                "TORCHEVAL_TPU_CHAOS_EXIT_CODE": str(CHAOS_EXIT_CODE),
            },
        )
        cls.router.add_host(cls.ep_c)
        cls.late_tenant = next(
            tid
            for tid in (f"late{i}" for i in range(256))
            if cls.router._place(tid) == cls.ep_c
        )
        cls.router.attach(cls.late_tenant, SPEC)
        for i in range(2):
            cls.router.submit(
                cls.late_tenant, *_make_batch(cls.late_tenant, i)
            )

        for t in COLD_TENANTS + ("hot", cls.late_tenant):
            cls.router.flush(t)
        cls.results = {
            t: float(np.asarray(cls.router.compute(t)["acc"]))
            for t in COLD_TENANTS + ("hot", cls.late_tenant)
        }
        cls.placement_after = cls.router.placement()
        cls.alive_after = cls.router.alive

        # post-scale-up invariants: queues drained, zero sheds anywhere
        cls.host_counters = {}
        cls.host_reports = {}
        for ep in (cls.ep_a, cls.ep_b):
            client = EvalClient(ep, request_timeout_s=30.0)
            cls.host_counters[ep] = client.snapshot()["snapshot"][
                "counters"
            ]
            cls.host_reports[ep] = client.load_report()
            client.close()

        cls.fleet_status_final = cls.router.fleet_status()
        cls.router_snapshot = obs.snapshot()
        with open(
            os.path.join(cls.outdir, "fleet.status.json"), "w"
        ) as f:
            json.dump(cls.fleet_status_final, f, indent=2, default=str)
        with open(
            os.path.join(cls.outdir, "fleet.trace.json"), "w"
        ) as f:
            f.write(cls.router.fleet_chrome_trace())
        with open(
            os.path.join(cls.outdir, "router.obs.json"), "w"
        ) as f:
            json.dump(cls.router_snapshot, f, indent=2)
        with open(
            os.path.join(cls.outdir, "router.trace.json"), "w"
        ) as f:
            f.write(obs.chrome_trace())
        with open(
            os.path.join(cls.outdir, "elastic.summary.json"), "w"
        ) as f:
            json.dump(
                {
                    "headroom_before_scaleup": cls.headroom_before,
                    "scale_delta": cls.scale_delta,
                    "rebalance_moved": cls.rebalance_moved,
                    "split_placement": cls.split_placement,
                    "late_tenant": cls.late_tenant,
                    "placement_after": cls.placement_after,
                },
                f,
                indent=2,
            )

        for tag in list(cls.procs):
            with open(os.path.join(cls.outdir, f"{tag}.stop"), "w"):
                pass
        for proc in cls.procs.values():
            try:
                proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        cls.router.close()
        _wait(
            lambda: not [
                t
                for t in threading.enumerate()
                if "torcheval-tpu-obs-" in t.name
                or t.name == "torcheval-tpu-router-rebalance"
            ]
        )
        cls.leaked_threads = [
            t.name
            for t in threading.enumerate()
            if "torcheval-tpu-obs-" in t.name
            or t.name == "torcheval-tpu-router-rebalance"
        ]
        obs.disable()

    def test_load_spike_starved_headroom(self):
        self.assertTrue(
            self.headroom_starved,
            f"headroom never starved: {self.headroom_before}",
        )
        self.assertLess(self.headroom_before, 0.55)

    def test_policy_scaled_up_one_real_host(self):
        self.assertEqual(self.scale_delta, 1)
        self.assertIn(self.ep_b, self.alive_after)
        self.assertTrue(self.b_fresh, "host B never reported fresh")

    def test_rebalance_moved_bounded_and_no_thrash(self):
        self.assertGreaterEqual(len(self.rebalance_moved), 1)
        self.assertLessEqual(len(self.rebalance_moved), 2)
        for t in self.rebalance_moved:
            self.assertEqual(self.placement_after[t], self.ep_b, t)
        # the immediate second pass under dwell hysteresis moved nothing
        self.assertEqual(self.rebalance_second_pass, [])

    def test_hot_tenant_split_spans_hosts(self):
        self.assertEqual(
            sorted(self.split_placement), ["hot", "hot@r1"]
        )
        self.assertEqual(len(set(self.split_placement.values())), 2)

    def test_chaos_killed_host_c_mid_scale_up(self):
        self.assertEqual(
            self.procs["hostC"].returncode, CHAOS_EXIT_CODE
        )
        self.assertNotIn(self.ep_c, self.alive_after)
        self.assertNotEqual(
            self.placement_after[self.late_tenant], self.ep_c
        )

    def test_results_bit_identical_to_fault_free_oracles(self):
        for t in COLD_TENANTS:
            self.assertEqual(
                self.results[t], _oracle(t, PHASE1 + PHASE2), t
            )
        # the split tenant merges its replica shards back exactly
        self.assertEqual(
            self.results["hot"], _oracle("hot", PHASE1 + PHASE2)
        )
        self.assertEqual(
            self.results[self.late_tenant],
            _oracle(self.late_tenant, 2),
        )

    def test_zero_sheds_and_drained_queues_after_scale_up(self):
        for ep, counters in self.host_counters.items():
            sheds = {
                k: v
                for k, v in counters.items()
                if k.startswith("serve.ingest.sheds{")
            }
            self.assertEqual(sheds, {}, ep)
        for ep, report in self.host_reports.items():
            self.assertEqual(report["queue"]["depth"], 0, ep)

    def test_router_recorded_rebalance_and_split_instruments(self):
        counters = self.router_snapshot["counters"]
        self.assertGreaterEqual(
            counters.get(
                "serve.router.migrations{reason=rebalance}", 0.0
            ),
            1.0,
        )
        self.assertEqual(
            counters.get("serve.router.splits{tenant=hot}"), 1.0
        )
        self.assertGreaterEqual(
            sum(
                v
                for k, v in counters.items()
                if k.startswith("serve.router.rebalances{")
            ),
            1.0,
        )
        gauges = self.router_snapshot["gauges"]
        self.assertIn("serve.fleet.headroom", gauges)

    def test_no_threads_leaked(self):
        self.assertEqual(self.leaked_threads, [])

    def test_artifacts_written(self):
        for name in (
            "fleet.status.json",
            "fleet.trace.json",
            "router.obs.json",
            "router.trace.json",
            "elastic.summary.json",
        ):
            self.assertTrue(
                os.path.getsize(os.path.join(self.outdir, name)) > 0,
                name,
            )


if __name__ == "__main__":
    unittest.main()

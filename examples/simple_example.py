"""Single-device training loop with a streaming metric.

TPU-native counterpart of the reference's ``examples/simple_example.py``
(``/root/reference/examples/simple_example.py:9-90``): a small MLP trained
with SGD while ``MulticlassAccuracy`` streams over the training batches. The
whole train-plus-metric step is one jitted function — model forward, loss,
gradients, optimizer update, and the metric's sufficient-statistic fold all
compile into a single XLA executable (the reference pays a Python round-trip
per batch for each of these).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics import MulticlassAccuracy

NUM_EPOCHS = 4
NUM_BATCHES = 16
BATCH_SIZE = 8
NUM_CLASSES = 2
LAYER_SIZES = (128, 64, 32, NUM_CLASSES)
LEARNING_RATE = 0.05


def init_params(key):
    params = []
    for d_in, d_out in zip(LAYER_SIZES[:-1], LAYER_SIZES[1:]):
        key, wkey = jax.random.split(key)
        params.append(
            {
                "w": jax.random.normal(wkey, (d_in, d_out)) * (2.0 / d_in) ** 0.5,
                "b": jnp.zeros((d_out,)),
            }
        )
    return params


def apply_mlp(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    final = params[-1]
    return x @ final["w"] + final["b"]


def loss_fn(params, x, y):
    logits = apply_mlp(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1)), logits


@jax.jit
def train_step(params, x, y):
    (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, x, y)
    params = jax.tree.map(lambda p, g: p - LEARNING_RATE * g, params, grads)
    return params, loss, logits


def main() -> None:
    key = jax.random.PRNGKey(42)
    params = init_params(key)
    data_key, label_key = jax.random.split(jax.random.PRNGKey(0))
    data = jax.random.normal(data_key, (NUM_BATCHES * BATCH_SIZE, 128))
    labels = jax.random.randint(
        label_key, (NUM_BATCHES * BATCH_SIZE,), 0, NUM_CLASSES
    )

    metric = MulticlassAccuracy()
    compute_frequency = 4

    for epoch in range(NUM_EPOCHS):
        for batch_idx in range(NUM_BATCHES):
            lo, hi = batch_idx * BATCH_SIZE, (batch_idx + 1) * BATCH_SIZE
            x, y = data[lo:hi], labels[lo:hi]
            params, loss, logits = train_step(params, x, y)
            metric.update(logits, y)
            if (batch_idx + 1) % compute_frequency == 0:
                print(
                    f"Epoch {epoch + 1}/{NUM_EPOCHS}, "
                    f"Batch {batch_idx + 1}/{NUM_BATCHES} --- "
                    f"loss: {float(loss):.4f}, acc: {float(metric.compute()):.4f}"
                )
        # reset the metric between epochs, as in the reference loop
        metric.reset()


if __name__ == "__main__":
    main()

"""Evaluating an existing PyTorch model with torcheval_tpu metrics.

The BASELINE goal names "a dlpack bridge for existing PyTorch eval loops":
this example is that loop, unchanged from how it would look against the
reference (``/root/reference/examples/simple_example.py``) except for the
metrics import. The torch model runs wherever torch runs (CPU here); its
output tensors feed ``update()`` directly — ``Metric._input`` bridges
zero-copy via dlpack where layouts allow and places the result on the
metric's device, so the evaluation math runs on the TPU/accelerator even
though the model is a torch module.

Run: python examples/torch_bridge_example.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import torch

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassF1Score,
)

NUM_CLASSES = 4
BATCH, N_BATCHES = 256, 24


class TinyTorchNet(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(16, 32),
            torch.nn.ReLU(),
            torch.nn.Linear(32, NUM_CLASSES),
        )

    def forward(self, x):
        return self.net(x)


def make_batch(rng, w_true):
    x = rng.standard_normal((BATCH, 16)).astype(np.float32)
    logits = x @ w_true
    y = logits.argmax(1)
    return torch.from_numpy(x), torch.from_numpy(y)


def main() -> None:
    rng = np.random.default_rng(0)
    w_true = rng.standard_normal((16, NUM_CLASSES)).astype(np.float32)
    model = TinyTorchNet()
    opt = torch.optim.Adam(model.parameters(), lr=1e-2)

    # brief training so the eval below measures something real
    for _ in range(200):
        x, y = make_batch(rng, w_true)
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()

    # ---- the eval loop: torch model, torcheval_tpu metrics -------------
    metrics = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=NUM_CLASSES),
            "f1": MulticlassF1Score(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    auroc = BinaryAUROC()  # one-vs-rest on class 0, streamed separately

    model.eval()
    with torch.no_grad():
        for _ in range(N_BATCHES):
            x, y = make_batch(rng, w_true)
            logits = model(x)
            # torch tensors go straight in: the bridge converts once and
            # places on the metric's device
            metrics.update(logits, y)
            auroc.update(
                torch.softmax(logits, dim=1)[:, 0], (y == 0).float()
            )

    results = metrics.compute()
    print(f"accuracy: {float(results['acc']):.4f}")
    print(f"f1_macro: {float(results['f1']):.4f}")
    print(f"auroc(class 0): {float(auroc.compute()):.4f}")


if __name__ == "__main__":
    main()

"""Data-parallel evaluation over a device mesh.

TPU-native counterpart of the reference's ``examples/distributed_example.py``
(``/root/reference/examples/distributed_example.py:14-148``), which launches
one process per GPU under torch.distributed.elastic, wraps the model in DDP,
and syncs metric state with ``sync_and_compute`` (pickled-object gather over
NCCL/Gloo).

The TPU version needs none of that machinery: ONE process drives the whole
mesh. Batches are global arrays sharded along the mesh's data axis, metric
state is replicated, and XLA inserts the psum collectives over ICI inside
the same compiled computation as the update math. ``compute()`` is globally
correct on every chip with no sync step.

On a multi-host pod, run this same script on every host after
``torcheval_tpu.parallel.init_from_env()`` (reads COORDINATOR_ADDRESS / the
torch-elastic MASTER_ADDR+RANK+WORLD_SIZE vars, or auto-detects on Cloud
TPU) — ``jax.devices()`` then spans all hosts and each host feeds its local
shard (``jax.make_array_from_process_local_data``); use
``torcheval_tpu.metrics.toolkit.sync_and_compute`` only for the
multi-controller pattern where each process keeps a *local* metric.

Run single-host with a simulated 8-chip mesh:
    JAX_PLATFORMS=cpu python examples/distributed_example.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # a site plugin may pin jax_platforms programmatically, so the env var
    # alone is not enough — override through jax.config before backend init
    from torcheval_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(8)

import numpy as np

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MulticlassAccuracy,
    MulticlassF1Score,
)
from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh

NUM_BATCHES = 64
BATCH_SIZE = 256
NUM_CLASSES = 4


def main() -> None:
    mesh = data_parallel_mesh()
    print(f"mesh: {mesh.devices.size} devices over axis {mesh.axis_names}")

    # metrics with the same (scores, labels) signature share one evaluator
    classification = ShardedEvaluator(
        {
            "accuracy": MulticlassAccuracy(num_classes=NUM_CLASSES),
            "f1_macro": MulticlassF1Score(
                num_classes=NUM_CLASSES, average="macro"
            ),
        },
        mesh=mesh,
    )
    auroc = ShardedEvaluator(BinaryAUROC(), mesh=mesh)

    rng = np.random.default_rng(2023)
    for _ in range(NUM_BATCHES):
        scores = rng.random((BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
        labels = rng.integers(0, NUM_CLASSES, BATCH_SIZE)
        classification.update(scores, labels)
        # one-vs-rest margin for class 0 feeds the binary AUROC
        auroc.update(scores[:, 0], (labels == 0).astype(np.float32))

    results = classification.compute()
    print(f"accuracy: {float(results['accuracy']):.4f}")
    print(f"f1_macro: {float(results['f1_macro']):.4f}")
    print(f"auroc:    {float(auroc.compute()):.4f}")


if __name__ == "__main__":
    main()

"""All benchmark configs — thin wrapper over the driver bench.

Run: python benchmarks/run_all.py  (real chip; ~12-18 min including the 1B
leg and the 4-process sync worlds; first run adds one-time XLA compiles
that land in the persistent .jax_cache/)

Every record and its methodology live in ``bench.py`` at the repo root (the
driver entry point); this file exists so `benchmarks/` stays a discoverable
home for perf work.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from bench import main

if __name__ == "__main__":
    main()

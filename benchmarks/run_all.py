"""All five BASELINE.json benchmark configs. Prints one JSON line each.

Run: python benchmarks/run_all.py  (real chip; ~2-4 min)

Each record: {"config", "metric", "value", "unit", "vs_baseline"} where
vs_baseline is the speedup over the reference torcheval implementation
(/root/reference) on torch CPU — the only backend it runs on here — on the
same workload; null when the reference leg cannot run.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def _run_tpu(fn, *args):
    fn(*args)  # warmup/compile
    t0 = time.perf_counter()
    fn(*args)
    return time.perf_counter() - t0


def _run_ref(fn, *args):
    try:
        fn(*args)
        t0 = time.perf_counter()
        fn(*args)
        return time.perf_counter() - t0
    except Exception:
        return None


def _report(config, preds, tpu_s, ref_s):
    print(
        json.dumps(
            {
                "config": config,
                "metric": "preds_per_sec",
                "value": round(preds / tpu_s, 1),
                "unit": "preds/s",
                "vs_baseline": round(ref_s / tpu_s, 3) if ref_s else None,
            }
        )
    )


def config1_simple_accuracy():
    """MulticlassAccuracy, num_classes=5, simple_example-style streaming."""
    import jax

    from torcheval_tpu.metrics import MulticlassAccuracy

    rng = np.random.default_rng(0)
    n_batches, batch = 200, 8192
    scores = rng.random((batch, 5)).astype(np.float32)
    labels = rng.integers(0, 5, batch)
    js, jl = jax.device_put(scores), jax.device_put(labels)
    jax.block_until_ready((js, jl))

    def tpu():
        m = MulticlassAccuracy(num_classes=5)
        for _ in range(n_batches):
            m.update(js, jl)
        return float(m.compute())

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import MulticlassAccuracy as RefAcc

        ts, tl = torch.from_numpy(scores), torch.from_numpy(labels)
        m = RefAcc()
        for _ in range(n_batches):
            m.update(ts, tl)
        return float(m.compute())

    _report(
        "1_multiclass_accuracy_c5",
        n_batches * batch,
        _run_tpu(tpu),
        _run_ref(ref),
    )


def config2_auroc_auprc():
    """BinaryAUROC + BinaryAUPRC, functional API, 10M logits."""
    import jax

    import torcheval_tpu.metrics.functional as F

    n = 10_000_000
    key = jax.random.PRNGKey(0)
    x = jax.random.uniform(key, (n,))
    t = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) > 0.5).astype(np.float32)
    jax.block_until_ready((x, t))

    def tpu():
        return float(F.binary_auroc(x, t)), float(F.binary_auprc(x, t))

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics.functional import binary_auroc as ref_auroc

        tx = torch.from_numpy(np.asarray(x))
        tt = torch.from_numpy(np.asarray(t))
        # the reference snapshot has no binary_auprc; time AUROC twice to
        # keep the work comparable
        return float(ref_auroc(tx, tt)), float(ref_auroc(tx, tt))

    _report("2_auroc_auprc_10M", 2 * n, _run_tpu(tpu), _run_ref(ref))


def config3_confusion_f1_imagenet():
    """MulticlassConfusionMatrix + F1, num_classes=1000, ImageNet-eval scale."""
    import jax

    from torcheval_tpu.metrics import MulticlassConfusionMatrix, MulticlassF1Score

    n_batches, batch, c = 13, 100_000, 1000  # 1.3M preds ~ ImageNet val x26
    key = jax.random.PRNGKey(0)
    pred = jax.random.randint(key, (batch,), 0, c, np.int32)
    label = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, c, np.int32)
    jax.block_until_ready((pred, label))

    def tpu():
        cm = MulticlassConfusionMatrix(c)
        f1 = MulticlassF1Score(num_classes=c, average="macro")
        for _ in range(n_batches):
            cm.update(pred, label)
            f1.update(pred, label)
        return np.asarray(cm.compute()).sum(), float(f1.compute())

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import MulticlassF1Score as RefF1

        # reference snapshot has no confusion-matrix metric; F1 only
        tp, tl = torch.from_numpy(np.asarray(pred)), torch.from_numpy(
            np.asarray(label)
        )
        f1 = RefF1(num_classes=c, average="macro")
        for _ in range(n_batches):
            f1.update(tp, tl)
        return float(f1.compute())

    _report(
        "3_confusion_f1_c1000", n_batches * batch, _run_tpu(tpu), _run_ref(ref)
    )


def config4_topk_multilabel():
    """TopKMultilabelAccuracy, k=5, num_labels=10k."""
    import jax

    from torcheval_tpu.metrics import TopKMultilabelAccuracy

    n_batches, batch, labels = 4, 8192, 10_000
    key = jax.random.PRNGKey(0)
    scores = jax.random.uniform(key, (batch, labels))
    target = (jax.random.uniform(jax.random.PRNGKey(1), (batch, labels)) > 0.999).astype(np.int32)
    jax.block_until_ready((scores, target))

    def tpu():
        m = TopKMultilabelAccuracy(k=5, criteria="contain")
        for _ in range(n_batches):
            m.update(scores, target)
        return float(m.compute())

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import TopKMultilabelAccuracy as RefTopK

        ts = torch.from_numpy(np.asarray(scores))
        tt = torch.from_numpy(np.asarray(target).astype(np.float32))
        m = RefTopK(k=5, criteria="contain")
        for _ in range(n_batches):
            m.update(ts, tt)
        return float(m.compute())

    _report(
        "4_topk_multilabel_k5_L10k",
        n_batches * batch,
        _run_tpu(tpu),
        _run_ref(ref),
    )


def config5_sharded_sync():
    """sync_and_compute-equivalent: MulticlassAccuracy over the device mesh
    (the implicit-SPMD sync path; 32-rank ICI on a pod, every local device
    here)."""
    import jax

    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh

    n_batches, batch = 50, 65536
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(0)
    scores = rng.random((batch, 5)).astype(np.float32)
    labels = rng.integers(0, 5, batch)

    def tpu():
        ev = ShardedEvaluator(MulticlassAccuracy(num_classes=5), mesh=mesh)
        for _ in range(n_batches):
            ev.update(scores, labels)
        return float(ev.compute())

    _report(
        f"5_sharded_sync_accuracy_{mesh.devices.size}dev",
        n_batches * batch,
        _run_tpu(tpu),
        None,  # reference needs a multi-GPU NCCL cluster; not runnable here
    )


if __name__ == "__main__":
    config1_simple_accuracy()
    config2_auroc_auprc()
    config3_confusion_f1_imagenet()
    config4_topk_multilabel()
    config5_sharded_sync()

"""Minimal ``torchtnt.utils`` stand-in for the reference benchmark leg.

``torchtnt`` is not installed in this image; the reference's toolkit
(``/root/reference/torcheval/metrics/toolkit.py:16``) imports exactly one
name from it — ``PGWrapper`` — and calls exactly three methods, each a
one-line delegation to ``torch.distributed`` (which is what the real
torchtnt ``PGWrapper`` does for an initialized process group). This shim
provides those three so the reference leg of the config-5 sync benchmark
can run unmodified; it adds no overhead and no behavior of its own.
"""

import torch.distributed as dist


class PGWrapper:
    def __init__(self, pg=None):
        self.pg = pg

    def get_rank(self) -> int:
        return dist.get_rank(group=self.pg)

    def get_world_size(self) -> int:
        return dist.get_world_size(group=self.pg)

    def broadcast_object_list(self, obj_list, src: int = 0) -> None:
        dist.broadcast_object_list(obj_list, src=src, group=self.pg)

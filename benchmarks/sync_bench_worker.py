"""Worker for the config-5 cross-process sync benchmark (both sides).

One rank of a 4-process ``sync_and_compute`` world — BASELINE config 5's
workload (stream ``MulticlassAccuracy`` shards, then sync across ranks) run
apples-to-apples on the only fabric both frameworks share in this
environment: CPU processes on one host.

* ``mode=tpu`` — this framework: the rank joins a ``jax.distributed`` CPU
  world through ``init_from_env`` (same bootstrap a torchrun script would
  drive) and syncs through the explicit typed-collective path
  (``torcheval_tpu/metrics/toolkit.py``).
* ``mode=ref`` — the reference: the rank joins a ``torch.distributed`` Gloo
  world and syncs through its object-pickle gather
  (``/root/reference/torcheval/metrics/toolkit.py:24-78``).

Each rank times ``k`` runs of (reset → n_batches updates → sync_and_compute
on every rank) after one warmup run, and writes its per-run times to
``<outdir>/<mode>_rank<r>.json``. The parent (``bench.py``) scores the run
by the SLOWEST rank per repeat (the sync is a barrier: the world's
throughput is the straggler's) and takes the MIN across repeats (see
bench.py's scoring comment: on this timeshared single-core host a median
would be poisoned by whichever framework's repeats co-tenant bursts land
on). Process startup
and world bootstrap are excluded on both sides — the measured quantity is
steady-state update+sync cost, not interpreter spawn.

Run: python sync_bench_worker.py <mode> <rank> <world> <port> <outdir>
                                 <n_batches> <batch>
"""

import json
import os
import sys
import time

import numpy as np

NUM_CLASSES = 5
REPEATS = 7  # min-of-k scoring upstream; more repeats = more clean windows


def _shard(rank: int, batch: int):
    rng = np.random.default_rng(1000 + rank)
    scores = rng.random((batch, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, batch).astype(np.int64)
    return scores, labels


def _time_runs(run, repeats=REPEATS):
    run()  # warmup: compiles / allocates outside the timed region
    times, result = [], None
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = run()
        times.append(time.perf_counter() - t0)
    return times, result


def main() -> None:
    mode, rank, world, port, outdir, n_batches, batch = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
        sys.argv[5],
        int(sys.argv[6]),
        int(sys.argv[7]),
    )
    scores, labels = _shard(rank, batch)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    if mode == "tpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        # same persistent compile cache as bench._jax(): without it every
        # rank recompiles its fold/sync jits on each bench invocation
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(repo, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        os.environ["MASTER_ADDR"] = "localhost"
        os.environ["MASTER_PORT"] = port
        os.environ["WORLD_SIZE"] = str(world)
        os.environ["RANK"] = str(rank)
        sys.path.insert(0, repo)
        from torcheval_tpu.parallel import init_from_env

        init_from_env()
        import jax.numpy as jnp

        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.metrics.toolkit import sync_and_compute

        # flight-recorder leg (bench --trace/--smoke): the parent cannot see
        # sync rounds — they happen HERE, in the worker processes — so when
        # asked it records this rank's obs timeline and ships the events
        # back for the parent to merge rank-tagged into the exported Chrome
        # trace. Opt-in only: recording adds spans inside the timed runs.
        record_obs = bool(os.environ.get("TORCHEVAL_TPU_BENCH_OBS"))
        if record_obs:
            from torcheval_tpu import obs

            obs.enable()

        js, jl = jnp.asarray(scores), jnp.asarray(labels)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES)

        def run():
            m.reset()
            for _ in range(n_batches):
                m.update(js, jl)
            # every rank receives: the result must land wherever the eval
            # loop runs, same contract the reference leg is given below.
            # device_get materializes the result INSIDE the timed region —
            # the ref leg's torch compute is eager, so leaving this value
            # unmaterialized would exclude the fold+compute tail from this
            # side only (same barrier policy as bench._time)
            return jax.device_get(sync_and_compute(m, recipient_rank="all"))

    elif mode == "ref":
        sys.path.insert(0, "/root/reference")
        # torchtnt is not installed here; the reference toolkit needs only
        # PGWrapper's three one-line delegations (see _torchtnt_shim)
        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "_torchtnt_shim"),
        )
        import torch
        import torch.distributed as dist

        os.environ["MASTER_ADDR"] = "localhost"
        os.environ["MASTER_PORT"] = port
        dist.init_process_group("gloo", rank=rank, world_size=world)
        from torcheval.metrics import MulticlassAccuracy
        from torcheval.metrics.toolkit import sync_and_compute

        ts, tl = torch.from_numpy(scores), torch.from_numpy(labels)
        m = MulticlassAccuracy(num_classes=NUM_CLASSES)

        def run():
            m.reset()
            for _ in range(n_batches):
                m.update(ts, tl)
            return sync_and_compute(m, recipient_rank="all")

    else:
        raise SystemExit(f"unknown mode {mode!r}")

    times, value = _time_runs(run)
    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"{mode}_rank{rank}.json"), "w") as f:
        json.dump({"rank": rank, "times": times, "value": float(value)}, f)
    if mode == "tpu" and os.environ.get("TORCHEVAL_TPU_BENCH_OBS"):
        from torcheval_tpu import obs

        with open(
            os.path.join(outdir, f"{mode}_rank{rank}_events.json"), "w"
        ) as f:
            json.dump({"rank": rank, "events": obs.timeline_events()}, f)


if __name__ == "__main__":
    main()

"""Generate per-module API reference pages from docstrings.

The reference ships Sphinx ``automodule`` pages for every module
(``/root/reference/docs/source/index.rst:1-27``, ``torcheval.metrics.rst``).
This is the equivalent without a Sphinx dependency (not in this image): walk
the public surface with ``inspect`` and emit one markdown page per module
under ``docs/api/``, plus an index.

Usage:
    python docs/generate_api.py          # (re)write docs/api/*.md
    python docs/generate_api.py --check  # exit 1 if pages are stale (CI)
"""

from __future__ import annotations

import importlib
import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "api")

MODULES = [
    "torcheval_tpu.metrics",
    "torcheval_tpu.metrics.functional",
    "torcheval_tpu.metrics.ranking",
    "torcheval_tpu.metrics.toolkit",
    "torcheval_tpu.metrics.collection",
    "torcheval_tpu.metrics.sliced",
    "torcheval_tpu.metrics.deferred",
    "torcheval_tpu.obs",
    "torcheval_tpu.parallel",
    "torcheval_tpu.resilience",
    "torcheval_tpu.serve",
    "torcheval_tpu.serve.ingest",
    "torcheval_tpu.sketch",
    "torcheval_tpu.utils.quant",
    "torcheval_tpu.tools",
    "torcheval_tpu.ops",
    "torcheval_tpu.ops.scatter",
    "torcheval_tpu.utils.test_utils",
]


def _signature(obj) -> str:
    import enum

    if inspect.isclass(obj) and issubclass(obj, enum.Enum):
        # Enum "signatures" are EnumType internals and differ per Python
        # minor version; normalise so regeneration never churns these lines
        return "(value)"
    try:
        import re

        # sentinel defaults repr as `<object object at 0x7f...>` — a fresh
        # address every process, which made --check churn on every run;
        # normalise the address away
        return re.sub(
            r"0x[0-9a-f]+", "0x...", str(inspect.signature(obj))
        )
    except (TypeError, ValueError):
        return "(...)"


def _doc(obj) -> str:
    return inspect.getdoc(obj) or "*(no docstring)*"


def _member_page(name: str, obj) -> list:
    lines = []
    if inspect.isclass(obj):
        lines.append(f"### class `{name}{_signature(obj)}`\n")
        lines.append(_doc(obj) + "\n")
        for mname, meth in sorted(vars(obj).items()):
            if mname.startswith("_") or not callable(meth):
                continue
            fn = inspect.unwrap(getattr(obj, mname, meth))
            lines.append(f"#### `{name}.{mname}{_signature(fn)}`\n")
            lines.append(_doc(fn) + "\n")
    elif callable(obj):
        lines.append(f"### `{name}{_signature(obj)}`\n")
        lines.append(_doc(obj) + "\n")
    return lines


def render_module(modname: str) -> str:
    mod = importlib.import_module(modname)
    exported = getattr(mod, "__all__", None)
    if exported is None:
        exported = [
            n
            for n, o in sorted(vars(mod).items())
            if not n.startswith("_")
            and (inspect.isclass(o) or inspect.isfunction(o))
            and getattr(o, "__module__", "").startswith("torcheval_tpu")
        ]
    lines = [f"# `{modname}`\n", _doc(mod) + "\n", "---\n"]
    for name in exported:
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        lines.extend(_member_page(name, obj))
    return "\n".join(lines) + "\n"


def render_index() -> str:
    lines = [
        "# API reference\n",
        "Generated from docstrings by `docs/generate_api.py` "
        "(the Sphinx-automodule equivalent for this tree; regenerate after "
        "changing public surface).\n",
    ]
    for modname in MODULES:
        fname = modname.replace(".", "_") + ".md"
        lines.append(f"- [`{modname}`]({fname})")
    return "\n".join(lines) + "\n"


def main() -> int:
    check = "--check" in sys.argv
    os.makedirs(OUT, exist_ok=True)
    pages = {"index.md": render_index()}
    for modname in MODULES:
        pages[modname.replace(".", "_") + ".md"] = render_module(modname)
    stale = []
    for fname, content in pages.items():
        path = os.path.join(OUT, fname)
        old = open(path).read() if os.path.exists(path) else None
        if old != content:
            stale.append(fname)
            if not check:
                with open(path, "w") as f:
                    f.write(content)
    if check and stale:
        print(f"stale API pages: {stale}; run python docs/generate_api.py")
        return 1
    print(f"{'checked' if check else 'wrote'} {len(pages)} pages under docs/api/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

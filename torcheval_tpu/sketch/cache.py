"""Resident-sketch state machinery for the ``approx=`` metric mode.

The metric-side glue between the pure fold/compute math
(``sketch/histogram.py``) and the sample-cache metric classes: the opt-in
knob resolution (ctor arg + ``TORCHEVAL_TPU_APPROX`` env), the staged-fold
cadence (update stays an O(1) host append; one jitted fold program folds the
staging cache into the resident histogram every ``SKETCH_FOLD_ROWS`` rows,
the ``_CompactingCacheLifecycle`` cadence shape), the idempotent
compute-from-parts programs (compute never mutates state — leftover staged
rows fold into a *temporary* histogram inside the compute program), and a
mixin for value-sketch metrics (``HitRate`` / ``ReciprocalRank`` / ``Cat``).

State registered by this module is deliberately plain — int32 SUM count
arrays plus an int32 SUM NaN lane — so approx metrics ride ``merge_state``
(bucket add = exact merge), the two-round sync wire (SUM lanes, which the
ISSUE 12/13 codecs narrow- or bucket-encode), ``resilience.snapshot`` and
the serve evict/reattach machinery with zero new protocol.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

# NOTE: torcheval_tpu.metrics.state is imported lazily inside the state
# registration methods below — the metric modules import this package at
# module level (Cat/HitRate/... need the mixins), so a module-level import
# of anything under torcheval_tpu.metrics here would be circular whenever
# the sketch package loads first.
from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.sketch.buckets import (
    DEFAULT_BUCKET_BITS,
    MAX_BUCKET_BITS,
    bucket_index,
    check_bucket_bits,
)
from torcheval_tpu.sketch.histogram import (
    _desc_reps,
    auprc_from_hist,
    auroc_from_hist,
    counts_exactness_flag,
    mc_score_hist_fold,
    prc_points_from_hist,
    score_hist_fold,
    value_hist_fold,
)

# staging-cache fold cadence: updates append host-side (zero dispatch) and
# one fold program runs per this many rows, so resident memory is bounded by
# O(buckets) + O(SKETCH_FOLD_ROWS) regardless of stream length
SKETCH_FOLD_ROWS = 65536

_APPROX_ENV = "TORCHEVAL_TPU_APPROX"


def resolve_approx(
    approx, *, default_bits: int = DEFAULT_BUCKET_BITS
) -> Optional[int]:
    """Resolve the ``approx=`` knob to ``bucket_bits`` (or ``None`` = exact).

    ``None`` defers to ``TORCHEVAL_TPU_APPROX`` (``0``/unset = off, ``1`` =
    on with the family default, an integer = bucket count); ``False`` forces
    exact even with the env set; ``True`` = family default; an int is the
    bucket count (a power of two — the bucket id is a bit prefix)."""
    if approx is None:
        env = os.environ.get(_APPROX_ENV, "0").strip().lower()
        if env in ("", "0", "false", "off"):
            return None
        if env in ("1", "true", "on"):
            return default_bits
        try:
            approx = int(env)
        except ValueError:
            raise ValueError(
                f"{_APPROX_ENV} must be 0/1/true/false or a bucket count, "
                f"got {env!r}."
            ) from None
    if approx is False:
        return None
    if approx is True:
        return default_bits
    count = int(approx)
    bits = count.bit_length() - 1
    if count <= 0 or (1 << bits) != count:
        raise ValueError(
            f"approx bucket count must be a power of two, got {count}."
        )
    return check_bucket_bits(bits)


def _count_fold(kind: str, rows: int) -> None:
    if _obs.enabled():
        _obs.counter("sketch.folds", kind=kind)
        _obs.counter("sketch.folded_rows", rows, kind=kind)


# ------------------------------------------------- jitted fold/compute parts
# All staged caches arrive as lists (jit retraces per list length, the same
# bounded-signature regime as the compaction programs in
# classification/auroc.py). ``bits`` (and ``num_classes``) are static.
@partial(jax.jit, static_argnums=5)
def score_fold_parts(raw_s, raw_t, tp, fp, nan_acc, bits):
    """Fold staged binary batches into the resident ``(tp, fp)`` sketch."""
    dtp, dfp, nan = score_hist_fold(
        jnp.concatenate(raw_s), jnp.concatenate(raw_t), bits
    )
    return tp + dtp, fp + dfp, nan_acc + nan


@partial(jax.jit, static_argnums=(5, 6))
def mc_score_fold_parts(raw_s, raw_t, tp, fp, nan_acc, bits, num_classes):
    dtp, dfp, nan = mc_score_hist_fold(
        jnp.concatenate(raw_s, axis=0),
        jnp.concatenate(raw_t),
        bits,
        num_classes,
    )
    return tp + dtp, fp + dfp, nan_acc + nan


@partial(jax.jit, static_argnums=3)
def value_fold_parts(cache, counts, nan_acc, bits):
    """Fold staged value batches into the resident count sketch."""
    dc, nan = value_hist_fold(
        jnp.concatenate([c.reshape(-1) for c in cache]), bits
    )
    return counts + dc, nan_acc + nan


def _folded_score_parts(raw_s, raw_t, tp, fp, nan_acc, bits):
    """Traced helper: resident sketch plus any staged leftovers, WITHOUT
    mutating state (compute-path use)."""
    if raw_s:
        return score_fold_parts(raw_s, raw_t, tp, fp, nan_acc, bits)
    return tp, fp, nan_acc


@partial(jax.jit, static_argnums=5)
def sketch_auroc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits):
    tp, fp, nan = _folded_score_parts(raw_s, raw_t, tp, fp, nan_acc, bits)
    return auroc_from_hist(tp, fp, bits), nan, counts_exactness_flag(tp, fp)


@partial(jax.jit, static_argnums=5)
def sketch_auprc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits):
    tp, fp, nan = _folded_score_parts(raw_s, raw_t, tp, fp, nan_acc, bits)
    return auprc_from_hist(tp, fp, bits), nan, counts_exactness_flag(tp, fp)


@partial(jax.jit, static_argnums=5)
def sketch_prc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits):
    tp, fp, nan = _folded_score_parts(raw_s, raw_t, tp, fp, nan_acc, bits)
    precision, recall, nonempty = prc_points_from_hist(tp, fp)
    return precision, recall, nonempty, nan, counts_exactness_flag(tp, fp)


def _folded_mc_parts(raw_s, raw_t, tp, fp, nan_acc, bits, num_classes):
    if raw_s:
        return mc_score_fold_parts(
            raw_s, raw_t, tp, fp, nan_acc, bits, num_classes
        )
    return tp, fp, nan_acc


@partial(jax.jit, static_argnums=(5, 6))
def sketch_mc_auroc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits, num_classes):
    tp, fp, nan = _folded_mc_parts(
        raw_s, raw_t, tp, fp, nan_acc, bits, num_classes
    )
    per_class = jax.vmap(lambda a, b: auroc_from_hist(a, b, bits))(tp, fp)
    return per_class, nan, counts_exactness_flag(tp, fp)


@partial(jax.jit, static_argnums=(5, 6))
def sketch_mc_auprc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits, num_classes):
    tp, fp, nan = _folded_mc_parts(
        raw_s, raw_t, tp, fp, nan_acc, bits, num_classes
    )
    per_class = jax.vmap(lambda a, b: auprc_from_hist(a, b, bits))(tp, fp)
    return per_class, nan, counts_exactness_flag(tp, fp)


@partial(jax.jit, static_argnums=(5, 6))
def sketch_mc_prc_from_parts(raw_s, raw_t, tp, fp, nan_acc, bits, num_classes):
    tp, fp, nan = _folded_mc_parts(
        raw_s, raw_t, tp, fp, nan_acc, bits, num_classes
    )
    precision, recall, nonempty = jax.vmap(prc_points_from_hist)(tp, fp)
    return precision, recall, nonempty, nan, counts_exactness_flag(tp, fp)


@partial(jax.jit, static_argnums=3)
def value_counts_from_parts(cache, counts, nan_acc, bits):
    if cache:
        counts, nan_acc = value_fold_parts(cache, counts, nan_acc, bits)
    return counts, nan_acc, counts_exactness_flag(counts)


# ---------------------------------------------------- shared loud failures
def raise_sketch_nan(nan, noun: str = "value(s)") -> None:
    """The ONE definition of the loud-NaN contract (review finding: three
    verbatim copies drifted-in-waiting). One int32 scalar host read."""
    dropped = int(nan)
    if dropped:
        raise ValueError(
            f"{dropped} {noun} with NaN scores reached the sketch; NaN "
            "has no order and cannot be bucketed (the exact kernels "
            "would count them). Filter NaNs before update() or use "
            "approx=False."
        )


def raise_sketch_overflow(flag) -> None:
    """Raise when :func:`histogram.counts_exactness_flag` tripped: the
    stream outgrew the int32-exact range (>= ~2.1e9 total counts, or a
    wrapped bucket) and curve/quantile computes would silently wrap their
    cumulative sums. Failing closed here is the unbounded-stream mode's
    exactness edge — shard the stream across replicas (merge is exact)
    before any single sketch accumulates 2^31 samples."""
    if bool(flag):
        raise ValueError(
            "sketch count state exceeded the int32-exact range (~2.1e9 "
            "total samples per sketch, or a wrapped bucket): curve and "
            "quantile computes would silently wrap. Reset or split the "
            "stream across replicas (sketch merges are exact) before a "
            "single sketch accumulates 2^31 samples."
        )


# ---------------------------------------------- shared state registration
def register_score_sketch_states(metric, bits: int, num_classes) -> None:
    """The ONE definition of the resident score-sketch state schema
    (names, shapes, dtype, reduction) — used by the PRC/value mixins AND
    the compacting curve lifecycle so the schemas can never diverge."""
    from torcheval_tpu.metrics.state import Reduction, zeros_state

    shape = (1 << bits,) if num_classes is None else (num_classes, 1 << bits)
    metric._add_state(
        "sketch_tp",
        zeros_state(shape, dtype=jnp.int32),
        reduction=Reduction.SUM,
    )
    metric._add_state(
        "sketch_fp",
        zeros_state(shape, dtype=jnp.int32),
        reduction=Reduction.SUM,
    )
    metric._add_state(
        "sketch_nan_dropped",
        zeros_state((), dtype=jnp.int32),
        reduction=Reduction.SUM,
    )


def merge_score_sketch_states(metric, others) -> None:
    """Bucket-add other replicas' resident score sketches into
    ``metric`` (the exact merge; staged rows travel via the cache
    merge)."""
    for other in others:
        metric.sketch_tp = metric.sketch_tp + jax.device_put(
            other.sketch_tp, metric.device
        )
        metric.sketch_fp = metric.sketch_fp + jax.device_put(
            other.sketch_fp, metric.device
        )
        metric.sketch_nan_dropped = (
            metric.sketch_nan_dropped
            + jax.device_put(other.sketch_nan_dropped, metric.device)
        )


# ---------------------------------------------- serve per-tenant approx knob
def _drop_state(metric, name: str) -> None:
    metric._state_name_to_default.pop(name, None)
    metric._state_name_to_reduction.pop(name, None)
    getattr(metric, "_cache_dtypes", {}).pop(name, None)
    if hasattr(metric, name):
        delattr(metric, name)


def _require_fresh(metric, *state_names: str) -> None:
    """The switchable-instance precondition: NO streamed data anywhere —
    raw caches, the cached-sample counter, OR the named compacted states
    (a fully-compacted curve metric has ``inputs=[]`` and
    ``_cached_samples=0`` while its summary_* states hold every sample; a
    schema switch would silently drop them)."""
    held = bool(getattr(metric, "inputs", None)) or bool(
        getattr(metric, "_cached_samples", 0)
    )
    for name in state_names:
        held = held or bool(getattr(metric, name, None))
    if held:
        raise ValueError(
            "approx= cannot be applied to a metric that already holds "
            "streamed samples (the registered state schema is part of "
            "checkpoints and sync lanes); construct it with approx= "
            "instead."
        )


def _score_sketch_bits(metric, approx):
    """Shared validation half of the two score-sketch families: the
    already-streamed guard runs in the caller (state names differ), this
    resolves ``num_classes`` + the family-default bucket bits."""
    num_classes = getattr(metric, "num_classes", None)
    is_mc = hasattr(metric, "num_classes")
    if is_mc and num_classes is None:
        raise ValueError(
            "approx= needs num_classes on the multiclass curve metrics "
            "(the (C, buckets) sketch state cannot be sized without it)."
        )
    bits = resolve_approx(
        approx,
        default_bits=DEFAULT_MC_BUCKET_BITS if is_mc else DEFAULT_BUCKET_BITS,
    )
    return bits, num_classes


def enable_metric_approx(metric, approx, *, dry_run: bool = False) -> bool:
    """Switch a FRESH approx-capable metric into sketch mode after
    construction — the serve per-tenant ``approx`` knob (ROADMAP 4(c)):
    ``daemon.attach(..., approx=...)`` admits a tenant whose curve metrics
    were built exact and opts them into resident-sketch state in one place,
    whether they arrived as live instances or through the wire metric spec.

    Returns ``True`` when the metric's class HAS an approx mode (the sketch
    state is then registered, exactly as the constructor's ``approx=``
    would have), ``False`` when it does not (counter/regression metrics —
    their state is already bounded; the caller decides whether that rejects
    the spec). Raises ``ValueError`` when the class supports approx but
    THIS instance cannot switch: it already holds streamed samples — raw
    cache OR compacted summary (the registered state schema is part of
    checkpoints and sync lanes; it must never change mid-stream) — or its
    configuration cannot size the sketch (``Cat(dim != 0)``, a multiclass
    curve without ``num_classes``).

    ``dry_run=True`` runs EVERY check and returns the same value but
    mutates nothing — callers switching a whole collection validate every
    member first, then apply (a rejection must never leave earlier members
    half-switched; ``serve/daemon.py::attach``).

    ``approx`` follows the constructors' contract (``True`` = family
    default bucket count, an int = bucket count); ``False``/``None`` are a
    no-op — pass the knob only when the tenant asked for it."""
    if approx is None or approx is False:
        return True
    # --- always-approximate metrics (Quantile): the knob is already satisfied
    if getattr(metric, "_always_approx", False):
        return True
    # --- compacting curve lifecycle (Binary/Multiclass AUROC & AUPRC):
    # exact-summary states swap for the resident (tp, fp) histograms
    if hasattr(metric, "_compaction_threshold") and hasattr(metric, "_compact"):
        if metric._sketch_enabled():
            return True
        _require_fresh(
            metric, "summary_scores", "summary_tp", "summary_fp"
        )
        bits, num_classes = _score_sketch_bits(metric, approx)
        if bits is None or dry_run:
            return True
        for name in ("summary_scores", "summary_tp", "summary_fp",
                     "summary_nan_dropped"):
            _drop_state(metric, name)
        metric._sketch_bits = bits
        metric._sketch_classes = num_classes
        if metric._compaction_threshold is None:
            metric._compaction_threshold = SKETCH_FOLD_ROWS
        register_score_sketch_states(metric, bits, num_classes)
        return True
    # --- PRC-family score sketch
    if isinstance(metric, ScoreSketchCacheMixin):
        if metric._sketch_enabled():
            return True
        _require_fresh(metric)
        bits, num_classes = _score_sketch_bits(metric, approx)
        if bits is not None and not dry_run:
            metric._init_score_sketch(bits, num_classes=num_classes)
        return True
    # --- value sketch (HitRate / ReciprocalRank / Cat)
    if isinstance(metric, ValueSketchCacheMixin):
        if metric._sketch_enabled():
            return True
        cache_name = "scores" if hasattr(metric, "scores") else "inputs"
        if getattr(metric, "dim", 0) != 0:
            raise ValueError(
                "approx= requires dim=0: the sketch pools elements and "
                "cannot represent higher-dimension concat structure."
            )
        if getattr(metric, cache_name):
            raise ValueError(
                "approx= cannot be applied to a metric that already holds "
                "streamed samples (the registered state schema is part of "
                "checkpoints and sync lanes); construct it with approx= "
                "instead."
            )
        bits = resolve_approx(approx, default_bits=DEFAULT_BUCKET_BITS)
        if bits is not None and not dry_run:
            metric._init_value_sketch(bits, cache_name)
        return True
    return False


# ------------------------------------------------- sliced sketch folds (ISSUE 15)
# Per-slice score sketches for SlicedMetricCollection: every slice keeps its
# own (tp, fp) bucket histogram, folded by ONE combined-index segment_sum
# (slice_row * buckets + bucket) so the scratch stays O(batch) instead of
# O(batch x buckets) — the shape the generic per-sample vmap fold would pay.
# The per-slice state is O(buckets) int32, so a million cohorts of curve
# state survive on bounded memory when the bucket count is sized for it.

# Sliced sketches may go COARSER than the standalone MIN_BUCKET_BITS floor:
# below 10 bits a bucket spans exponent boundaries and the per-value
# relative-error story collapses — but a per-slice AUROC/AUPRC only needs
# the bucket ORDER (the curve kernels never read the representatives), and
# at a million slices every extra bit doubles hundreds of MB of state. The
# a-posteriori error bounds (auroc_error_bound) stay computable and honest
# at any width; docs/performance.md "Sliced metrics" carries the cost model.
SLICED_MIN_BUCKET_BITS = 4


def check_sliced_bucket_bits(bucket_bits: int) -> int:
    if (
        not isinstance(bucket_bits, int)
        or not SLICED_MIN_BUCKET_BITS <= bucket_bits <= MAX_BUCKET_BITS
    ):
        raise ValueError(
            "sliced curve_bucket_bits must be an int in "
            f"[{SLICED_MIN_BUCKET_BITS}, {MAX_BUCKET_BITS}], got "
            f"{bucket_bits!r}."
        )
    return bucket_bits


def check_sliced_sketch_extent(
    bucket_bits: int, num_slices: int, shards: int = 1
) -> None:
    """Fail closed at the sliced sketch's addressing edge (review finding):
    the combined segment index is ``rows * planes + plane`` in int32, so
    the PER-SHARD extent ``ceil(num_slices/shards) * (2^(bits+1) + 1)``
    must stay <= 2^31 - 1 — past it the index silently WRAPS and per-slice
    counts corrupt (and the flat histogram's memory explodes long before
    that helps anyone). Raised at member registration / capacity growth,
    never inside the program, with the two remedies named. The bound is
    per shard because the sharded fold builds each shard's combined index
    over its own block-range row tile: sharding over N devices multiplies
    the admissible cohort count by N. Default 16-bit buckets cap out at
    ~16k slices per shard; a million unsharded cohorts need coarse widths
    (``curve_bucket_bits`` 4-6) or a sharded slice axis
    (docs/performance.md "Sliced metrics")."""
    planes = 2 * (1 << bucket_bits) + 1
    shards = max(int(shards), 1)
    per_shard = -(-int(num_slices) // shards)
    if per_shard * planes > 2**31 - 1:
        raise ValueError(
            f"sliced sketch extent {per_shard} slices/shard x {planes} "
            f"planes (curve_bucket_bits={bucket_bits}, {num_slices} slices "
            f"over {shards} shard(s)) exceeds the int32 segment-index "
            "range (2^31-1): per-slice histogram counts would silently "
            "corrupt. Use a coarser curve_bucket_bits (each bit halves the "
            "slice headroom) or shard the slice axis over more devices "
            'with slices={"mesh_axis": ...} (SlicedMetricCollection('
            "mesh_axis=...)) — the extent bound is per shard "
            "(docs/performance.md, 'Sliced metrics')."
        )


def sliced_score_hist_fold(rows, scores, targets, bits, num_slices, shard=None):
    """Fold one ``(N,)`` binary score/target batch into per-slice
    ``(num_slices, B)`` ``(tp, fp)`` int32 histograms plus a per-slice NaN
    lane, routed by the dense ``rows`` column. Additive and integer-exact:
    any chunking of the stream sums to the same counts, so per-slice values
    are bit-identical to a looped per-slice fold of the same kernel.

    ONE combined-index scatter carries everything: each sample lands in
    plane ``2*bucket + (1 - target)`` of its slice's ``2B + 1`` planes
    (NaN samples in the last plane), so the fold pays a single
    segment_sum pass over the batch however many count lanes the sketch
    keeps — XLA:CPU's scatter is serial per update, so pass count, not
    lane count, is the cost (docs/performance.md "Sliced metrics").

    With ``shard=(mesh, axis)`` the scatter runs per block-range shard:
    each shard localizes the row column into its own ``num_slices/N`` tile
    and builds the combined index over THAT extent only — which is exactly
    why the int32 bound (:func:`check_sliced_sketch_extent`) is per shard —
    and the histogram is born ``P(axis)``-sharded with no state-sized
    collective. A global combined index would re-wrap int32 at the same
    edge, so the localization must happen before the multiply."""
    check_sliced_bucket_bits(bits)
    rows = rows.astype(jnp.int32)
    nan = jnp.isnan(scores.astype(jnp.float32))
    t = targets.astype(jnp.int32)
    b = bucket_index(scores, bits)
    num_buckets = 1 << bits
    planes = 2 * num_buckets + 1
    plane = jnp.where(nan, 2 * num_buckets, 2 * b + (1 - t))
    if shard is None:
        idx = rows * planes + plane
        hist = jax.ops.segment_sum(
            jnp.ones_like(rows), idx, num_segments=num_slices * planes
        ).reshape(num_slices, planes)
    else:
        from jax.sharding import PartitionSpec as _P

        from torcheval_tpu.ops.topk import (
            _SHARD_MAP_KWARGS,
            _shard_map,
            shard_tile_width,
        )

        mesh, axis = shard
        w = shard_tile_width(num_slices, int(mesh.shape[axis]))

        def _body(rows_l, plane_l):
            s = jax.lax.axis_index(axis)
            local = rows_l - s * w
            ok = (local >= 0) & (local < w)
            # rows owned by other shards route to one dead trailing segment
            idx = jnp.where(ok, local * planes + plane_l, w * planes)
            h = jax.ops.segment_sum(
                jnp.ones_like(rows_l), idx, num_segments=w * planes + 1
            )
            return h[: w * planes].reshape(w, planes)

        hist = _shard_map(
            _body,
            mesh=mesh,
            in_specs=(_P(), _P()),
            out_specs=_P(axis),
            **_SHARD_MAP_KWARGS,
        )(rows, plane)
    return {
        "sketch_tp": hist[:, 0 : 2 * num_buckets : 2],
        "sketch_fp": hist[:, 1 : 2 * num_buckets : 2],
        "sketch_nan_dropped": hist[:, 2 * num_buckets],
    }


def sliced_curve_values(tp, fp, bits, kind):
    """Per-slice curve values from ``(S, B)`` sketches: the SAME presorted
    counts kernel the standalone sketch metrics compute through, vmapped
    over the slice axis — per-slice values are bit-identical to
    :func:`~torcheval_tpu.sketch.histogram.auroc_from_hist` on that slice's
    counts. For coarse sliced widths (below the standalone bucket-bits
    floor) the representatives row is inert zeros: the counts kernels use
    the score column for shape only."""
    from torcheval_tpu.ops.curves import (
        binary_auprc_counts_presorted_kernel,
        binary_auroc_counts_presorted_kernel,
    )

    kernel = (
        binary_auroc_counts_presorted_kernel
        if kind == "auroc"
        else binary_auprc_counts_presorted_kernel
    )
    try:
        reps = _desc_reps(bits)
    except ValueError:  # coarse sliced width: representatives undefined
        reps = jnp.zeros((1 << bits,), jnp.float32)
    return jax.vmap(lambda a, b: kernel(reps, a[::-1], b[::-1]))(tp, fp)


def sliced_curve_compute(tp, fp, nan, _hi, _lo, _count, bits, kind):
    """Terminal ``_compute_fn`` of the sliced score-sketch member (the id
    states ride the registration order but the curve ignores them): returns
    ``(per_slice_values, exactness_flag, nan_total)`` — the host-side
    ``_on_window_result`` raises on the flags and wraps the values."""
    return (
        sliced_curve_values(tp, fp, bits, kind),
        counts_exactness_flag(tp, fp),
        jnp.sum(nan),
    )


# ------------------------------------------------------- score-sketch mixin
class ScoreSketchCacheMixin:
    """Approx mode for (score, target) cache metrics that do NOT carry the
    exact-summary compaction lifecycle (the PRC curve classes): the raw
    ``inputs``/``targets`` caches become a staging buffer folded into
    resident ``(tp, fp)`` bucket histograms every :data:`SKETCH_FOLD_ROWS`
    rows. The compacting curve metrics (``classification/auroc.py``) carry
    an integrated branch instead — their fold cadence is the existing
    ``compaction_threshold`` machinery — but share these jitted fold
    programs, so the math has one definition."""

    _sketch_bits: Optional[int] = None

    def _init_score_sketch(
        self, bits: int, *, num_classes: Optional[int] = None
    ) -> None:
        self._sketch_bits = bits
        self._sketch_classes = num_classes
        self._sketch_staged = 0
        register_score_sketch_states(self, bits, num_classes)

    def _sketch_enabled(self) -> bool:
        return self._sketch_bits is not None

    def _score_sketch_stage(self, n_rows: int) -> None:
        self._sketch_staged += n_rows
        if self._sketch_staged >= SKETCH_FOLD_ROWS:
            self._score_sketch_fold()

    def _score_sketch_fold(self) -> None:
        if self.inputs:
            if self._sketch_classes is None:
                tp, fp, nan = score_fold_parts(
                    self.inputs,
                    self.targets,
                    self.sketch_tp,
                    self.sketch_fp,
                    self.sketch_nan_dropped,
                    self._sketch_bits,
                )
                _count_fold("score", self._sketch_staged)
            else:
                tp, fp, nan = mc_score_fold_parts(
                    self.inputs,
                    self.targets,
                    self.sketch_tp,
                    self.sketch_fp,
                    self.sketch_nan_dropped,
                    self._sketch_bits,
                    self._sketch_classes,
                )
                _count_fold("mc_score", self._sketch_staged)
            self.inputs = []
            self.targets = []
            self.sketch_tp = tp
            self.sketch_fp = fp
            self.sketch_nan_dropped = nan
        self._sketch_staged = 0

    def _score_sketch_parts(self):
        """Positional args for the ``sketch_*_from_parts`` compute programs
        (state untouched — staged leftovers fold inside the program)."""
        return (
            list(self.inputs),
            list(self.targets),
            self.sketch_tp,
            self.sketch_fp,
            self.sketch_nan_dropped,
        )

    def _sketch_check_nan(self, nan, noun: str = "sample(s)") -> None:
        raise_sketch_nan(nan, noun)

    def _score_sketch_recount(self) -> None:
        self._sketch_staged = sum(int(a.shape[0]) for a in self.inputs)
        if self._sketch_staged >= SKETCH_FOLD_ROWS:
            self._score_sketch_fold()

    def _sketch_merge_from(self, metrics) -> None:
        merge_score_sketch_states(self, metrics)

    # ------------------------------------------- cooperative lifecycle hooks
    def _prepare_for_merge_state(self) -> None:
        if self._sketch_enabled():
            self._score_sketch_fold()
        super()._prepare_for_merge_state()

    def merge_state(self, metrics):
        metrics = list(metrics)
        super().merge_state(metrics)
        if self._sketch_enabled():
            self._sketch_merge_from(metrics)
            self._score_sketch_recount()
        return self

    def reset(self):
        super().reset()
        if self._sketch_enabled():
            self._sketch_staged = 0
        return self

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        super().load_state_dict(state_dict, strict)
        if self._sketch_enabled():
            self._score_sketch_recount()


# ------------------------------------------------------- value-sketch mixin
class ValueSketchCacheMixin:
    """Approx mode for value-cache metrics (``HitRate``/``ReciprocalRank``/
    ``Cat``): the per-sample cache becomes a staging buffer folded into a
    resident bucket-count sketch every :data:`SKETCH_FOLD_ROWS` rows.

    Cooperative overrides (``merge_state`` / ``_prepare_for_merge_state`` /
    ``reset`` / ``load_state_dict``) keep the base ``SampleCacheMetric``
    protocol; metrics with bespoke merges (``Cat``) call the granular
    ``_sketch_*`` helpers instead."""

    _sketch_bits: Optional[int] = None

    def _init_value_sketch(self, bits: int, cache_name: str) -> None:
        from torcheval_tpu.metrics.state import Reduction, zeros_state

        self._sketch_bits = bits
        self._sketch_cache_name = cache_name
        self._sketch_staged = 0
        self._add_state(
            "sketch_counts",
            zeros_state((1 << bits,), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )
        self._add_state(
            "sketch_nan_dropped",
            zeros_state((), dtype=jnp.int32),
            reduction=Reduction.SUM,
        )

    def _sketch_enabled(self) -> bool:
        return self._sketch_bits is not None

    def _sketch_stage(self, arr) -> None:
        """Account freshly-appended staging rows; fold at the cadence."""
        self._sketch_staged += int(arr.size) if arr.ndim else 1
        if self._sketch_staged >= SKETCH_FOLD_ROWS:
            self._sketch_fold()

    def _sketch_fold(self) -> None:
        cache = getattr(self, self._sketch_cache_name)
        if cache:
            counts, nan = value_fold_parts(
                list(cache),
                self.sketch_counts,
                self.sketch_nan_dropped,
                self._sketch_bits,
            )
            _count_fold("value", self._sketch_staged)
            setattr(self, self._sketch_cache_name, [])
            self.sketch_counts = counts
            self.sketch_nan_dropped = nan
        self._sketch_staged = 0

    def _sketch_counts_parts(self):
        """``(counts, nan, overflow_flag)`` including staged leftovers,
        without mutating state (idempotent-compute contract)."""
        cache = getattr(self, self._sketch_cache_name)
        return value_counts_from_parts(
            list(cache),
            self.sketch_counts,
            self.sketch_nan_dropped,
            self._sketch_bits,
        )

    def _sketch_check_nan(self, nan) -> None:
        raise_sketch_nan(nan)

    def _sketch_recount(self) -> None:
        cache = getattr(self, self._sketch_cache_name)
        self._sketch_staged = sum(int(a.size) for a in cache)
        if self._sketch_staged >= SKETCH_FOLD_ROWS:
            self._sketch_fold()

    def _sketch_merge_from(self, metrics) -> None:
        """Bucket-add other replicas' resident sketches (their staged rows
        arrive through the base cache merge; the follow-up recount folds
        when over the cadence)."""
        for metric in metrics:
            self.sketch_counts = self.sketch_counts + jax.device_put(
                metric.sketch_counts, self.device
            )
            self.sketch_nan_dropped = (
                self.sketch_nan_dropped
                + jax.device_put(metric.sketch_nan_dropped, self.device)
            )

    # ------------------------------------------- cooperative lifecycle hooks
    def _prepare_for_merge_state(self) -> None:
        if self._sketch_enabled():
            # sync ships the bounded sketch, never the staging rows
            self._sketch_fold()
        super()._prepare_for_merge_state()

    def merge_state(self, metrics):
        metrics = list(metrics)
        super().merge_state(metrics)
        if self._sketch_enabled():
            self._sketch_merge_from(metrics)
            self._sketch_recount()
        return self

    def reset(self):
        super().reset()
        if self._sketch_enabled():
            self._sketch_staged = 0
        return self

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        super().load_state_dict(state_dict, strict)
        if self._sketch_enabled():
            self._sketch_recount()

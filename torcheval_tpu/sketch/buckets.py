"""Float-prefix bucket mapping: the one bucket family behind every sketch.

A score/value sketch needs a *fixed*, *distribution-independent*, *monotone*
partition of the float line so that (a) bucket counts from any two streams
merge by plain addition (the mergeability contract — ISSUE 13's "exact merge
= bucket add"), and (b) the partition is a pure bit-level function that is
jit/vmap-safe and costs one shift per element inside a fold kernel.

The mapping here is the top ``bucket_bits`` bits of the monotone u32 order
key the distributed curve kernels already use for their splitter histograms
(``ops/dist_curves.py:_desc_key``, ascending orientation): every finite f32
maps through a sign-aware bitcast to a u32 whose *unsigned order equals the
float order*, and the bucket index is that key's high bits. Because the key's
layout is ``[sign][8-bit exponent][mantissa]``, keeping ``bucket_bits >= 10``
means a bucket never spans an exponent boundary, so each bucket's value range
is a *relative* slice of the line — exactly the DDSketch/t-digest shape:

* **relative-error buckets**: for any normal float ``v``, every value in
  ``v``'s bucket is within ``relative_error(bucket_bits) = 2**-(bucket_bits
  - 9)`` of ``v`` (the bucket keeps ``bucket_bits - 9`` mantissa bits; its
  width over its lower edge is ``<= 2**-(mantissa bits)``). Subnormals and
  zero get *absolute* slices of a ~1e-38 neighborhood — tighter than any
  caller cares about.
* **full-line coverage**: negatives, ``+-0`` (canonicalized to one bucket),
  ``+-inf`` and every magnitude are covered with no configuration — there is
  no DDSketch "index range" knob to mis-set, and heavy-tailed streams cannot
  fall off the edges.
* **NaN is not representable** (its order is undefined); fold kernels mask
  NaN elements out and count them into a separate lane so callers can keep
  the library's loud-NaN contract (``_CompactingCacheLifecycle``).

Bucket *representatives* (the value handed back by quantile queries and used
as curve thresholds) are the value-space midpoint of the bucket's edge
values, precomputed host-side per ``bucket_bits`` and embedded as an XLA
constant — compute kernels never invert keys at runtime. Buckets that lie
inside the NaN regions of the key space decode to NaN representatives; they
can never hold a count, and the curve kernels treat (NaN, 0, 0) rows as
padding by contract (``ops/curves.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# default bucket count exponent: 2^16 buckets = 256 KiB per int32 histogram
# (the dist_curves splitter-histogram precedent) and a documented relative
# error of 2^-7 ~ 0.8% on any representative — while curve VALUES (AUROC /
# AUPRC) see only the within-bucket tie mass, typically orders of magnitude
# tighter (sketch/histogram.py error bounds).
DEFAULT_BUCKET_BITS = 16
# multiclass curve state is (num_classes, B) x2 — default to 2^12 buckets so
# a 1000-class metric holds 32 MiB, not 512 MiB. The AUROC/AUPRC error bound
# scales ~1/B and stays ~1e-4 on smooth score distributions at 2^12.
DEFAULT_MC_BUCKET_BITS = 12
# below 10 bits a bucket would span exponent boundaries (no mantissa bits
# left) and the relative-error story collapses; above 20 the "bounded
# memory" story does (4 MiB per histogram and counting).
MIN_BUCKET_BITS, MAX_BUCKET_BITS = 10, 20

_NAN_KEY = np.uint32(0xFFFFFFFF)


def check_bucket_bits(bucket_bits: int) -> int:
    if (
        not isinstance(bucket_bits, int)
        or not MIN_BUCKET_BITS <= bucket_bits <= MAX_BUCKET_BITS
    ):
        raise ValueError(
            f"bucket_bits must be an int in [{MIN_BUCKET_BITS}, "
            f"{MAX_BUCKET_BITS}], got {bucket_bits!r}."
        )
    return bucket_bits


def relative_error(bucket_bits: int) -> float:
    """Documented per-value bound: any finite normal value and its bucket's
    representative differ by at most this *relative* amount (conservative
    full-bucket-width bound; the midpoint representative typically halves
    it). Subnormal buckets are bounded absolutely by ~1e-38 instead."""
    return 2.0 ** -(check_bucket_bits(bucket_bits) - 9)


def ascending_key(x: jax.Array) -> jax.Array:
    """Monotone u32 order key, ascending: ``key(a) < key(b)`` iff ``a < b``
    as floats, ``-0.0`` and ``+0.0`` share one key, every NaN maps to the
    max key (callers mask NaN before bucketing). The sign-aware bitcast is
    ``ops/dist_curves.py:_desc_key`` without the final inversion.

    Subnormal magnitudes flush to the zero key explicitly: XLA backends
    disagree on FTZ/DAZ (CPU flushes ``-1e-40 == 0`` to true, others may
    not), and the bucket id must be a pure deterministic function of the
    value for cross-replica merges to agree. Costs < 1.18e-38 absolute
    error, beneath every documented bound."""
    x = x.astype(jnp.float32)
    # where(), not `x + 0.0`: XLA folds add(x, 0) away, sign bit and all
    tiny = jnp.float32(np.finfo(np.float32).tiny)
    x = jnp.where(jnp.abs(x) < tiny, jnp.float32(0.0), x)
    b = jax.lax.bitcast_convert_type(x, jnp.uint32)
    key = jnp.where(
        jax.lax.shift_right_logical(b, jnp.uint32(31)) == jnp.uint32(1),
        ~b,
        b | jnp.uint32(0x80000000),
    )
    return jnp.where(jnp.isnan(x), jnp.uint32(_NAN_KEY), key)


def bucket_index(x: jax.Array, bucket_bits: int) -> jax.Array:
    """Bucket id in ``[0, 2**bucket_bits)`` for every element (NaN lands in
    the top bucket — mask it out before counting). Pure bit ops: safe under
    jit, vmap and shard_map."""
    shift = jnp.uint32(32 - bucket_bits)
    return jax.lax.shift_right_logical(ascending_key(x), shift).astype(
        jnp.int32
    )


def _key_to_float(key: np.ndarray) -> np.ndarray:
    """Host-side inverse of :func:`ascending_key` (vectorized numpy)."""
    key = np.asarray(key, dtype=np.uint32)
    positive = (key & np.uint32(0x80000000)) != 0
    bits = np.where(positive, key & np.uint32(0x7FFFFFFF), ~key).astype(
        np.uint32
    )
    return bits.view(np.float32)


@functools.lru_cache(maxsize=None)
def bucket_edges(bucket_bits: int):
    """``(lo, hi)`` float32 arrays of every bucket's inclusive value edges,
    ascending by bucket id. Edges in the key space's NaN regions decode to
    NaN (those buckets can never hold a count)."""
    check_bucket_bits(bucket_bits)
    shift = 32 - bucket_bits
    ids = np.arange(1 << bucket_bits, dtype=np.uint64)
    lo_key = (ids << shift).astype(np.uint32)
    hi_key = ((ids << shift) + ((1 << shift) - 1)).astype(np.uint32)
    lo = _key_to_float(lo_key)
    hi = _key_to_float(hi_key)
    # the +-inf buckets' outward edge keys decode into the NaN bit-pattern
    # region; clamp to the inward edge so every bucket that can hold a
    # value has finite-or-inf edges (buckets with BOTH edges NaN lie fully
    # inside a NaN region and can never hold a count)
    lo = np.where(np.isnan(lo) & ~np.isnan(hi), hi, lo)
    hi = np.where(np.isnan(hi) & ~np.isnan(lo), lo, hi)
    lo.setflags(write=False)
    hi.setflags(write=False)
    return lo, hi


@functools.lru_cache(maxsize=None)
def bucket_representatives(bucket_bits: int) -> np.ndarray:
    """Per-bucket representative value (value-space midpoint of the edges),
    ascending by bucket id, float32. Precomputed once per ``bucket_bits``
    and closed over as an XLA constant by the compute kernels. The
    ``+-inf``-edge buckets keep their infinite edge as representative;
    NaN-region buckets stay NaN (padding rows by the curve-kernel
    contract)."""
    lo, hi = bucket_edges(bucket_bits)
    # float64 midpoint: (lo + hi) / 2 cannot overflow and rounds once.
    # NaN-region buckets legitimately produce NaN mids — mute the cast
    # warning rather than special-case them twice.
    with np.errstate(invalid="ignore"):
        mid = (
            (lo.astype(np.float64) + hi.astype(np.float64)) / 2.0
        ).astype(np.float32)
    # an infinite edge dominates the midpoint (inf + finite = inf, which is
    # the honest representative for the bucket holding +-inf); a NaN edge
    # paired with a finite one keeps the finite edge
    mid = np.where(np.isnan(mid) & ~np.isnan(lo), lo, mid)
    mid = np.where(np.isnan(mid) & ~np.isnan(hi), hi, mid)
    mid.setflags(write=False)
    return mid

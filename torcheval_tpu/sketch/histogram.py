"""Mergeable bounded-memory histogram sketches over float-prefix buckets.

Two sketch shapes, both *plain state trees* (int32 count arrays + an int32
NaN lane) so they ride the existing deferred window-step, ``merge_state``,
the two-round sync wire (``Reduction.SUM`` lanes — psum/bucket-add is the
exact merge) and atomic checkpoints with no new machinery:

* **score sketch** — per-bucket ``(tp, fp)`` counts for curve metrics
  (AUROC / AUPRC / PRC). ``(B,)`` for binary, ``(C, B)`` one-vs-all for
  multiclass. Compute feeds the counts straight into the existing
  presorted counts kernels (``ops/curves.py``) with the bucket
  representatives as thresholds — within-bucket samples become one tie
  group, which is the *entire* approximation; cross-bucket order is exact.
* **value sketch** — per-bucket counts of a value multiset for quantile /
  mean / distribution queries (``Quantile``, approx ``HitRate`` /
  ``ReciprocalRank`` / ``Cat``).

Error accounting (the documented, tested bounds — all computable a
posteriori from the sketch itself, so tests assert against the *actual*
stream, not a model of it):

* AUROC: binning can only re-score positive-negative pairs that share a
  bucket, each by at most 1/2 (they become trapezoid ties) —
  ``|approx - exact| <= 0.5 * sum_b tp_b * fp_b / (P * N)``
  (:func:`auroc_error_bound`). Exact score ties were ties already, so
  adversarial all-tied streams cost *zero* error.
* AUPRC: both the exact and the binned step integral assign the ``i``-th
  positive of a bucket a precision between the bucket's negatives-last and
  negatives-first extremes; the bound sums those envelopes
  (:func:`auprc_error_bound`).
* quantiles / representatives: ``buckets.relative_error(bucket_bits)``
  relative to the true order statistic (rank resolution is exact — counts
  are integers).

NaN policy: NaN elements are masked out of every histogram and counted into
the fold's NaN lane; metric callers raise at ``compute()`` (the
``_CompactingCacheLifecycle`` loud-NaN contract) unless they opt into
``nan_policy="ignore"``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.obs.recompile import watched_jit
from torcheval_tpu.ops.curves import (
    binary_auprc_counts_presorted_kernel,
    binary_auroc_counts_presorted_kernel,
)
from torcheval_tpu.sketch.buckets import (
    bucket_index,
    bucket_representatives,
    check_bucket_bits,
)

__all__ = [
    "score_hist_fold",
    "mc_score_hist_fold",
    "value_hist_fold",
    "auroc_from_hist",
    "auprc_from_hist",
    "prc_from_hist",
    "mean_from_counts",
    "quantiles_from_counts",
    "auroc_error_bound",
    "auprc_error_bound",
]


# ------------------------------------------------------------------ folds
def score_hist_fold(
    scores: jax.Array, targets: jax.Array, bucket_bits: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold a ``(N,)`` binary score/target batch into ``(B,)`` per-bucket
    ``(tp, fp)`` int32 counts plus the batch's NaN-sample count. Pure and
    additive: folding chunks in any grouping and adding the results is
    bit-identical to one fold of the concatenated stream (integer adds)."""
    nan = jnp.isnan(scores.astype(jnp.float32))
    t = jnp.where(nan, 0, targets.astype(jnp.int32))
    f = jnp.where(nan, 0, 1 - targets.astype(jnp.int32))
    idx = jnp.where(nan, 0, bucket_index(scores, bucket_bits))
    num = 1 << check_bucket_bits(bucket_bits)
    tp = jax.ops.segment_sum(t, idx, num_segments=num)
    fp = jax.ops.segment_sum(f, idx, num_segments=num)
    return tp, fp, jnp.sum(nan.astype(jnp.int32))


def mc_score_hist_fold(
    scores: jax.Array, labels: jax.Array, bucket_bits: int, num_classes: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One-vs-all fold of an ``(N, C)`` score block + ``(N,)`` integer
    labels into ``(C, B)`` per-class ``(tp, fp)`` counts plus the NaN
    per-class score-entry count (one bad row contributes up to C — the
    multiclass NaN-noun convention of ``classification/auroc.py``)."""
    onehot = (
        labels[None, :].astype(jnp.int32)
        == jnp.arange(num_classes, dtype=jnp.int32)[:, None]
    ).astype(jnp.int32)  # (C, N)
    cols = scores.T  # (C, N)
    nan = jnp.isnan(cols.astype(jnp.float32))
    t = jnp.where(nan, 0, onehot)
    f = jnp.where(nan, 0, 1 - onehot)
    idx = jnp.where(nan, 0, bucket_index(cols, bucket_bits))
    num = 1 << check_bucket_bits(bucket_bits)
    seg = jax.vmap(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=num)
    )
    return seg(t, idx), seg(f, idx), jnp.sum(nan.astype(jnp.int32))


def value_hist_fold(
    values: jax.Array, bucket_bits: int
) -> Tuple[jax.Array, jax.Array]:
    """Fold a value batch (any shape — flattened) into ``(B,)`` int32
    bucket counts plus the batch's NaN count."""
    flat = values.reshape(-1)
    nan = jnp.isnan(flat.astype(jnp.float32))
    idx = jnp.where(nan, 0, bucket_index(flat, bucket_bits))
    counts = jax.ops.segment_sum(
        jnp.where(nan, 0, 1),
        idx,
        num_segments=1 << check_bucket_bits(bucket_bits),
    )
    return counts, jnp.sum(nan.astype(jnp.int32))


# --------------------------------------------------------------- computes
def _desc_reps(bucket_bits: int) -> jnp.ndarray:
    """Representatives in descending-threshold order (reversed bucket ids)
    — the presorted counts kernels' row order. Embedded as a constant."""
    return jnp.asarray(bucket_representatives(bucket_bits)[::-1])


def auroc_from_hist(
    tp: jax.Array, fp: jax.Array, bucket_bits: int
) -> jax.Array:
    """AUROC from a ``(B,)`` score sketch: the buckets are already unique
    descending thresholds once reversed, so the sort-free presorted kernel
    applies directly (zero-count buckets add zero-width segments; its
    score column is unused beyond shape, so NaN-region representatives are
    inert padding by the kernel contract)."""
    return binary_auroc_counts_presorted_kernel(
        _desc_reps(bucket_bits), tp[::-1], fp[::-1]
    )


def auprc_from_hist(
    tp: jax.Array, fp: jax.Array, bucket_bits: int
) -> jax.Array:
    """Average precision from a ``(B,)`` score sketch (see
    :func:`auroc_from_hist`)."""
    return binary_auprc_counts_presorted_kernel(
        _desc_reps(bucket_bits), tp[::-1], fp[::-1]
    )


def prc_points_from_hist(
    tp: jax.Array, fp: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full-length ``(precision, recall, nonempty)`` rows in descending
    threshold order from a ``(B,)`` score sketch — static shapes for jit;
    the host API (:func:`prc_from_hist`) trims empty buckets."""
    ctp = jnp.cumsum(tp[::-1].astype(jnp.int32), dtype=jnp.int32)
    cfp = jnp.cumsum(fp[::-1].astype(jnp.int32), dtype=jnp.int32)
    tpf = ctp.astype(jnp.float32)
    fpf = cfp.astype(jnp.float32)
    precision = tpf / jnp.maximum(tpf + fpf, 1.0)
    total_pos = tpf[-1]
    recall = jnp.where(
        total_pos > 0, tpf / jnp.maximum(total_pos, 1.0), 1.0
    )
    nonempty = (tp + fp)[::-1] > 0
    return precision, recall, nonempty


# module-level program (one jit cache per shape + recompile accounting);
# a per-call jax.jit wrapper would retrace every invocation invisibly
_prc_points_program = watched_jit(
    prc_points_from_hist, name="sketch.prc_points"
)


def trim_hist_curve(
    precision, recall, nonempty, bucket_bits: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Host-side trim of :func:`prc_points_from_hist` output (full-length
    DESCENDING-threshold rows) to the reference curve layout: nonempty
    buckets only, ascending thresholds (the bucket representatives), the
    ``(precision=1, recall=0)`` graph origin appended. The one shared
    definition — both the functional :func:`prc_from_hist` and the approx
    PRC metric classes call it."""
    keep = np.asarray(nonempty)
    p = np.asarray(precision)[keep][::-1]
    r = np.asarray(recall)[keep][::-1]
    t = bucket_representatives(bucket_bits)[::-1][keep][::-1]
    p = np.concatenate([p, np.ones(1, dtype=p.dtype)])
    r = np.concatenate([r, np.zeros(1, dtype=r.dtype)])
    return jnp.asarray(p), jnp.asarray(r), jnp.asarray(t)


def prc_from_hist(
    tp, fp, bucket_bits: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Reference-layout ``(precision, recall, thresholds)`` from a ``(B,)``
    score sketch: one point per NONEMPTY bucket, ascending thresholds, the
    ``(precision=1, recall=0)`` origin appended — the approximate analogue
    of ``functional...binary_precision_recall_curve`` with bucket
    representatives as thresholds (host-side trim, like the exact API)."""
    precision, recall, nonempty = _prc_points_program(
        jnp.asarray(tp), jnp.asarray(fp)
    )
    return trim_hist_curve(precision, recall, nonempty, bucket_bits)


def mean_from_counts(counts: jax.Array, bucket_bits: int) -> jax.Array:
    """Representative-weighted mean of a value sketch — within
    ``relative_error(bucket_bits)`` of the exact mean of ``|values|`` mass.
    Empty sketch returns 0.0 (the empty-cache mean convention)."""
    reps = jnp.asarray(bucket_representatives(bucket_bits))
    c = counts.astype(jnp.float32)
    # zero-count NaN-region buckets must not poison the sum (0 * NaN)
    weighted = jnp.where(counts > 0, c * reps, 0.0)
    n = jnp.sum(c)
    return jnp.where(n > 0, jnp.sum(weighted) / jnp.maximum(n, 1.0), 0.0)


def quantiles_from_counts(
    counts: jax.Array, q: Tuple[float, ...], bucket_bits: int
) -> jax.Array:
    """Quantile estimates from a value sketch: for each ``q`` the bucket
    holding the order statistic of (1-indexed) rank ``ceil(q * n)`` — the
    DDSketch convention (``inverted_cdf``) — whose representative is within
    ``relative_error(bucket_bits)`` of the true order statistic. Rank
    resolution is exact up to f32 rank arithmetic (~2^24; beyond that the
    rank may slip by a few ulps of ``q * n``, never the value bound).
    Returns NaN per quantile on an empty sketch. jit-safe (static ``q``),
    so it rides the window-step as a terminal compute."""
    reps = jnp.asarray(bucket_representatives(bucket_bits))
    cum = jnp.cumsum(counts.astype(jnp.int32), dtype=jnp.int32)
    n = cum[-1]
    qs = jnp.asarray(q, dtype=jnp.float32)
    rank = jnp.clip(
        jnp.ceil(qs * n.astype(jnp.float32)).astype(jnp.int32), 1, n
    )
    idx = jnp.searchsorted(cum, rank, side="left")
    vals = reps[jnp.clip(idx, 0, reps.shape[0] - 1)]
    return jnp.where(n > 0, vals, jnp.float32(jnp.nan))


def counts_exactness_flag(*arrays) -> jax.Array:
    """Traced guard: True when int32 count state can no longer be trusted
    — a bucket went negative (per-bucket add wrapped) or a cumulative sum
    would wrap. The compute-side cumsums run along the BUCKET axis, one
    per count array per leading index (per class for ``(C, B)`` state),
    so the bound is the worst PER-CUMSUM total — summing across classes
    would trip ~C times too early (review finding; a 1000-class stream is
    exact until ~2.1e9 samples PER CLASS). Totals are measured in f32 —
    exact enough for a threshold, no x64 dependency — against a slightly
    conservative bound (``2^31·(1 - 2^-7)``) absorbing the f32 rounding.
    Callers raise a loud error instead of returning silently wrapped
    curve values: the unbounded-stream mode fails closed at its
    exactness edge."""
    neg = jnp.asarray(False)
    worst = jnp.float32(0.0)
    for c in arrays:
        neg = neg | (jnp.min(c) < 0)
        worst = jnp.maximum(
            worst, jnp.max(jnp.sum(c.astype(jnp.float32), axis=-1))
        )
    return neg | (worst >= jnp.float32(2.0**31 * (1.0 - 2.0**-7)))


# ----------------------------------------------------------- error bounds
def auroc_error_bound(tp, fp) -> float:
    """A-posteriori bound on ``|approx AUROC - exact AUROC|`` for the
    stream this ``(B,)`` sketch summarizes: every cross-label pair that
    shares a bucket moves by at most 1/2 concordance (it becomes a
    trapezoid tie); cross-bucket pairs are untouched. Float64 host math."""
    tp = np.asarray(tp, dtype=np.float64)
    fp = np.asarray(fp, dtype=np.float64)
    pos, neg = tp.sum(), fp.sum()
    if pos == 0 or neg == 0:
        return 0.0
    return float(0.5 * np.sum(tp * fp) / (pos * neg))


def auprc_error_bound(tp, fp) -> float:
    """A-posteriori bound on ``|approx AP - exact AP|``: within a bucket
    holding ``t`` positives / ``f`` negatives after cumulative ``(T0, F0)``,
    every positive's precision — under ANY intra-bucket order, and under
    the binned tie-group formula — lies in
    ``[(T0+1)/(T0+1+F0+f), (T0+t)/(T0+t+F0)]``; the bound sums those
    envelope widths weighted ``t / P``. Descending-threshold cumulative
    counts, float64 host math."""
    tp = np.asarray(tp, dtype=np.float64)[::-1]
    fp = np.asarray(fp, dtype=np.float64)[::-1]
    pos = tp.sum()
    if pos == 0:
        return 0.0
    ctp = np.cumsum(tp)
    cfp = np.cumsum(fp)
    t0 = ctp - tp  # cumulative counts BEFORE each bucket
    f0 = cfp - fp
    hi = (t0 + tp) / np.maximum(t0 + tp + f0, 1.0)
    lo = (t0 + 1.0) / (t0 + 1.0 + f0 + fp)
    width = np.where(tp > 0, hi - lo, 0.0)
    return float(np.sum(tp * width) / pos)

"""``torcheval_tpu.sketch`` — mergeable bounded-memory sketch state.

ISSUE 13 / ROADMAP item 4: the curve and quantile metrics' last O(samples)
state (sample caches, even compacted ones) becomes an opt-in O(buckets)
resident sketch — fixed-size bucket-count histograms over a
distribution-independent float-prefix partition (``buckets.py``), folded by
pure jit/vmap-safe kernels (``histogram.py``) and merged by plain addition,
so sketch state rides the deferred window-step, ``merge_state``, the
two-round sync wire and atomic checkpoints unchanged (``cache.py``).

Consumers: the ``approx=`` mode on ``BinaryAUROC``/``BinaryAUPRC``/
``MulticlassAUROC``/``MulticlassAUPRC``/``(Multiclass)PrecisionRecallCurve``
/``HitRate``/``ReciprocalRank``/``Cat`` and the ``Quantile`` aggregation
metric. Error bounds are documented per function and computable a
posteriori from the sketch itself (``auroc_error_bound`` /
``auprc_error_bound`` / ``relative_error``).
"""

from torcheval_tpu.sketch.buckets import (
    DEFAULT_BUCKET_BITS,
    DEFAULT_MC_BUCKET_BITS,
    MAX_BUCKET_BITS,
    MIN_BUCKET_BITS,
    ascending_key,
    bucket_edges,
    bucket_index,
    bucket_representatives,
    check_bucket_bits,
    relative_error,
)
from torcheval_tpu.sketch.cache import (
    SKETCH_FOLD_ROWS,
    ScoreSketchCacheMixin,
    ValueSketchCacheMixin,
    resolve_approx,
)
from torcheval_tpu.sketch.histogram import (
    auprc_error_bound,
    auprc_from_hist,
    auroc_error_bound,
    auroc_from_hist,
    mc_score_hist_fold,
    mean_from_counts,
    prc_from_hist,
    quantiles_from_counts,
    score_hist_fold,
    value_hist_fold,
)

__all__ = [
    "DEFAULT_BUCKET_BITS",
    "DEFAULT_MC_BUCKET_BITS",
    "MIN_BUCKET_BITS",
    "MAX_BUCKET_BITS",
    "SKETCH_FOLD_ROWS",
    "ScoreSketchCacheMixin",
    "ValueSketchCacheMixin",
    "ascending_key",
    "auprc_error_bound",
    "auprc_from_hist",
    "auroc_error_bound",
    "auroc_from_hist",
    "bucket_edges",
    "bucket_index",
    "bucket_representatives",
    "check_bucket_bits",
    "mc_score_hist_fold",
    "mean_from_counts",
    "prc_from_hist",
    "quantiles_from_counts",
    "relative_error",
    "resolve_approx",
    "score_hist_fold",
    "value_hist_fold",
]

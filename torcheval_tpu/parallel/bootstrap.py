"""Multi-host process bootstrap.

The reference boots its multi-process world with ``torchtnt.utils.init_from_env``
under ``torch.distributed.elastic`` (reference
``utils/test_utils/metric_class_tester.py:287-311``,
``examples/distributed_example.py:44-57``): each worker reads
``RANK`` / ``WORLD_SIZE`` / ``MASTER_ADDR`` / ``MASTER_PORT`` from the launcher
and joins a NCCL/Gloo process group. The TPU-native equivalent is
``jax.distributed.initialize``: after it, ``jax.devices()`` spans every host in
the pod and one SPMD program (with XLA collectives over ICI/DCN) replaces the
process-group calls.

``init_from_env`` is the drop-in: it resolves the coordinator from either the
JAX-style environment (``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` /
``PROCESS_ID``) or the torch-elastic style one (``MASTER_ADDR`` +
``MASTER_PORT`` / ``WORLD_SIZE`` / ``RANK``) so launch scripts written for the
reference port unchanged, and falls back to JAX's own auto-detection on
Cloud TPU pods (where the TPU runtime publishes the topology and no
environment is needed).
"""

from __future__ import annotations

import logging
import os
import random
import time
from typing import Optional, Tuple

import jax

from torcheval_tpu.obs import registry as _obs

_logger = logging.getLogger(__name__)

__all__ = ["init_from_env", "is_initialized", "shutdown"]

# coordinator-connection retry policy (ISSUE 5): on a preemptible slice the
# coordinator process routinely comes up seconds after its workers (or is
# itself restarted mid-join), so one-shot connection failure is an ordinary
# launch race, not an error. Bounded exponential backoff with jitter —
# jitter because a whole pod retrying in lockstep re-creates the thundering
# herd that made the first attempt fail.
_DEFAULT_CONNECT_ATTEMPTS = 3
_CONNECT_ATTEMPTS_ENV = "TORCHEVAL_TPU_CONNECT_ATTEMPTS"
_BACKOFF_CAP_S = 30.0


def _resolve_env(environ) -> Tuple[Optional[str], Optional[int], Optional[int]]:
    """(coordinator_address, num_processes, process_id) from the environment.

    JAX-style variables win; torch-elastic ones (as set by ``torchrun`` /
    ``torch.distributed.launcher``, which the reference's tests and examples
    use) are accepted as aliases. Any field left unresolved stays ``None`` and
    is delegated to ``jax.distributed.initialize``'s auto-detection.
    """
    coordinator = environ.get("COORDINATOR_ADDRESS")
    if coordinator is None:
        master_addr = environ.get("MASTER_ADDR")
        master_port = environ.get("MASTER_PORT")
        if (master_addr is None) != (master_port is None):
            raise ValueError(
                "init_from_env: MASTER_ADDR and MASTER_PORT must be set "
                f"together (got MASTER_ADDR={master_addr!r}, "
                f"MASTER_PORT={master_port!r})"
            )
        if master_addr is not None:
            coordinator = f"{master_addr}:{master_port}"

    def _int(*names: str) -> Optional[int]:
        for name in names:
            raw = environ.get(name)
            if raw is not None:
                try:
                    return int(raw)
                except ValueError:
                    raise ValueError(
                        f"environment variable {name}={raw!r} is not an integer"
                    ) from None
        return None

    num_processes = _int("NUM_PROCESSES", "WORLD_SIZE")
    process_id = _int("PROCESS_ID", "RANK")
    return coordinator, num_processes, process_id


def _fallback_auto_detect(environ) -> bool:
    """Conservative multi-host env check, used only if the probe API moves."""
    hosts = environ.get("TPU_WORKER_HOSTNAMES", "")
    if len(hosts.split(",")) > 1:
        return True
    try:
        if int(environ.get("SLURM_NTASKS", "1")) > 1:
            return True
    except ValueError:
        pass
    return "OMPI_MCA_orte_hnp_uri" in environ


def _auto_detectable() -> bool:
    """True when JAX's own cluster probes recognise this process as part of a
    launchable world OF MORE THAN ONE PROCESS (GCE/GKE TPU pods, SLURM, Open
    MPI, mpi4py, k8s). Delegating to the probes rather than re-listing env
    vars keeps this in lockstep with what a bare
    ``jax.distributed.initialize()`` can actually resolve — a hand-rolled
    heuristic either misses real pods (GCE publishes topology via the metadata
    server, not env vars) or false-fires on single-host TPU VMs (where
    ``TPU_WORKER_HOSTNAMES=localhost`` is set but there is nothing to join).

    The world-size>1 requirement filters probes that fire on mere machine
    configuration rather than an actual launch: ``Mpi4pyCluster`` is "present"
    whenever the mpi4py package is installed (world size 1 outside mpirun),
    ``K8sCluster`` in any kubernetes pod (its process count then raises
    outside a JobSet — also treated as "nothing to join")."""
    try:
        from jax._src.clusters import ClusterEnv

        cluster_types = ClusterEnv._cluster_types
    except Exception:  # pragma: no cover - depends on jax internals moving
        return _fallback_auto_detect(os.environ)
    for cluster in cluster_types:
        try:
            if cluster.is_env_present() and cluster.get_process_count() > 1:
                return True
        except Exception:
            continue
    return False


def is_initialized() -> bool:
    """True once this process has joined a multi-process JAX world."""
    try:
        return jax.distributed.is_initialized()
    except AttributeError:  # jax < 0.5: no public predicate; read the
        # runtime state object the initialize/shutdown pair maintains
        try:
            from jax._src.distributed import global_state

            return global_state.client is not None
        except Exception:  # pragma: no cover - internals moved; assume fresh
            return False


def _reset_partial_init() -> None:
    """Clear runtime state left behind by a FAILED ``jax.distributed.
    initialize``: the runtime assigns its client object before the
    connection attempt, so a connect failure leaves ``is_initialized()``
    true and every subsequent initialize raising "should only be called
    once" — which would turn the retry loop below into a no-op that burns
    its backoff sleeps on an instant, misleading error."""
    try:
        jax.distributed.shutdown()
        return
    except Exception:
        pass
    # a client that never connected can fail its own shutdown; fall back to
    # clearing the runtime state object directly so the next attempt starts
    # from scratch (best effort — internals may move)
    try:
        from jax._src.distributed import global_state

        global_state.client = None
        global_state.service = None
        if hasattr(global_state, "preemption_sync_manager"):
            global_state.preemption_sync_manager = None
    except Exception:  # pragma: no cover - jax internals moved
        pass


def init_from_env(
    *,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    connect_attempts: Optional[int] = None,
    connect_backoff_s: float = 1.0,
) -> Tuple[int, int]:
    """Join (or confirm membership in) the multi-process JAX world.

    Explicit keyword arguments override the environment; unresolved fields are
    left to JAX's cluster auto-detection (Cloud TPU, SLURM, Open MPI).
    Idempotent: if the runtime is already initialized, logs and returns the
    existing coordinates — matching the reference's world-size guards
    (reference ``toolkit.py:199-215``) rather than raising.

    Coordinator connection failures (the runtime's ``RuntimeError`` family —
    configuration errors raise ``ValueError`` and are never retried) are
    retried up to ``connect_attempts`` times (default 3, or
    ``TORCHEVAL_TPU_CONNECT_ATTEMPTS``) with exponential backoff starting at
    ``connect_backoff_s`` seconds, capped at 30 s, each sleep jittered to
    0.5-1.5× so a restarted pod does not reconverge on the coordinator in
    lockstep. A failed attempt leaves the runtime half-initialized (its
    client object is assigned before the connection is attempted), so each
    retry first resets that state — without it, every retry would raise
    "should only be called once" instead of reconnecting. Each retry bumps
    the ``bootstrap.retries`` obs counter; the final failure re-raises the
    runtime's own error.

    Caveat (verified against this jaxlib build): some CLIENT-side connect
    failures — e.g. a dead/unresolvable coordinator timing out RegisterTask
    — are handled by the C++ distributed client as a fatal abort of the
    whole process before Python sees any exception. No in-process retry can
    cover that shape; the recovery story there is the scheduler restarting
    the worker and ``torcheval_tpu.resilience.restore()`` reloading state.
    The retry layer covers every failure the runtime *raises*.

    Returns ``(process_index, process_count)``. In a single-process run with
    no coordinator configured anywhere, skips initialization entirely and
    returns ``(0, 1)`` — the toolkit's explicit sync path already treats
    world size 1 as a no-op.
    """
    if is_initialized():
        _logger.warning(
            "init_from_env: jax.distributed already initialized "
            "(process %d of %d); ignoring the new request.",
            jax.process_index(),
            jax.process_count(),
        )
        return jax.process_index(), jax.process_count()

    env_coord, env_world, env_rank = _resolve_env(os.environ)
    coordinator_address = coordinator_address or env_coord
    num_processes = num_processes if num_processes is not None else env_world
    process_id = process_id if process_id is not None else env_rank

    if coordinator_address is None and not _auto_detectable():
        if (num_processes or 1) > 1 or (process_id or 0) > 0:
            # a multi-process world-size or nonzero rank without a
            # coordinator is a half-configured launcher, not a
            # single-process run — degrading silently would leave every
            # worker believing it is rank 0 of 1. (RANK=0/WORLD_SIZE=1,
            # a common container default, IS a consistent single-process
            # configuration and falls through.)
            raise ValueError(
                "init_from_env: WORLD_SIZE/NUM_PROCESSES/RANK configured but "
                "no coordinator address (set COORDINATOR_ADDRESS or "
                "MASTER_ADDR+MASTER_PORT)"
            )
        _logger.info(
            "init_from_env: no coordinator configured; staying single-process."
        )
        return 0, 1

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    if connect_attempts is None:
        connect_attempts = int(
            os.environ.get(_CONNECT_ATTEMPTS_ENV, _DEFAULT_CONNECT_ATTEMPTS)
        )
    if connect_attempts < 1:
        raise ValueError(
            f"connect_attempts must be >= 1, got {connect_attempts}."
        )
    _enable_cpu_collectives()
    delay_s = connect_backoff_s
    for attempt in range(1, connect_attempts + 1):
        try:
            jax.distributed.initialize(**kwargs)
            break
        except RuntimeError as e:
            # the runtime reports coordinator-unreachable/handshake-deadline
            # failures as RuntimeError (XlaRuntimeError subclasses it);
            # ValueError (bad arguments) propagates immediately above.
            # Either way the failed attempt may have left the runtime
            # half-initialized — reset it, or the next initialize (ours or
            # a caller-level retry) raises "called once" instead of
            # reconnecting.
            _reset_partial_init()
            if attempt == connect_attempts:
                _logger.error(
                    "init_from_env: coordinator connection failed after "
                    "%d attempt(s); giving up.",
                    connect_attempts,
                )
                raise
            sleep_s = min(delay_s, _BACKOFF_CAP_S) * (0.5 + random.random())
            _logger.warning(
                "init_from_env: coordinator connection failed (attempt "
                "%d/%d: %s); retrying in %.1fs.",
                attempt,
                connect_attempts,
                e,
                sleep_s,
            )
            _obs.counter("bootstrap.retries")
            time.sleep(sleep_s)
            delay_s *= 2
    return jax.process_index(), jax.process_count()


def _enable_cpu_collectives() -> None:
    """Older jax (< 0.5) ships CPU cross-process collectives but defaults
    the implementation to 'none', so a CPU world fails at the first
    collective with "Multiprocess computations aren't implemented on the
    CPU backend". Newer jax defaults to gloo and dropped the knob — select
    gloo where the knob exists and nothing was chosen explicitly. Must run
    before the backend initialises, which init_from_env's contract (call
    before first jax use) already guarantees."""
    name = "jax_cpu_collectives_implementation"
    try:
        values = jax.config.values
    except Exception:  # config internals moved; don't guess
        return
    if name not in values:
        return  # knob gone: newer jax defaults CPU collectives to gloo
    if values[name] not in (None, "none"):
        return  # explicit user choice (e.g. mpi) — leave it
    try:
        jax.config.update(name, "gloo")
    except Exception:  # backend already up: leave the user's world alone
        _logger.warning(
            "init_from_env: could not select gloo CPU collectives; "
            "cross-process CPU sync may be unavailable.",
            exc_info=True,
        )


def shutdown() -> None:
    """Leave the multi-process world (no-op when not initialized)."""
    if is_initialized():
        jax.distributed.shutdown()

"""Mesh construction and batch-sharding helpers.

The reference's distributed surface is data parallelism: shard the eval
stream over ranks, merge metric states at the end (SURVEY §2.7). On TPU the
idiomatic equivalent is a 1-D ``jax.sharding.Mesh`` over a ``"data"`` axis:
batches are global arrays sharded along axis 0, metric state is replicated,
and XLA's SPMD partitioner inserts the psum/all-gather collectives over ICI
when an update kernel reduces across the batch axis. Multi-host pods use the
same code — ``jax.devices()`` spans all hosts after
``jax.distributed.initialize()``, and each host feeds its local shard via
``make_array_from_process_local_data``.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_logger = logging.getLogger(__name__)
_warned_uneven_batch = False
_warned_replicated_global = False


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices with axis name ``"data"``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), axis_names=("data",))


def shard_batch(mesh: Mesh, *arrays: jax.Array):
    """Place arrays as global jax.Arrays sharded along axis 0 of ``mesh``'s
    ``"data"`` axis. Single-process: a plain device_put with a NamedSharding.
    Multi-host callers should build global arrays with
    ``jax.make_array_from_process_local_data`` instead.

    A batch whose axis 0 is not divisible by the mesh size (the last partial
    batch of an epoch) falls back to a fully-replicated placement — results
    stay correct (replicated in, replicated out), only that batch loses the
    data-parallel speedup. Keep batch sizes a multiple of the device count
    for the hot path.
    """
    from torcheval_tpu.utils.convert import as_jax

    global _warned_uneven_batch
    n_dev = mesh.devices.size
    converted = [as_jax(a) for a in arrays]
    multiprocess = jax.process_count() > 1  # hoisted: hot path, one call
    mesh_devices = set(mesh.devices.flat) if multiprocess else None
    target_cache = {}  # ndim -> NamedSharding, avoids re-building per array

    def _target(ndim: int) -> NamedSharding:
        if ndim not in target_cache:
            target_cache[ndim] = NamedSharding(
                mesh, P("data", *([None] * (ndim - 1)))
            )
        return target_cache[ndim]

    def _already_placed(a) -> bool:
        global _warned_replicated_global
        if not isinstance(a, jax.Array):
            return False
        if multiprocess:
            # multi-process: any global array on this mesh is accepted as-is
            # (re-placing would need a cross-host transfer); layout is the
            # caller's choice via make_array_from_process_local_data
            on_mesh = getattr(a.sharding, "device_set", None) == mesh_devices
            if (
                on_mesh
                and not _warned_replicated_global
                and a.ndim
                and a.sharding.is_equivalent_to(
                    NamedSharding(mesh, P(*([None] * a.ndim))), a.ndim
                )
            ):
                # the single-controller path re-places replicated batches to
                # P('data') for exactly this reason; here that would need a
                # cross-host transfer, so warn instead of silently letting
                # every device process the full batch
                _warned_replicated_global = True
                _logger.warning(
                    "shard_batch: received a fully-replicated global batch in "
                    "a multi-process world; every device will process the "
                    "whole batch. Build data-sharded input with "
                    "jax.make_array_from_process_local_data(NamedSharding("
                    "mesh, P('data', ...)), local_shard). (warned once)"
                )
            return on_mesh
        # single-controller: bypass ONLY when the array already has the
        # target data sharding — a replicated array must still be re-placed
        # to P("data") or every device would process the full batch
        return a.sharding.is_equivalent_to(_target(a.ndim), a.ndim)

    if all(_already_placed(a) for a in converted):
        out = tuple(converted)
        return out[0] if len(out) == 1 else out
    if multiprocess:
        raise ValueError(
            "shard_batch received host-local data in a multi-process world; "
            "device_put cannot scatter host values across hosts. Build the "
            "global batch with jax.make_array_from_process_local_data("
            "NamedSharding(mesh, P('data', ...)), local_shard) and pass the "
            "resulting jax.Array instead."
        )
    if not _warned_uneven_batch and any(
        a.shape[0] % n_dev != 0 for a in converted
    ):
        _warned_uneven_batch = True
        _logger.warning(
            "shard_batch: batch axis not divisible by the %d-device mesh; "
            "falling back to a replicated placement for such batches (correct "
            "but not data-parallel). Pad batches to a multiple of the device "
            "count for full speed. (warned once)",
            n_dev,
        )
    out = tuple(
        jax.device_put(
            a,
            _target(a.ndim)
            if a.shape[0] % n_dev == 0
            else NamedSharding(mesh, P()),
        )
        for a in converted
    )
    return out[0] if len(out) == 1 else out


def replicate(mesh: Mesh, value):
    """Fully-replicated placement for metric state on ``mesh``.

    Multi-process meshes build the global array from each host's local copy
    (every host holds the same value in SPMD lockstep) instead of
    ``device_put``, which would demand a cross-host transfer most backends
    don't provide — same policy as metric state placement
    (``metrics/state.py::_put_leaf``)."""
    from torcheval_tpu.metrics.state import _put_leaf

    return _put_leaf(value, NamedSharding(mesh, P()), strict_layout=True)

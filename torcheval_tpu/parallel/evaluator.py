"""Data-parallel streaming evaluator over a device mesh.

TPU-first counterpart of the reference's DDP eval loop
(``/root/reference/examples/distributed_example.py:14-148``): instead of one
process per GPU with object-pickle state sync, one process drives the whole
mesh. Batches are **global arrays sharded along axis 0**; metric state is
**replicated**. Every update kernel (confusion counts, rank tests, binned
compares) reduces over the batch axis, so XLA's SPMD partitioner
automatically turns the per-shard partial reduction into a ``psum`` over ICI
— the typed collective the reference's ``sync_and_compute`` performs by hand,
here fused into the same compiled computation as the update math.

``compute()`` needs no sync step at all: state is already globally correct on
every chip. Cross-*process* sync for the multi-controller pattern lives in
:mod:`torcheval_tpu.metrics.toolkit`.
"""

from __future__ import annotations

from typing import Any, Dict, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.obs.annotate import traced as _traced
from torcheval_tpu.parallel.mesh import data_parallel_mesh, shard_batch


def eval_shardings(mesh: Mesh):
    """``(replicated, data_sharded)`` NamedShardings for jit annotations."""
    return NamedSharding(mesh, P()), NamedSharding(mesh, P("data"))


class ShardedEvaluator:
    """Drive one metric (or a named collection) with mesh-sharded batches.

    Args:
        metrics: a ``Metric`` or ``{name: Metric}`` dict. State is moved to a
            replicated placement on the mesh.
        mesh: 1-D data mesh; defaults to all devices.

    Example::

        ev = ShardedEvaluator({"acc": MulticlassAccuracy(num_classes=10)})
        for scores, labels in loader:
            ev.update(scores, labels)      # global sharded batch, SPMD update
        results = ev.compute()             # no sync step needed
    """

    def __init__(
        self,
        metrics: Union[Metric, Dict[str, Metric]],
        *,
        mesh: Mesh = None,
    ) -> None:
        from torcheval_tpu.metrics.collection import MetricCollection

        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        # the collection owns single-vs-dict wrapping, folds every deferred
        # member's pending batches in one SPMD program per budget window,
        # and is the delegate for compute/reset; cache metrics stay eager
        # inside it
        self._collection = MetricCollection(metrics)
        self.metrics: Dict[str, Metric] = self._collection.metrics
        replicated = NamedSharding(self.mesh, P())
        for m in self.metrics.values():
            m.to(replicated)

    @_traced("evaluator.update")
    def update(self, *args: Any, **kwargs: Any) -> "ShardedEvaluator":
        """Shard positional array arguments along the mesh data axis and
        queue them for every metric — array-state metrics defer and fold in
        one SPMD program per budget window. Keyword arguments pass through
        unsharded (weights etc. follow their positional companions' sharding
        via XLA)."""
        sharded = tuple(
            shard_batch(self.mesh, a) if _is_batch_arraylike(a) else a
            for a in args
        )
        self._collection.update(*sharded, **kwargs)
        return self

    @_traced("evaluator.compute")
    def compute(self) -> Any:
        return self._collection.compute()

    def reset(self) -> "ShardedEvaluator":
        self._collection.reset()
        return self

    # ------------------------------------------------------- checkpointing
    # the resilience snapshot engine (torcheval_tpu.resilience) talks to the
    # evaluator through the same per-member state-dict protocol as the
    # collection; restored state lands back in the metrics' replicated mesh
    # placement via each metric's own load_state_dict -> put_state.
    def state_dicts(self) -> Dict[str, Dict[str, Any]]:
        return self._collection.state_dicts()

    def load_state_dicts(
        self, state_dicts: Dict[str, Dict[str, Any]], strict: bool = True
    ) -> "ShardedEvaluator":
        self._collection.load_state_dicts(state_dicts, strict)
        return self


def _is_batch_arraylike(x: Any) -> bool:
    """Array-like with a leading batch axis (0-d scalars pass through)."""
    import numpy as np

    return (
        (hasattr(x, "shape") and hasattr(x, "dtype")) or hasattr(x, "__array__")
    ) and np.ndim(x) >= 1

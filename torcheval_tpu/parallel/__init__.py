from torcheval_tpu.parallel.bootstrap import init_from_env, is_initialized, shutdown
from torcheval_tpu.parallel.evaluator import ShardedEvaluator, eval_shardings
from torcheval_tpu.parallel.mesh import (
    data_parallel_mesh,
    replicate,
    shard_batch,
)

__all__ = [
    "ShardedEvaluator",
    "data_parallel_mesh",
    "eval_shardings",
    "init_from_env",
    "is_initialized",
    "replicate",
    "shard_batch",
    "shutdown",
]

"""Trace-safety helpers.

The reference logs warnings on degenerate values (e.g. NaN recall classes,
``recall.py:195-202``), which requires reading values back to the host. Under
``jax.jit`` those values are tracers with no concrete data, and even outside
jit a read blocks the async dispatch stream. Callers gate every such warning
on :func:`is_concrete` so jitted code stays pure and traceable; the warning
simply does not fire inside a compiled computation.
"""

from __future__ import annotations

import jax


def is_concrete(x) -> bool:
    """True when ``x`` holds real data (not a tracer inside jit/vmap/grad)."""
    return not isinstance(x, jax.core.Tracer)

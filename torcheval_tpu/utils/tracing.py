"""Trace-safety helpers.

The reference logs warnings on degenerate values (e.g. NaN recall classes,
``recall.py:195-202``), which requires reading values back to the host. Under
``jax.jit`` those values are tracers with no concrete data, and even outside
jit a read blocks the async dispatch stream. Callers gate every such warning
on :func:`is_concrete` so jitted code stays pure and traceable; the warning
simply does not fire inside a compiled computation.

Outside jit, the value readback itself is the hazard: ``np.asarray(arr)``
blocks the host until the whole queued device stream completes — on this
project's tunneled chip that is a ~0.1 s round trip INSIDE ``compute()``,
dwarfing the metric math (measured: the F1 degenerate-class warning cost a
full RTT per compute). :func:`async_value_warn` moves the readback to a
daemon thread so the warning still fires (a moment later) while the dispatch
stream runs free.
"""

from __future__ import annotations

import threading

import jax


def is_concrete(x) -> bool:
    """True when ``x`` holds real data (not a tracer inside jit/vmap/grad)."""
    return not isinstance(x, jax.core.Tracer)


_logger = __import__("logging").getLogger(__name__)


def host_resident(x) -> bool:
    """True when reading ``x``'s values costs no device sync: a non-jax
    array-like (numpy, torch-CPU) or a jax array committed to CPU devices.
    Value-dependent eager checks gate on this so a TPU-resident batch never
    blocks the dispatch stream for validation."""
    if isinstance(x, jax.core.Tracer):
        return False
    if isinstance(x, jax.Array):
        try:
            return all(d.platform == "cpu" for d in x.devices())
        except Exception:
            return False
    return hasattr(x, "__array__")


def async_value_warn(check, *arrays) -> None:
    """Run ``check(*host_values)`` — which may log a warning — on a daemon
    thread after reading ``arrays`` back to the host, without blocking the
    caller on the device stream. No-op inside a trace.

    The device→host copies are STARTED here (``copy_to_host_async``), in
    stream order, before any later dispatch can donate the buffers away; the
    thread then blocks only on those already-queued copies."""
    if not all(is_concrete(a) for a in arrays):
        return
    for a in arrays:
        try:
            a.copy_to_host_async()
        except AttributeError:
            pass  # non-jax leaf (numpy/python scalar): already on host

    def _worker() -> None:
        try:
            import numpy as np

            check(*(np.asarray(a) for a in arrays))
        except Exception:  # a dying warn thread must never kill the app
            _logger.debug("async value-warning check failed", exc_info=True)

    threading.Thread(target=_worker, daemon=True).start()

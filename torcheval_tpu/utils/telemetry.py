"""Once-per-key API-usage telemetry.

The reference logs every metric construction through
``torch._C._log_api_usage_once(f"torcheval.metrics.{cls}")``
(``/root/reference/torcheval/metrics/metric.py:44``) so fleet owners can
count which metrics are actually used. This is the framework-neutral
equivalent: a ``logging``-based hook that emits one DEBUG record per unique
key per process on the ``torcheval_tpu.api_usage`` logger, plus a
registration point for a custom sink (e.g. a production telemetry client).

The hot-path cost is a set lookup — no handler work unless a sink or a
DEBUG-level handler is attached, and never more than once per key.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Set

_logger = logging.getLogger("torcheval_tpu.api_usage")

_seen: Set[str] = set()
_seen_lock = threading.Lock()
_sink: Optional[Callable[[str], None]] = None


def set_api_usage_sink(sink: Optional[Callable[[str], None]]) -> None:
    """Install a callable invoked once per unique API-usage key (or ``None``
    to remove it). Mirrors how ``torch._C._log_api_usage_once`` feeds
    deployment-side usage counters."""
    global _sink
    _sink = sink


def _first_time(key: str) -> bool:
    """True exactly once per unique key per process (thread-safe)."""
    if key in _seen:  # lock-free fast path for the already-seen common case
        return False
    with _seen_lock:
        if key in _seen:
            return False
        _seen.add(key)
    return True


def log_api_usage_once(key: str) -> None:
    """Record one use of ``key`` (e.g. ``"torcheval_tpu.metrics.BinaryAUROC"``);
    subsequent calls with the same key are no-ops."""
    if not _first_time(key):
        return
    _logger.debug("API usage: %s", key)
    if _sink is not None:
        try:
            _sink(key)
        except Exception:  # a broken sink must never break metric construction
            _logger.exception("api-usage sink failed for key %r", key)


def log_once(
    key: str, message: str, *args, level: int = logging.WARNING
) -> None:
    """Emit ``message % args`` through the telemetry logger once per unique
    ``key`` — the once-per-key machinery behind :func:`log_api_usage_once`,
    opened up for in-library watchdogs (e.g. the recompile watchdog,
    ``obs/recompile.py``) whose warnings must not spam a hot loop."""
    if not _first_time(key):
        return
    _logger.log(level, message, *args)


def reset_once_keys(prefix: str = "") -> None:
    """Forget recorded once-per-key keys starting with ``prefix`` (every key
    when empty). Test/tooling hook: lets a fresh run re-arm its warnings."""
    with _seen_lock:
        if not prefix:
            _seen.clear()
        else:
            for k in [k for k in _seen if k.startswith(prefix)]:
                _seen.discard(k)

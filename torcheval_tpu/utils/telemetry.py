"""Once-per-key API-usage telemetry.

The reference logs every metric construction through
``torch._C._log_api_usage_once(f"torcheval.metrics.{cls}")``
(``/root/reference/torcheval/metrics/metric.py:44``) so fleet owners can
count which metrics are actually used. This is the framework-neutral
equivalent: a ``logging``-based hook that emits one DEBUG record per unique
key per process on the ``torcheval_tpu.api_usage`` logger, plus a
registration point for a custom sink (e.g. a production telemetry client).

The hot-path cost is a set lookup — no handler work unless a sink or a
DEBUG-level handler is attached, and never more than once per key.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Set

_logger = logging.getLogger("torcheval_tpu.api_usage")

_seen: Set[str] = set()
_seen_lock = threading.Lock()
_sink: Optional[Callable[[str], None]] = None


def set_api_usage_sink(sink: Optional[Callable[[str], None]]) -> None:
    """Install a callable invoked once per unique API-usage key (or ``None``
    to remove it). Mirrors how ``torch._C._log_api_usage_once`` feeds
    deployment-side usage counters."""
    global _sink
    _sink = sink


def log_api_usage_once(key: str) -> None:
    """Record one use of ``key`` (e.g. ``"torcheval_tpu.metrics.BinaryAUROC"``);
    subsequent calls with the same key are no-ops."""
    if key in _seen:  # lock-free fast path for the already-seen common case
        return
    with _seen_lock:
        if key in _seen:
            return
        _seen.add(key)
    _logger.debug("API usage: %s", key)
    if _sink is not None:
        try:
            _sink(key)
        except Exception:  # a broken sink must never break metric construction
            _logger.exception("api-usage sink failed for key %r", key)

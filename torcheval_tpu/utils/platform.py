"""Backend capability probes.

One quirk matters enough to gate on: buffer donation through a *tunneled*
device client (the ``axon`` PJRT plugin that proxies a remote TPU chip)
breaks execution pipelining — a chain of donated-state dispatches was
measured at 5.2 ms/step against 0.7 ms/step for the identical chain without
donation (the client must confirm the donated buffer's hand-back before it
can enqueue the next step, so every dispatch pays a tunnel round trip).
On directly-attached TPUs donation is a straight win (no allocation, state
updates in place in HBM) and stays on.
"""

from __future__ import annotations

import functools


@functools.lru_cache(maxsize=None)
def donation_pipelines() -> bool:
    """False when the default backend is a tunneled client on which donated
    dispatches serialise; True on real local devices (TPU/CPU/GPU)."""
    try:
        import jax._src.xla_bridge as xb

        version = getattr(xb.get_backend(), "platform_version", "") or ""
    except Exception:
        # private API may move between jax versions; default to donating
        return True
    return "axon" not in version


def force_cpu_devices(n: int) -> None:
    """Pin this process to an ``n``-device CPU backend, across jax versions:
    newer jax has the ``jax_num_cpu_devices`` config option; older jax only
    honors the XLA flag. Must run before first backend use either way (a
    later call into an already-initialised backend raises RuntimeError from
    jax.config.update, which propagates — callers that tolerate an existing
    backend catch it and verify the device count themselves)."""
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()

"""Small shared numeric helpers for trace-safe kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def safe_div(num, den, *, fill=0.0) -> jax.Array:
    """``num / den`` with ``fill`` where ``den == 0``.

    Branch-free and jit-embeddable: the guarded denominator keeps the untaken
    division from producing inf/nan (which would still propagate through
    ``jnp.where`` gradients and debug-nan checks).
    """
    zero = den == 0
    return jnp.where(zero, fill, num / jnp.where(zero, 1.0, den))

"""Array-conversion helpers, including the zero-copy torch bridge.

The reference consumes ``torch.Tensor`` everywhere. Here every public entry
point funnels through :func:`as_jax` so callers can pass ``jax.Array``, numpy,
Python scalars/sequences, *or* ``torch.Tensor`` (converted via dlpack — the
bridge required by BASELINE.json so existing PyTorch eval loops can offload
metric computation to TPU without code changes).
"""

from __future__ import annotations

import sys
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _torch_module():
    return sys.modules.get("torch")


def _is_torch_tensor(x: Any) -> bool:
    torch = _torch_module()
    return torch is not None and isinstance(x, torch.Tensor)


def as_jax(x: Any, dtype=None) -> jax.Array:
    """Convert ``x`` to a ``jax.Array``.

    ``torch.Tensor`` inputs go through dlpack (zero-copy on CPU / same-device);
    anything else through ``jnp.asarray``.
    """
    if isinstance(x, jax.Array):
        return x if dtype is None else x.astype(dtype)
    if _is_torch_tensor(x):
        x = x.detach()
        if x.device.type != "cpu":
            # dlpack handles same-backend exchange; cross-backend falls back to host.
            x = x.cpu()
        if x.dtype == _torch_module().bool:
            arr = jnp.asarray(x.numpy())
        else:
            try:
                arr = jnp.from_dlpack(x)
            except Exception:
                arr = jnp.asarray(np.asarray(x))
        return arr if dtype is None else arr.astype(dtype)
    return jnp.asarray(x, dtype=dtype)


def to_numpy(x: Any) -> np.ndarray:
    """Device → host transfer."""
    return np.asarray(x)

"""Device / placement helpers.

The reference framework threads a ``torch.device`` through every metric
(``/root/reference/torcheval/metrics/metric.py:49-50``). The TPU-native
equivalent is a ``jax.Device`` *or* a ``jax.sharding.Sharding``: metric state is
a pytree of ``jax.Array`` s that can live on one chip or be laid out across a
mesh. ``None`` means "JAX's default device" (the first TPU chip when present).
"""

from __future__ import annotations

from typing import Optional, Union

import jax

DeviceLike = Union[str, jax.Device, jax.sharding.Sharding, None]


def canonical_device(device: DeviceLike) -> Union[jax.Device, jax.sharding.Sharding]:
    """Resolve a user-supplied device spec to a concrete placement.

    Accepts a ``jax.Device``, a ``jax.sharding.Sharding``, a platform string
    (``"cpu"``, ``"tpu"``), or ``None`` (default device).
    """
    if device is None:
        # local_devices, not devices: in a multi-process world the global
        # list leads with rank 0's device, which other ranks cannot address
        return jax.local_devices()[0]
    if isinstance(device, jax.sharding.Sharding):
        return device
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, str):
        devs = [d for d in jax.devices(device) if d.process_index == jax.process_index()]
        if not devs:
            raise ValueError(f"No devices found for platform {device!r}.")
        return devs[0]
    raise TypeError(
        f"device must be a jax.Device, jax.sharding.Sharding, str or None, "
        f"got {type(device)!r}."
    )


def device_of(x: jax.Array) -> Optional[jax.Device]:
    """Best-effort single device of an array (None for multi-device arrays)."""
    try:
        return list(x.devices())[0] if len(x.devices()) == 1 else None
    except Exception:
        return None

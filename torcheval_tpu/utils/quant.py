"""Byte-shrinking codecs shared by the sync wire and the cluster wire.

ISSUE 12: every sync lane's payload is observable and the WINDOW lane is
bounded, so the next throughput multiplier is shrinking the bytes
themselves — on BOTH transports. This module holds the host-side
primitives; the device-side analogue (int8 exchange columns + bf16
splitter histograms inside the ``shard_map`` kernels) lives in
``ops/dist_curves.py``.

Three primitives, two loss classes:

* **narrow-int** (lossless): an integer array whose value *span* fits a
  narrower unsigned width ships as ``min`` (8 bytes) + ``width`` (1 byte)
  + ``(x - min)`` in that width. Decoding widens back to the declared
  dtype before any accumulation, so folding narrowed count lanes is
  bit-exact (*widened accumulation* — the EQuARX framing for integer
  payloads).
* **delta-int** (lossless): the cluster-wire variant — consecutive
  differences (computed in int64), then the same min-offset narrowing.
  Monotone sequences (timestamps, sorted ids) narrow to their step size;
  bounded-range data (class labels) narrows like narrow-int.
* **q8 block quantization** (bounded error): EQuARX-style int8 blocks of
  :data:`Q8_BLOCK` elements with one f32 scale per block
  (``scale = max|block| / 127``). Per-element error is bounded by
  ``scale / 2 = max|block| / 254 < 2^-7.98 · max|block|`` (≈ ``2^15``
  ulps of the f32 block max); encoded size is ``n + 4·ceil(n/256)``
  bytes vs ``4n`` raw (~3.94×). Non-finite blocks do not quantize —
  callers fall back to the raw lane (the dist-curves error-channel
  shape: detect, never silently corrupt).

Every encoder returns ``None`` when encoding would not shrink the
payload (scalars, tiny arrays, already-narrow dtypes, spans too wide),
so a codec can be applied unconditionally and degrade to raw per entry.
Arrays below :data:`Q8_MIN_ELEMENTS` never quantize: small f32 states
(the scalar ``Sum``/accuracy counters most metrics carry) stay bit-exact
even with quantization forced on fleet-wide.
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "Q8_BLOCK",
    "Q8_MIN_ELEMENTS",
    "sync_quantize_enabled",
    "sync_quantize_mode",
    "wire_codec_default",
    "bucket_payload_encode",
    "bucket_payload_decode",
    "q8_parts",
    "q8_from_parts",
    "q8_encode",
    "q8_decode",
    "narrow_int_encode",
    "narrow_int_decode",
    "delta_int_parts",
    "delta_int_from_parts",
    "delta_int_encode",
    "delta_int_decode",
]

# elements per q8 block (one f32 scale each). 256 keeps the scale
# overhead at ~1.6% while bounding each element's error to its own
# block's dynamic range, not the whole array's.
Q8_BLOCK = 256

# below this element count quantization cannot meaningfully win (the
# scale overhead eats the gain) and scalar states would lose exactness
# for nothing — they stay raw even when quantization is forced on.
Q8_MIN_ELEMENTS = 64

_SYNC_QUANTIZE_ENV = "TORCHEVAL_TPU_SYNC_QUANTIZE"
_WIRE_CODEC_ENV = "TORCHEVAL_TPU_WIRE_CODEC"


# env spellings that mean "off" — mirrored from the TORCHEVAL_TPU_APPROX
# parser so 'false'/'off' never silently ENABLE the thing they try to
# disable (review finding); values are compared case-insensitively
_QUANTIZE_OFF = ("0", "", "false", "off")


def _sync_quantize_env() -> str:
    return os.environ.get(_SYNC_QUANTIZE_ENV, "0").strip().lower()


def sync_quantize_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the metric-sync quantization knob: an explicit per-call
    ``quantize=`` wins; otherwise the ``TORCHEVAL_TPU_SYNC_QUANTIZE``
    environment variable — ``0``/empty/``false``/``off`` = off (any
    case), ``1``/``true``/``on``/``bf16``/``int8`` = on, anything else
    raises (delegated to :func:`sync_quantize_mode` so the env is
    validated identically everywhere)."""
    if override is not None:
        return bool(override)
    return sync_quantize_mode() is not False


def sync_quantize_mode(override=None):
    """The dist_curves splitter-histogram reduction mode behind the same
    knob: ``False`` (exact int32 psum), ``"bf16"`` (half the fixed round —
    ``quantize=True`` / env ``"1"``, the ISSUE 12 behavior) or ``"int8"``
    (the EQuARX int8-chunked reduce-scatter/all-gather qpsum — quarter the
    bytes at +2 small scale collectives; ``quantize="int8"`` / env
    ``"int8"``, case-insensitive). Either lossy mode can only shift
    splitter placement, never curve values (``ops/dist_curves.py``,
    "Quantized exchange")."""
    if override is not None:
        if isinstance(override, str):
            # strings are mode names: validate, don't alias a typo like
            # "INT8"/"in8t" to the bf16 mode via truthiness (review
            # finding) — the repo's knob-string convention raises
            mode = override.strip().lower()
            if mode not in ("bf16", "int8"):
                raise ValueError(
                    f'quantize mode must be "bf16" or "int8" (or a bool), '
                    f"got {override!r}."
                )
            return mode
        return "bf16" if override else False
    env = _sync_quantize_env()
    if env in _QUANTIZE_OFF:
        return False
    if env == "int8":
        return "int8"
    if env in ("1", "true", "on", "bf16"):
        return "bf16"
    # same rationale as the override path: a typo ("in8t") must not
    # silently alias to a different lossy mode
    raise ValueError(
        f"{_SYNC_QUANTIZE_ENV} must be 0/1/true/false/on/off/bf16/int8, "
        f"got {env!r}."
    )


def wire_codec_default() -> str:
    """The cluster-wire codec a client prefers when none is passed:
    ``TORCHEVAL_TPU_WIRE_CODEC`` (``raw`` / ``delta`` / ``qblk``),
    default ``raw``. ``delta`` is lossless and safe fleet-wide; ``qblk``
    additionally block-quantizes f32 leaves (bounded error, see module
    doc) and is an explicit opt-in."""
    return os.environ.get(_WIRE_CODEC_ENV, "raw")


# ------------------------------------------------------- q8 block quant
def q8_parts(
    arr: np.ndarray, *, check_finite: bool = True
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Block-quantize a float32 array: ``(scales f32[nblocks], q int8[n])``
    or ``None`` when the array is too small, non-f32, or non-finite
    (caller falls back to raw — the error-channel contract).
    ``check_finite=False`` skips the finiteness scan for callers that
    already ran it (the sync wire checks once to count its fallback) —
    non-finite input then produces garbage, so only pass it after a real
    check."""
    if arr.dtype != np.float32 or arr.size < Q8_MIN_ELEMENTS:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    if check_finite and not np.isfinite(flat).all():
        return None
    n = flat.size
    nblocks = -(-n // Q8_BLOCK)
    pad = nblocks * Q8_BLOCK - n
    padded = np.concatenate([flat, np.zeros(pad, np.float32)]) if pad else flat
    blocks = padded.reshape(nblocks, Q8_BLOCK)
    scales = (np.abs(blocks).max(axis=1) / 127.0).astype(np.float32)
    safe = np.where(scales == 0.0, np.float32(1.0), scales)
    q = np.clip(np.rint(blocks / safe[:, None]), -127, 127).astype(np.int8)
    return scales, q.reshape(-1)[:n]


def q8_from_parts(
    scales: np.ndarray, q: np.ndarray, shape: Tuple[int, ...]
) -> np.ndarray:
    """Dequantize :func:`q8_parts` output back to float32 of ``shape``."""
    scales = np.asarray(scales, dtype=np.float32).reshape(-1)
    q = np.asarray(q, dtype=np.int8).reshape(-1)
    n = q.size
    nblocks = scales.size
    pad = nblocks * Q8_BLOCK - n
    padded = (
        np.concatenate([q, np.zeros(pad, np.int8)]) if pad else q
    ).reshape(nblocks, Q8_BLOCK)
    out = (padded.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return out.reshape(shape)


def q8_encode(
    arr: np.ndarray, *, check_finite: bool = True
) -> Optional[bytes]:
    """:func:`q8_parts` as one byte string (scales then quants) for the
    sync wire's concatenated payload round. ``None`` when quantization
    does not apply or would not shrink the entry."""
    parts = q8_parts(arr, check_finite=check_finite)
    if parts is None:
        return None
    scales, q = parts
    out = scales.tobytes() + q.tobytes()
    return out if len(out) < arr.nbytes else None


def q8_decode(buf: bytes, shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`q8_encode` (shape comes from the descriptor)."""
    n = 1
    for d in shape:
        n *= int(d)
    nblocks = -(-n // Q8_BLOCK)
    scales = np.frombuffer(buf, dtype=np.float32, count=nblocks)
    q = np.frombuffer(buf, dtype=np.int8, count=n, offset=4 * nblocks)
    return q8_from_parts(scales, q, shape)


# ------------------------------------------------------------ narrow-int
_NARROW_HEAD = struct.Struct("<qB")  # int64 min, uint8 byte width


def _narrow_width(span: int) -> Optional[int]:
    if span <= 0xFF:
        return 1
    if span <= 0xFFFF:
        return 2
    if span <= 0xFFFFFFFF:
        return 4
    return None


def narrow_int_encode(arr: np.ndarray) -> Optional[bytes]:
    """Lossless min-offset narrowing of an integer array; ``None`` when
    it would not shrink (empty, span too wide, dtype already narrow, or
    values outside int64's exact range)."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    lo, hi = int(flat.min()), int(flat.max())
    if lo < -(2**63) or hi >= 2**63:  # uint64 beyond int64: bail
        return None
    width = _narrow_width(hi - lo)
    if width is None or width >= arr.dtype.itemsize:
        return None
    data = (flat.astype(np.int64) - lo).astype(f"<u{width}")
    out = _NARROW_HEAD.pack(lo, width) + data.tobytes()
    return out if len(out) < arr.nbytes else None


def narrow_int_decode(
    buf: bytes, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`narrow_int_encode`, widening back to ``dtype``
    BEFORE any accumulation touches the values (bit-exact folds)."""
    lo, width = _NARROW_HEAD.unpack_from(buf)
    n = 1
    for d in shape:
        n *= int(d)
    data = np.frombuffer(
        buf, dtype=f"<u{width}", count=n, offset=_NARROW_HEAD.size
    )
    return (data.astype(np.int64) + lo).astype(dtype).reshape(shape)


# ------------------------------------------------------------- delta-int
def delta_int_parts(
    arr: np.ndarray,
) -> Optional[Tuple[int, np.ndarray]]:
    """Delta + min-offset narrowing for the cluster wire: returns
    ``(offset, deltas-minus-offset as a narrow unsigned array)`` or
    ``None`` when it would not shrink. Lossless: ``cumsum`` of the
    restored int64 deltas reproduces the values exactly."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    lo, hi = int(flat.min()), int(flat.max())
    if lo < -(2**62) or hi >= 2**62:  # keep every delta exact in int64
        return None
    d = np.diff(flat.astype(np.int64), prepend=np.int64(0))
    dlo = int(d.min())
    width = _narrow_width(int(d.max()) - dlo)
    if width is None or width >= arr.dtype.itemsize:
        return None
    return dlo, (d - dlo).astype(f"<u{width}")


def delta_int_from_parts(
    data: np.ndarray, offset: int, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`delta_int_parts`."""
    d = np.asarray(data).astype(np.int64) + int(offset)
    return np.cumsum(d).astype(dtype).reshape(shape)


def delta_int_encode(arr: np.ndarray) -> Optional[bytes]:
    """:func:`delta_int_parts` as one byte string (same header layout as
    narrow-int: int64 offset + uint8 width + data)."""
    parts = delta_int_parts(arr)
    if parts is None:
        return None
    offset, data = parts
    out = _NARROW_HEAD.pack(offset, data.dtype.itemsize) + data.tobytes()
    return out if len(out) < arr.nbytes else None


def delta_int_decode(
    buf: bytes, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`delta_int_encode`."""
    offset, width = _NARROW_HEAD.unpack_from(buf)
    n = 1
    for d in shape:
        n *= int(d)
    data = np.frombuffer(
        buf, dtype=f"<u{width}", count=n, offset=_NARROW_HEAD.size
    )
    return delta_int_from_parts(data, offset, dtype, shape)


# ----------------------------------------------------------- bucket payload
# ISSUE 13 / ROADMAP 1(c): the resident sketch state (fixed-size bucket
# histograms — CAT's approx mode, the curve sketches, Quantile) is int32
# counts that are typically SPARSE: a stream's score cardinality occupies a
# small fraction of the 2^16 buckets. Min-offset narrowing alone still ships
# every zero; this codec ships only the nonzero buckets — delta-narrowed
# indices (sorted, so deltas are tiny) plus narrowed values — and degrades
# per part: the index block falls back to raw u32, the value block to raw
# dtype bytes, and the whole encoder to None when it would not shrink.
# Decode is faithful for ANY integer array (scatter into zeros), so the
# sync wire may offer it on every integer lane and pick the smaller of
# narrow/bucket per entry.
_BUCKET_HEAD = struct.Struct("<IBBI")  # nnz, idx_mode, val_mode, idx_nbytes
_BUCKET_RAW, _BUCKET_PACKED = 0, 1


def bucket_payload_encode(arr: np.ndarray) -> Optional[bytes]:
    """Sparse nonzero encoding of an integer bucket-count array; ``None``
    when it would not shrink the raw payload (dense arrays — the caller
    then tries/keeps min-offset narrowing)."""
    if arr.dtype.kind not in "iu" or arr.size == 0 or arr.size >= 2**32:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    idx = np.flatnonzero(flat)
    if idx.size >= 2**32:
        return None
    # dense lower bound: the output can never beat header + 1 index byte +
    # 1 value byte per nonzero — bail before building the real encodings
    # (a dense lane on the sync hot path otherwise pays flatnonzero +
    # int64 index copies + two encoders just to fail the final size check)
    if _BUCKET_HEAD.size + 2 * idx.size >= arr.nbytes:
        return None
    if idx.size == 0:
        out = _BUCKET_HEAD.pack(0, _BUCKET_RAW, _BUCKET_RAW, 0)
        return out if len(out) < arr.nbytes else None
    vals = flat[idx]
    idx_enc = delta_int_encode(idx.astype(np.int64))
    if idx_enc is not None:
        idx_mode, idx_part = _BUCKET_PACKED, idx_enc
    else:  # tiny nnz: the delta header does not amortize
        idx_mode, idx_part = _BUCKET_RAW, idx.astype("<u4").tobytes()
    val_enc = narrow_int_encode(vals)
    if val_enc is not None:
        val_mode, val_part = _BUCKET_PACKED, val_enc
    else:
        val_mode, val_part = _BUCKET_RAW, vals.tobytes()
    out = (
        _BUCKET_HEAD.pack(idx.size, idx_mode, val_mode, len(idx_part))
        + idx_part
        + val_part
    )
    return out if len(out) < arr.nbytes else None


def bucket_payload_decode(
    buf: bytes, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`bucket_payload_encode`: scatter the nonzero
    values back into a zeros array of the declared dtype/shape (widening
    happens before any accumulation — bit-exact folds, the narrow-int
    contract)."""
    nnz, idx_mode, val_mode, idx_nbytes = _BUCKET_HEAD.unpack_from(buf)
    out = np.zeros(shape, dtype=dtype).reshape(-1)
    if nnz == 0:
        return out.reshape(shape)
    off = _BUCKET_HEAD.size
    idx_buf = buf[off : off + idx_nbytes]
    if idx_mode == _BUCKET_PACKED:
        idx = delta_int_decode(idx_buf, np.dtype(np.int64), (nnz,))
    else:
        idx = np.frombuffer(idx_buf, dtype="<u4", count=nnz).astype(np.int64)
    val_buf = buf[off + idx_nbytes :]
    if val_mode == _BUCKET_PACKED:
        vals = narrow_int_decode(val_buf, dtype, (nnz,))
    else:
        vals = np.frombuffer(val_buf, dtype=dtype, count=nnz)
    out[idx] = vals
    return out.reshape(shape)

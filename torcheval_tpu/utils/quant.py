"""Byte-shrinking codecs shared by the sync wire and the cluster wire.

ISSUE 12: every sync lane's payload is observable and the WINDOW lane is
bounded, so the next throughput multiplier is shrinking the bytes
themselves — on BOTH transports. This module holds the host-side
primitives; the device-side analogue (int8 exchange columns + bf16
splitter histograms inside the ``shard_map`` kernels) lives in
``ops/dist_curves.py``.

Three primitives, two loss classes:

* **narrow-int** (lossless): an integer array whose value *span* fits a
  narrower unsigned width ships as ``min`` (8 bytes) + ``width`` (1 byte)
  + ``(x - min)`` in that width. Decoding widens back to the declared
  dtype before any accumulation, so folding narrowed count lanes is
  bit-exact (*widened accumulation* — the EQuARX framing for integer
  payloads).
* **delta-int** (lossless): the cluster-wire variant — consecutive
  differences (computed in int64), then the same min-offset narrowing.
  Monotone sequences (timestamps, sorted ids) narrow to their step size;
  bounded-range data (class labels) narrows like narrow-int.
* **q8 block quantization** (bounded error): EQuARX-style int8 blocks of
  :data:`Q8_BLOCK` elements with one f32 scale per block
  (``scale = max|block| / 127``). Per-element error is bounded by
  ``scale / 2 = max|block| / 254 < 2^-7.98 · max|block|`` (≈ ``2^15``
  ulps of the f32 block max); encoded size is ``n + 4·ceil(n/256)``
  bytes vs ``4n`` raw (~3.94×). Non-finite blocks do not quantize —
  callers fall back to the raw lane (the dist-curves error-channel
  shape: detect, never silently corrupt).

Every encoder returns ``None`` when encoding would not shrink the
payload (scalars, tiny arrays, already-narrow dtypes, spans too wide),
so a codec can be applied unconditionally and degrade to raw per entry.
Arrays below :data:`Q8_MIN_ELEMENTS` never quantize: small f32 states
(the scalar ``Sum``/accuracy counters most metrics carry) stay bit-exact
even with quantization forced on fleet-wide.
"""

from __future__ import annotations

import os
import struct
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "Q8_BLOCK",
    "Q8_MIN_ELEMENTS",
    "sync_quantize_enabled",
    "wire_codec_default",
    "q8_parts",
    "q8_from_parts",
    "q8_encode",
    "q8_decode",
    "narrow_int_encode",
    "narrow_int_decode",
    "delta_int_parts",
    "delta_int_from_parts",
    "delta_int_encode",
    "delta_int_decode",
]

# elements per q8 block (one f32 scale each). 256 keeps the scale
# overhead at ~1.6% while bounding each element's error to its own
# block's dynamic range, not the whole array's.
Q8_BLOCK = 256

# below this element count quantization cannot meaningfully win (the
# scale overhead eats the gain) and scalar states would lose exactness
# for nothing — they stay raw even when quantization is forced on.
Q8_MIN_ELEMENTS = 64

_SYNC_QUANTIZE_ENV = "TORCHEVAL_TPU_SYNC_QUANTIZE"
_WIRE_CODEC_ENV = "TORCHEVAL_TPU_WIRE_CODEC"


def sync_quantize_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the metric-sync quantization knob: an explicit per-call
    ``quantize=`` wins; otherwise the ``TORCHEVAL_TPU_SYNC_QUANTIZE``
    environment variable (``"1"`` = on); default off."""
    if override is not None:
        return bool(override)
    return os.environ.get(_SYNC_QUANTIZE_ENV, "0") == "1"


def wire_codec_default() -> str:
    """The cluster-wire codec a client prefers when none is passed:
    ``TORCHEVAL_TPU_WIRE_CODEC`` (``raw`` / ``delta`` / ``qblk``),
    default ``raw``. ``delta`` is lossless and safe fleet-wide; ``qblk``
    additionally block-quantizes f32 leaves (bounded error, see module
    doc) and is an explicit opt-in."""
    return os.environ.get(_WIRE_CODEC_ENV, "raw")


# ------------------------------------------------------- q8 block quant
def q8_parts(
    arr: np.ndarray, *, check_finite: bool = True
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Block-quantize a float32 array: ``(scales f32[nblocks], q int8[n])``
    or ``None`` when the array is too small, non-f32, or non-finite
    (caller falls back to raw — the error-channel contract).
    ``check_finite=False`` skips the finiteness scan for callers that
    already ran it (the sync wire checks once to count its fallback) —
    non-finite input then produces garbage, so only pass it after a real
    check."""
    if arr.dtype != np.float32 or arr.size < Q8_MIN_ELEMENTS:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    if check_finite and not np.isfinite(flat).all():
        return None
    n = flat.size
    nblocks = -(-n // Q8_BLOCK)
    pad = nblocks * Q8_BLOCK - n
    padded = np.concatenate([flat, np.zeros(pad, np.float32)]) if pad else flat
    blocks = padded.reshape(nblocks, Q8_BLOCK)
    scales = (np.abs(blocks).max(axis=1) / 127.0).astype(np.float32)
    safe = np.where(scales == 0.0, np.float32(1.0), scales)
    q = np.clip(np.rint(blocks / safe[:, None]), -127, 127).astype(np.int8)
    return scales, q.reshape(-1)[:n]


def q8_from_parts(
    scales: np.ndarray, q: np.ndarray, shape: Tuple[int, ...]
) -> np.ndarray:
    """Dequantize :func:`q8_parts` output back to float32 of ``shape``."""
    scales = np.asarray(scales, dtype=np.float32).reshape(-1)
    q = np.asarray(q, dtype=np.int8).reshape(-1)
    n = q.size
    nblocks = scales.size
    pad = nblocks * Q8_BLOCK - n
    padded = (
        np.concatenate([q, np.zeros(pad, np.int8)]) if pad else q
    ).reshape(nblocks, Q8_BLOCK)
    out = (padded.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return out.reshape(shape)


def q8_encode(
    arr: np.ndarray, *, check_finite: bool = True
) -> Optional[bytes]:
    """:func:`q8_parts` as one byte string (scales then quants) for the
    sync wire's concatenated payload round. ``None`` when quantization
    does not apply or would not shrink the entry."""
    parts = q8_parts(arr, check_finite=check_finite)
    if parts is None:
        return None
    scales, q = parts
    out = scales.tobytes() + q.tobytes()
    return out if len(out) < arr.nbytes else None


def q8_decode(buf: bytes, shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of :func:`q8_encode` (shape comes from the descriptor)."""
    n = 1
    for d in shape:
        n *= int(d)
    nblocks = -(-n // Q8_BLOCK)
    scales = np.frombuffer(buf, dtype=np.float32, count=nblocks)
    q = np.frombuffer(buf, dtype=np.int8, count=n, offset=4 * nblocks)
    return q8_from_parts(scales, q, shape)


# ------------------------------------------------------------ narrow-int
_NARROW_HEAD = struct.Struct("<qB")  # int64 min, uint8 byte width


def _narrow_width(span: int) -> Optional[int]:
    if span <= 0xFF:
        return 1
    if span <= 0xFFFF:
        return 2
    if span <= 0xFFFFFFFF:
        return 4
    return None


def narrow_int_encode(arr: np.ndarray) -> Optional[bytes]:
    """Lossless min-offset narrowing of an integer array; ``None`` when
    it would not shrink (empty, span too wide, dtype already narrow, or
    values outside int64's exact range)."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    lo, hi = int(flat.min()), int(flat.max())
    if lo < -(2**63) or hi >= 2**63:  # uint64 beyond int64: bail
        return None
    width = _narrow_width(hi - lo)
    if width is None or width >= arr.dtype.itemsize:
        return None
    data = (flat.astype(np.int64) - lo).astype(f"<u{width}")
    out = _NARROW_HEAD.pack(lo, width) + data.tobytes()
    return out if len(out) < arr.nbytes else None


def narrow_int_decode(
    buf: bytes, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`narrow_int_encode`, widening back to ``dtype``
    BEFORE any accumulation touches the values (bit-exact folds)."""
    lo, width = _NARROW_HEAD.unpack_from(buf)
    n = 1
    for d in shape:
        n *= int(d)
    data = np.frombuffer(
        buf, dtype=f"<u{width}", count=n, offset=_NARROW_HEAD.size
    )
    return (data.astype(np.int64) + lo).astype(dtype).reshape(shape)


# ------------------------------------------------------------- delta-int
def delta_int_parts(
    arr: np.ndarray,
) -> Optional[Tuple[int, np.ndarray]]:
    """Delta + min-offset narrowing for the cluster wire: returns
    ``(offset, deltas-minus-offset as a narrow unsigned array)`` or
    ``None`` when it would not shrink. Lossless: ``cumsum`` of the
    restored int64 deltas reproduces the values exactly."""
    if arr.dtype.kind not in "iu" or arr.size == 0:
        return None
    flat = np.ascontiguousarray(arr).reshape(-1)
    lo, hi = int(flat.min()), int(flat.max())
    if lo < -(2**62) or hi >= 2**62:  # keep every delta exact in int64
        return None
    d = np.diff(flat.astype(np.int64), prepend=np.int64(0))
    dlo = int(d.min())
    width = _narrow_width(int(d.max()) - dlo)
    if width is None or width >= arr.dtype.itemsize:
        return None
    return dlo, (d - dlo).astype(f"<u{width}")


def delta_int_from_parts(
    data: np.ndarray, offset: int, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`delta_int_parts`."""
    d = np.asarray(data).astype(np.int64) + int(offset)
    return np.cumsum(d).astype(dtype).reshape(shape)


def delta_int_encode(arr: np.ndarray) -> Optional[bytes]:
    """:func:`delta_int_parts` as one byte string (same header layout as
    narrow-int: int64 offset + uint8 width + data)."""
    parts = delta_int_parts(arr)
    if parts is None:
        return None
    offset, data = parts
    out = _NARROW_HEAD.pack(offset, data.dtype.itemsize) + data.tobytes()
    return out if len(out) < arr.nbytes else None


def delta_int_decode(
    buf: bytes, dtype: np.dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Inverse of :func:`delta_int_encode`."""
    offset, width = _NARROW_HEAD.unpack_from(buf)
    n = 1
    for d in shape:
        n *= int(d)
    data = np.frombuffer(
        buf, dtype=f"<u{width}", count=n, offset=_NARROW_HEAD.size
    )
    return delta_int_from_parts(data, offset, dtype, shape)

from torcheval_tpu.utils.convert import as_jax, to_numpy
from torcheval_tpu.utils.devices import canonical_device

__all__ = ["as_jax", "to_numpy", "canonical_device"]

"""Zero-copy ``npz`` decoding: array views over the archive's own bytes.

``np.load(io.BytesIO(payload))`` copies every leaf twice on the ingest hot
path — once out of the zip member stream into a fresh ``bytes`` object and
once into the returned array — which made npz decode the single largest
host allocation site on the cluster wire (ISSUE 11). An *uncompressed*
npz (what :func:`np.savez` writes) needs neither copy: every member is
STORED, its ``.npy`` header pads the data to a 64-byte boundary, and the
dtype/shape metadata is a one-line literal — so each leaf can be a
``np.frombuffer`` view straight into the archive buffer.

:func:`npz_views` implements exactly that, with per-leaf fallbacks that
reproduce ``np.load(..., allow_pickle=False)`` semantics byte for byte:

* DEFLATED members, structured/object descrs, misaligned data, or any
  header surprise fall back to the stdlib ``zipfile`` + ``np.lib.format``
  copy path for THAT leaf only (``allow_pickle=False``, so an object
  array still raises ``ValueError`` exactly like ``np.load``);
* an archive that is not a zip at all returns the same errors the
  ``np.load`` path would, so callers keep their existing error mapping.

The returned views hold a reference to ``buf`` (via ``ndarray.base``), so
the archive buffer lives as long as any leaf does — the property the
serve ingest pool leans on for its aliasing contract. Views are read-only
when ``buf`` is (a ``bytes`` payload); metric updates only read.

CRC note: the zero-copy path does not verify member CRCs (reading the
data to checksum it would be the copy this module exists to avoid). Both
producers that feed it already carry stronger integrity: the eval wire
rides TCP checksums and the checkpoint payload is sha256-verified before
decode.
"""

from __future__ import annotations

import ast
import io
import struct
import zipfile
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["npz_views", "NPZ_FORMAT_ERRORS"]

# the exception classes a caller should treat as "undecodable archive" —
# the same set the np.load path surfaces
NPZ_FORMAT_ERRORS = (ValueError, OSError, KeyError, zipfile.BadZipFile)

_LOCAL_HEADER_LEN = 30  # fixed part of a zip local file header
_NPY_MAGIC = b"\x93NUMPY"


class _BufferIO(io.RawIOBase):
    """Read-only file object over a buffer WITHOUT copying it up front
    (``io.BytesIO(memoryview)`` copies at construction). ``zipfile`` reads
    only the central directory and local headers through this on the
    zero-copy path, so the per-read ``bytes`` slices stay tiny."""

    def __init__(self, mv: memoryview) -> None:
        self._mv = mv
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        elif whence == io.SEEK_END:
            self._pos = len(self._mv) + offset
        else:  # pragma: no cover - zipfile never passes another whence
            raise ValueError(f"bad whence {whence}")
        self._pos = max(self._pos, 0)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        end = len(self._mv) if n is None or n < 0 else self._pos + n
        out = bytes(self._mv[self._pos : end])
        self._pos += len(out)
        return out


def _member_views(
    buf, raw, zf: zipfile.ZipFile, zi: zipfile.ZipInfo
) -> np.ndarray:
    """One member as a zero-copy view into ``buf`` where possible, else a
    copied array via the ``np.lib.format`` reader (identical semantics to
    ``np.load(..., allow_pickle=False)``, including the object-array
    rejection)."""
    if zi.compress_type == zipfile.ZIP_STORED:
        arr = _stored_view(buf, raw, zi)
        if arr is not None:
            return arr
    with zf.open(zi.filename) as f:
        return np.lib.format.read_array(f, allow_pickle=False)


def _stored_view(buf, raw, zi: zipfile.ZipInfo) -> Optional[np.ndarray]:
    """Parse the STORED member's local header + npy header in place and
    return a ``frombuffer`` view, or ``None`` when the member needs the
    copy fallback (exotic descr, misalignment, truncation)."""
    base = zi.header_offset
    if base + _LOCAL_HEADER_LEN > len(raw):
        return None
    nlen, elen = struct.unpack_from("<HH", raw, base + 26)
    doff = base + _LOCAL_HEADER_LEN + nlen + elen
    if doff + 12 > len(raw) or bytes(raw[doff : doff + 6]) != _NPY_MAGIC:
        return None
    major = raw[doff + 6]
    if major == 1:
        (hlen,) = struct.unpack_from("<H", raw, doff + 8)
        hstart = doff + 10
    else:  # npy format 2/3: 4-byte header length
        (hlen,) = struct.unpack_from("<I", raw, doff + 8)
        hstart = doff + 12
    try:
        header = ast.literal_eval(
            bytes(raw[hstart : hstart + hlen]).decode("latin1")
        )
        descr = header["descr"]
        shape = tuple(header["shape"])
        fortran = bool(header["fortran_order"])
        if not isinstance(descr, str):
            return None  # structured dtype: fallback copies it correctly
        dtype = np.dtype(descr)
    except (ValueError, SyntaxError, KeyError, TypeError):
        return None
    if dtype.hasobject:
        return None  # the fallback raises exactly like allow_pickle=False
    dstart = hstart + hlen
    count = 1
    for dim in shape:
        count *= int(dim)
    if dstart + count * dtype.itemsize > len(raw):
        return None  # truncated member: let the checked reader complain
    arr = np.frombuffer(buf, dtype=dtype, count=count, offset=dstart)
    if not arr.flags.aligned:
        return None  # misaligned for this dtype: copy instead of a slow view
    return arr.reshape(shape, order="F" if fortran else "C")


def npz_views(buf) -> Dict[str, np.ndarray]:
    """Decode an npz archive held in ``buf`` (bytes / bytearray /
    memoryview / uint8 ndarray / mmap) into ``{name: array}`` with
    zero-copy leaf views wherever the format allows and per-leaf copy
    fallbacks everywhere else. Raises the same exception classes the
    ``np.load`` path would for an unusable archive
    (:data:`NPZ_FORMAT_ERRORS`)."""
    # bytes payloads ride BytesIO's zero-copy sharing of immutable bytes;
    # everything else (pooled buffers, mmaps) goes through the no-copy
    # _BufferIO wrapper — either way the archive is never duplicated
    if isinstance(buf, bytes):
        raw: Any = buf
        f: Any = io.BytesIO(buf)
    else:
        raw = memoryview(buf)
        f = _BufferIO(raw)
    with zipfile.ZipFile(f) as zf:
        out: Dict[str, np.ndarray] = {}
        for zi in zf.infolist():
            name = zi.filename
            key = name[:-4] if name.endswith(".npy") else name
            out[key] = _member_views(buf, raw, zf, zi)
        return out


def _views_share_buffer(arrays: Dict[str, np.ndarray], buf: Any) -> bool:
    """Test helper: every array leaf is a view (no owned data)."""
    return all(not a.flags.owndata for a in arrays.values())

from torcheval_tpu.utils.test_utils.dummy_metric import (
    DummySumDequeStateMetric,
    DummySumDictStateMetric,
    DummySumListStateMetric,
    DummySumMetric,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    BATCH_SIZE,
    NUM_PROCESSES,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
    assert_result_close,
)

__all__ = [
    "BATCH_SIZE",
    "NUM_PROCESSES",
    "NUM_TOTAL_UPDATES",
    "MetricClassTester",
    "assert_result_close",
    "DummySumDequeStateMetric",
    "DummySumDictStateMetric",
    "DummySumListStateMetric",
    "DummySumMetric",
]

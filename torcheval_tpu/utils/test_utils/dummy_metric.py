"""Dummy metrics exercising every state container type.

Reference: ``torcheval/utils/test_utils/dummy_metric.py:19-141`` — one fixture
per ``TState`` variant (array / list / dict / deque) powering the base-class
and toolkit tests.
"""

from __future__ import annotations

from collections import deque
from typing import Iterable

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.convert import as_jax
from torcheval_tpu.utils.devices import DeviceLike


class DummySumMetric(Metric[jax.Array]):
    """Scalar-array state: running sum."""

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("sum", jnp.zeros(()), reduction=Reduction.SUM)

    def update(self, x) -> "DummySumMetric":
        self.sum = self.sum + jnp.sum(as_jax(x))
        return self

    def compute(self) -> jax.Array:
        return self.sum

    def merge_state(self, metrics: Iterable["DummySumMetric"]) -> "DummySumMetric":
        for metric in metrics:
            self.sum = self.sum + jax.device_put(metric.sum, self.device)
        return self


class DummySumListStateMetric(Metric[jax.Array]):
    """List-of-arrays state: caches every update."""

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("x", [], reduction=Reduction.CAT)

    def update(self, x) -> "DummySumListStateMetric":
        self.x.append(jax.device_put(as_jax(x), self.device))
        return self

    def compute(self) -> jax.Array:
        return jnp.stack(self.x).sum() if self.x else jnp.zeros(())

    def merge_state(
        self, metrics: Iterable["DummySumListStateMetric"]
    ) -> "DummySumListStateMetric":
        for metric in metrics:
            self.x.extend(jax.device_put(x, self.device) for x in metric.x)
        return self

    def _prepare_for_merge_state(self) -> None:
        if self.x:
            self.x = [jnp.stack([jnp.asarray(v, dtype=jnp.float32) for v in self.x]).sum()]


class DummySumDictStateMetric(Metric[jax.Array]):
    """Dict-keyed state (host-side only; no shipped metric uses dicts)."""

    def __init__(self, *, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("x", {}, reduction=Reduction.CUSTOM)

    def update(self, key: str, x) -> "DummySumDictStateMetric":
        self.x[key] = self.x.get(key, jnp.zeros(())) + jnp.sum(as_jax(x))
        return self

    def compute(self) -> jax.Array:
        return jnp.stack(list(self.x.values())).sum() if self.x else jnp.zeros(())

    def merge_state(
        self, metrics: Iterable["DummySumDictStateMetric"]
    ) -> "DummySumDictStateMetric":
        for metric in metrics:
            for k, v in metric.x.items():
                self.x[k] = self.x.get(k, jnp.zeros(())) + jax.device_put(v, self.device)
        return self


class DummySumDequeStateMetric(Metric[jax.Array]):
    """Deque state with bounded window."""

    def __init__(self, *, maxlen: int = 10, device: DeviceLike = None) -> None:
        super().__init__(device=device)
        self._add_state("x", deque(maxlen=maxlen), reduction=Reduction.CAT)

    def update(self, x) -> "DummySumDequeStateMetric":
        self.x.append(jax.device_put(as_jax(x), self.device))
        return self

    def compute(self) -> jax.Array:
        return jnp.stack(list(self.x)).sum() if self.x else jnp.zeros(())

    def merge_state(
        self, metrics: Iterable["DummySumDequeStateMetric"]
    ) -> "DummySumDequeStateMetric":
        for metric in metrics:
            self.x.extend(jax.device_put(x, self.device) for x in metric.x)
        return self

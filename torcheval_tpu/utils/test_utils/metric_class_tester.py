"""Protocol-conformance harness for class metrics.

TPU re-design of the reference's ``MetricClassTester``
(``torcheval/utils/test_utils/metric_class_tester.py:46-311``). Same shape
convention: every update argument carries a leading ``num_total_updates`` axis;
update ``i`` consumes slice ``i``. The harness verifies, for one spec:

1. init invariants — state names, deepcopy/pickle, state_dict round-trip;
2. streaming ``update`` + ``compute`` — chaining, idempotence, expected value;
3. the **distributed-equivalence property**: splitting the updates across
   ``num_processes`` simulated replicas and ``merge_state``-ing must equal the
   single-stream result, sources must be unmutated, and merge-before-update
   must work.

Multi-device sync testing (tier 3) lives in ``tests/metrics/test_toolkit.py``
on a forced-multi-device CPU mesh rather than here, because JAX's SPMD model
needs no process launcher for single-host simulation.
"""

from __future__ import annotations

import copy
import pickle
import unittest
from typing import Any, Dict, List, Optional, Union

import numpy as np

from torcheval_tpu.metrics.metric import Metric

NUM_TOTAL_UPDATES = 8
NUM_PROCESSES = 4
BATCH_SIZE = 16


def assert_result_close(
    result: Any, expected: Any, atol: float = 1e-5, rtol: float = 1e-4
) -> None:
    """Recursively compare metric results (arrays / sequences / dicts),
    NaN-equal, with float32-appropriate tolerances (the reference uses
    torch-float64 tolerances at ``metric_class_tester.py:41-42``)."""
    if isinstance(expected, dict):
        assert isinstance(result, dict), f"expected dict, got {type(result)}"
        assert set(result) == set(expected)
        for k in expected:
            assert_result_close(result[k], expected[k], atol=atol, rtol=rtol)
    elif isinstance(expected, (list, tuple)):
        assert isinstance(result, (list, tuple)), f"expected sequence, got {type(result)}"
        assert len(result) == len(expected), f"{len(result)} != {len(expected)}"
        for r, e in zip(result, expected):
            assert_result_close(r, e, atol=atol, rtol=rtol)
    else:
        np.testing.assert_allclose(
            np.asarray(result, dtype=np.float64),
            np.asarray(expected, dtype=np.float64),
            atol=atol,
            rtol=rtol,
            equal_nan=True,
        )


def _slice_kwargs(update_kwargs: Dict[str, Any], idx: int) -> Dict[str, Any]:
    return {name: value[idx] for name, value in update_kwargs.items()}


class MetricClassTester(unittest.TestCase):
    """Inherit in class-metric tests and call
    :meth:`run_class_implementation_tests`."""

    def run_class_implementation_tests(
        self,
        metric: Metric,
        state_names: Union[set, frozenset],
        update_kwargs: Dict[str, Any],
        compute_result: Any,
        num_total_updates: int = NUM_TOTAL_UPDATES,
        num_processes: int = NUM_PROCESSES,
        merge_and_compute_result: Optional[Any] = None,
        test_merge_with_one_update: bool = True,
        atol: float = 1e-5,
        rtol: float = 1e-4,
    ) -> None:
        assert num_total_updates % num_processes == 0, (
            "num_total_updates must divide evenly among num_processes"
        )
        self._test_init(metric, state_names)
        self._test_update_and_compute(
            metric, update_kwargs, compute_result, num_total_updates, atol, rtol
        )
        expected_merge = (
            merge_and_compute_result
            if merge_and_compute_result is not None
            else compute_result
        )
        self._test_merge_state(
            metric,
            update_kwargs,
            expected_merge,
            num_total_updates,
            num_processes,
            test_merge_with_one_update,
            atol,
            rtol,
            stream_result=compute_result,
        )
        self._test_cross_device_merge(
            metric, update_kwargs, expected_merge, num_total_updates,
            num_processes, atol, rtol,
        )

    def _test_cross_device_merge(
        self, metric, update_kwargs, compute_result, n, num_processes, atol, rtol
    ) -> None:
        """Replicas living on different devices must merge correctly, with the
        merged state landing on the destination's device (reference:
        ``metric_class_tester.py:177-270`` exercises CPU↔CUDA)."""
        import jax

        devices = jax.devices()
        if len(devices) < 2:
            return
        per_rank = n // num_processes
        replicas = [
            copy.deepcopy(metric).to(devices[rank % len(devices)])
            for rank in range(num_processes)
        ]
        for rank, rep in enumerate(replicas):
            for i in range(rank * per_rank, (rank + 1) * per_rank):
                rep.update(**_slice_kwargs(update_kwargs, i))
        merged = replicas[0].merge_state(replicas[1:])
        assert_result_close(merged.compute(), compute_result, atol=atol, rtol=rtol)
        # merged state BUFFERS must land on the destination's device (the
        # _device attribute alone would be vacuous — merge never touches it)
        for name, value in merged._states().items():
            leaves = (
                list(value.values()) if isinstance(value, dict)
                else list(value) if isinstance(value, (list, tuple)) or type(value).__name__ == "deque"
                else [value]
            )
            for leaf in leaves:
                self.assertIn(
                    devices[0], leaf.devices(),
                    f"state {name!r} not on destination device after cross-device merge",
                )

    def _test_init(self, metric: Metric, state_names) -> None:
        self.assertEqual(set(metric.state_names), set(state_names))
        cloned = copy.deepcopy(metric)
        self.assertEqual(set(cloned.state_names), set(state_names))
        restored = pickle.loads(pickle.dumps(metric))
        self.assertEqual(set(restored.state_names), set(state_names))
        sd = metric.state_dict()
        self.assertEqual(set(sd.keys()), set(state_names))
        fresh = copy.deepcopy(metric)
        fresh.load_state_dict(sd)
        with self.assertRaises(RuntimeError):
            fresh.load_state_dict({"__not_a_state__": 0}, strict=True)

    def _test_update_and_compute(
        self, metric: Metric, update_kwargs, compute_result, n, atol, rtol
    ) -> None:
        m = copy.deepcopy(metric)
        for i in range(n):
            ret = m.update(**_slice_kwargs(update_kwargs, i))
            self.assertIs(ret, m)  # update chains
        r1 = m.compute()
        r2 = m.compute()  # idempotent
        assert_result_close(r1, compute_result, atol=atol, rtol=rtol)
        assert_result_close(r2, compute_result, atol=atol, rtol=rtol)

    def _test_merge_state(
        self,
        metric: Metric,
        update_kwargs,
        compute_result,
        n,
        num_processes,
        test_merge_with_one_update,
        atol,
        rtol,
        stream_result=None,
    ) -> None:
        if stream_result is None:
            stream_result = compute_result
        per_rank = n // num_processes
        replicas: List[Metric] = [copy.deepcopy(metric) for _ in range(num_processes)]
        for rank, rep in enumerate(replicas):
            for i in range(rank * per_rank, (rank + 1) * per_rank):
                rep.update(**_slice_kwargs(update_kwargs, i))
        source_dicts = [copy.deepcopy(rep.state_dict()) for rep in replicas[1:]]
        merged = replicas[0].merge_state(replicas[1:])
        self.assertIs(merged, replicas[0])
        assert_result_close(merged.compute(), compute_result, atol=atol, rtol=rtol)
        # sources unchanged by merge
        for rep, before in zip(replicas[1:], source_dicts):
            after = rep.state_dict()
            self.assertEqual(set(after), set(before))
            for k in before:
                self._assert_state_equal(before[k], after[k])
        # merge into a metric that has never been updated
        fresh = copy.deepcopy(metric)
        sources = [copy.deepcopy(metric) for _ in range(num_processes)]
        for rank, rep in enumerate(sources):
            for i in range(rank * per_rank, (rank + 1) * per_rank):
                rep.update(**_slice_kwargs(update_kwargs, i))
        fresh.merge_state(sources)
        assert_result_close(fresh.compute(), compute_result, atol=atol, rtol=rtol)
        # merge an empty metric mid-stream, then continue updating
        if test_merge_with_one_update:
            a = copy.deepcopy(metric)
            b = copy.deepcopy(metric)
            for i in range(n // 2):
                a.update(**_slice_kwargs(update_kwargs, i))
            a.merge_state([b])
            for i in range(n // 2, n):
                a.update(**_slice_kwargs(update_kwargs, i))
            # merging an empty metric is a no-op, so this path matches the
            # single-stream result (which can differ from the N-way merge
            # result, e.g. Throughput's max-elapsed merge)
            assert_result_close(a.compute(), stream_result, atol=atol, rtol=rtol)

    def _assert_state_equal(self, before, after) -> None:
        if isinstance(before, (list, tuple)) or type(before).__name__ == "deque":
            before_l, after_l = list(before), list(after)
            self.assertEqual(len(before_l), len(after_l))
            for b, a in zip(before_l, after_l):
                np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
        elif isinstance(before, dict):
            self.assertEqual(set(before), set(after))
            for k in before:
                np.testing.assert_array_equal(
                    np.asarray(before[k]), np.asarray(after[k])
                )
        else:
            np.testing.assert_array_equal(np.asarray(before), np.asarray(after))

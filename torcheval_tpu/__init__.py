"""torcheval_tpu: a TPU-native (JAX/XLA) model-evaluation metrics framework.

A ground-up re-design of the capabilities of TorchEval for TPUs: streaming
metrics whose state is a pytree of ``jax.Array`` s in HBM, per-batch updates
compiled to jitted XLA kernels, and distributed sync expressed as typed mesh
collectives (``psum`` / ``pmax`` / ``all_gather``) over ICI/DCN instead of
pickled object gathers. See SURVEY.md for the structural map of the reference.
"""

from torcheval_tpu.version import __version__

__all__ = ["__version__"]

from torcheval_tpu.tools.flops import module_flops
from torcheval_tpu.tools.module_summary import (
    ModuleSummary,
    get_module_summary,
    get_summary_table,
    prune_module_summary,
)

__all__ = [
    "ModuleSummary",
    "get_module_summary",
    "get_summary_table",
    "module_flops",
    "prune_module_summary",
]

"""Per-module FLOP analysis for flax models.

TPU-native replacement for the reference's dispatch-interception FLOP counter
(``torcheval/tools/flops.py:143-329``): torcheval wraps tensors in a
``__torch_dispatch__`` subclass, looks every aten op up in a hand-written
``flop_mapping``, and replays a module stack through custom autograd nodes to
attribute backward FLOPs. None of that machinery is needed on TPU — XLA
already computes exact FLOPs for every compiled executable. This module:

1. traces the model once under ``flax.linen.intercept_methods``, recording
   every submodule call (path, unbound module clone, argument avals) — the
   analogue of the reference's forward-hook module stack
   (``flops.py:313-326``);
2. for each recorded call, lowers the submodule in isolation with abstract
   inputs and reads ``compile().cost_analysis()["flops"]`` — forward — and
   the same for ``jax.grad`` of the call's scalar mean minus the forward
   cost — backward (the reference's ``.mean().backward()`` convention,
   ``module_summary.py:171-175``).

Everything runs on abstract values: no real parameters, data, or device
compute — only compile-time analysis.

Note on units: XLA counts multiply and add separately (a dot of (m,k)x(k,n)
is ``2*m*k*n`` flops), while the reference's hand-written mapping counts
fused MACs (``m*k*n``, ``flops.py:21-40``). Expect a factor ~2 when
comparing.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class _CallRecord(NamedTuple):
    path: Tuple[str, ...]
    module: Any  # unbound flax module clone
    method_name: str
    arg_avals: Tuple[jax.ShapeDtypeStruct, ...]
    type_name: str


class ModuleFlops(NamedTuple):
    forward: int
    backward: int


def _record_calls(module, rng, *args, **kwargs):
    import flax.linen as nn

    records: list[_CallRecord] = []

    def interceptor(next_fun, call_args, call_kwargs, context):
        try:
            clone = context.module.clone(parent=None)
            avals = tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in call_args
                if hasattr(a, "shape") and hasattr(a, "dtype")
            )
            records.append(
                _CallRecord(
                    tuple(context.module.path),
                    clone,
                    context.method_name,
                    avals,
                    type(context.module).__name__,
                )
            )
        except Exception:
            pass
        return next_fun(*call_args, **call_kwargs)

    with nn.intercept_methods(interceptor):
        variables = jax.eval_shape(lambda: module.init(rng, *args, **kwargs))
    return records, variables


def _subtree(variables: Dict[str, Any], path: Tuple[str, ...]) -> Dict[str, Any]:
    out = {}
    for coll, tree in variables.items():
        node = tree
        for p in path:
            if isinstance(node, dict) and p in node:
                node = node[p]
            else:
                node = None
                break
        if node is not None:
            out[coll] = node
    return out


def _cost_flops(fn, *avals) -> int:
    cost = jax.jit(fn).lower(*avals).compile().cost_analysis()
    if not cost:
        return 0
    if isinstance(cost, (list, tuple)):
        # jax <= 0.4.x returns one properties dict per executable computation
        return int(sum(c.get("flops", 0) for c in cost))
    return int(cost.get("flops", 0))


def module_flops(
    module,
    *args,
    rng: Optional[jax.Array] = None,
    backward: bool = True,
    _traced=None,
    **kwargs,
) -> Dict[Tuple[str, ...], ModuleFlops]:
    """Forward/backward FLOPs for every submodule of a flax model.

    Args:
        module: an (unbound) ``flax.linen.Module``.
        *args / **kwargs: example inputs (arrays or ShapeDtypeStructs).
        rng: PRNG key for abstract init (default ``PRNGKey(0)``).
        backward: also compute backward FLOPs (costs one extra lowering per
            submodule).

    Returns:
        ``{module_path: ModuleFlops(forward, backward)}`` — ``()`` is the root
        module; a parent's counts include its children (reference stack
        semantics, ``flops.py:204-233``). Backward is -1 when not computed.
        Repeated calls to the same submodule accumulate.
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    records, variables = (
        _traced
        if _traced is not None
        else _record_calls(module, rng, *args, **kwargs)
    )
    out: Dict[Tuple[str, ...], ModuleFlops] = {}
    for rec in records:
        sub_vars = _subtree(variables, rec.path)
        sub_abs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), sub_vars
        )
        mod, method = rec.module, rec.method_name

        def fwd(v, *a):
            return mod.apply(v, *a, method=method)

        try:
            fwd_flops = _cost_flops(fwd, sub_abs, *rec.arg_avals)
        except Exception:
            continue
        bwd_flops = -1
        if backward:

            def loss(v, *a):
                y = mod.apply(v, *a, method=method)
                return jnp.mean(jnp.asarray(y, jnp.float32))

            try:
                total = _cost_flops(
                    jax.value_and_grad(loss), sub_abs, *rec.arg_avals
                )
                bwd_flops = max(total - fwd_flops, 0)
            except Exception:
                bwd_flops = -1
        prev = out.get(rec.path)
        if prev is None:
            out[rec.path] = ModuleFlops(fwd_flops, bwd_flops)
        else:
            out[rec.path] = ModuleFlops(
                prev.forward + fwd_flops,
                prev.backward + bwd_flops
                if prev.backward >= 0 and bwd_flops >= 0
                else -1,
            )
    return out


def record_module_types(
    module, rng, *args, **kwargs
) -> Dict[Tuple[str, ...], str]:
    """``{path: type_name}`` for every submodule reached by the forward pass."""
    records, _ = _record_calls(module, rng, *args, **kwargs)
    return {rec.path: rec.type_name for rec in records}

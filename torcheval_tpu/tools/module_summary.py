"""Module summaries for flax models. Reference:
``torcheval/tools/module_summary.py:41-503``.

Parameter/byte counts come from ``jax.eval_shape`` over ``module.init`` —
a pure compile-time tree walk, no device memory touched (the reference walks
live ``named_children`` / ``parameters(recurse=False)``,
``module_summary.py:232-293``). FLOPs come from XLA cost analysis
(:mod:`torcheval_tpu.tools.flops`). A module's numbers include its whole
subtree.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax

from torcheval_tpu.tools.flops import _record_calls, module_flops

_ATTRIB_TO_COL_HEADER = {
    "module_name": "Name",
    "module_type": "Type",
    "num_parameters": "# Parameters",
    "num_trainable_parameters": "# Trainable Parameters",
    "size_bytes": "Size (bytes)",
    "has_uninitialized_param": "Contains Uninitialized Parameter?",
    "flops_forward": "Forward FLOPs",
    "flops_backward": "Backward FLOPs",
}
_FLOP_ATTRIBS = ("flops_forward", "flops_backward")
_PARAMETER_NUM_UNITS = (" ", "K", "M", "B", "T")
_PARAMETER_FLOPS_UNITS = (" ", "k", "M", "G", "T", "P", "E", "Z", "Y")


class ModuleSummary:
    """Summary record for one module and (recursively) its submodules.

    Mirrors the reference's attribute surface (``module_summary.py:41-147``):
    name, type, parameter/trainable counts, byte size, uninitialized flag,
    forward/backward FLOPs (-1 = not computed), and a dict of child
    summaries.
    """

    def __init__(self) -> None:
        self._module_name: str = ""
        self._module_type: str = ""
        self._num_parameters: int = 0
        self._num_trainable_parameters: int = 0
        self._size_bytes: int = 0
        self._submodule_summaries: Dict[str, "ModuleSummary"] = {}
        self._has_uninitialized_param: bool = False
        self._flops_forward: int = -1
        self._flops_backward: int = -1

    @property
    def submodule_summaries(self) -> Dict[str, "ModuleSummary"]:
        return self._submodule_summaries

    @property
    def module_name(self) -> str:
        return self._module_name

    @property
    def module_type(self) -> str:
        return self._module_type

    @property
    def num_parameters(self) -> int:
        return self._num_parameters

    @property
    def num_trainable_parameters(self) -> int:
        return self._num_trainable_parameters

    @property
    def size_bytes(self) -> int:
        return self._size_bytes

    @property
    def has_uninitialized_param(self) -> bool:
        """Always False for flax models — parameters are shape-inferred at
        ``init`` time, so the lazy/uninitialized state the reference guards
        against (torch ``UninitializedParameter``) cannot exist."""
        return self._has_uninitialized_param

    @property
    def flops_forward(self) -> int:
        return self._flops_forward

    @property
    def flops_backward(self) -> int:
        return self._flops_backward

    def __repr__(self) -> str:
        return get_summary_table(self)


def get_module_summary(
    module,
    module_args: Tuple[Any, ...] = (),
    module_kwargs: Optional[Dict[str, Any]] = None,
    *,
    rng: Optional[jax.Array] = None,
    compute_flops: Optional[bool] = None,
) -> ModuleSummary:
    """Summarize a flax module: parameters, bytes, and (with example inputs)
    forward/backward FLOPs per submodule.

    Args:
        module: an unbound ``flax.linen.Module``.
        module_args / module_kwargs: example inputs (arrays or
            ``jax.ShapeDtypeStruct`` — everything stays abstract).
        rng: PRNG key for the abstract init (default ``PRNGKey(0)``).
        compute_flops: defaults to ``bool(module_args or module_kwargs)``,
            matching the reference's "FLOPs iff an input is given"
            (``module_summary.py:219-229``).
    """
    module_kwargs = module_kwargs or {}
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if compute_flops is None:
        compute_flops = bool(module_args or module_kwargs)

    # one abstract trace serves everything: variables tree (param counts),
    # call records (module types), and the FLOP pass below
    try:
        records, variables = _record_calls(
            module, rng, *module_args, **module_kwargs
        )
    except TypeError as e:
        raise TypeError(
            "get_module_summary needs example inputs for flax modules — "
            "parameters are shape-inferred at init, so pass module_args "
            "(arrays or jax.ShapeDtypeStruct; use compute_flops=False to "
            f"skip FLOP analysis). Underlying error: {e}"
        ) from e
    type_names: Dict[Tuple[str, ...], str] = {
        rec.path: rec.type_name for rec in records
    }
    flops: Dict[Tuple[str, ...], Any] = {}
    if compute_flops:
        flops = module_flops(
            module,
            *module_args,
            rng=rng,
            _traced=(records, variables),
            **module_kwargs,
        )

    # accumulate per-path parameter/byte counts from the variables pytree
    stats: Dict[Tuple[str, ...], Dict[str, int]] = {}

    def _touch(path: Tuple[str, ...]) -> Dict[str, int]:
        return stats.setdefault(
            path, {"params": 0, "trainable": 0, "bytes": 0}
        )

    _touch(())
    for coll, tree in variables.items():
        for leaf_path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            keys = tuple(
                k.key for k in leaf_path if hasattr(k, "key")
            )  # last key is the param name; the rest are module path
            mod_path = keys[:-1]
            n = math.prod(leaf.shape) if leaf.shape else 1
            nbytes = n * leaf.dtype.itemsize
            for depth in range(len(mod_path) + 1):
                s = _touch(mod_path[:depth])
                s["params"] += n
                s["bytes"] += nbytes
                if coll == "params":
                    s["trainable"] += n
    for path in set(type_names) | set(flops):
        _touch(path)

    def _build(path: Tuple[str, ...], name: str) -> ModuleSummary:
        ms = ModuleSummary()
        ms._module_name = name
        ms._module_type = type_names.get(
            path, type(module).__name__ if not path else _strip_index(path[-1])
        )
        s = stats[path]
        ms._num_parameters = s["params"]
        ms._num_trainable_parameters = s["trainable"]
        ms._size_bytes = s["bytes"]
        if path in flops:
            ms._flops_forward = flops[path].forward
            ms._flops_backward = flops[path].backward
        children = sorted(
            {p[len(path)] for p in stats if len(p) == len(path) + 1 and p[: len(path)] == path}
        )
        for child in children:
            child_path = path + (child,)
            child_name = ".".join(child_path)
            ms._submodule_summaries[child_name] = _build(child_path, child_name)
        return ms

    return _build((), "")


def _strip_index(key: str) -> str:
    """``Dense_0`` -> ``Dense`` (flax auto-naming convention)."""
    base, _, idx = key.rpartition("_")
    return base if base and idx.isdigit() else key


def prune_module_summary(module_summary: ModuleSummary, *, max_depth: int) -> None:
    """In-place: drop submodule summaries below ``max_depth`` levels
    (reference ``module_summary.py:363-383``)."""
    if max_depth < 1:
        raise ValueError(f"`max_depth` must be an int greater than 0, got {max_depth}.")
    if max_depth == 1:
        module_summary._submodule_summaries.clear()
        return
    for child in module_summary._submodule_summaries.values():
        prune_module_summary(child, max_depth=max_depth - 1)


def _human_readable(num: float, units) -> str:
    if num < 0:
        return str(num)
    idx = 0
    while num >= 1000 and idx < len(units) - 1:
        num /= 1000.0
        idx += 1
    digits = f"{num:.1f}".rstrip("0").rstrip(".")
    return f"{digits} {units[idx]}".rstrip()


def get_summary_table(
    module_summary: ModuleSummary, human_readable_nums: bool = True
) -> str:
    """Fixed-width text table over the summary tree (reference
    ``module_summary.py:296-360``)."""
    has_flops = module_summary.flops_forward >= 0
    attribs = [
        a
        for a in _ATTRIB_TO_COL_HEADER
        if has_flops or a not in _FLOP_ATTRIBS
    ]

    rows = []

    def _format(ms: ModuleSummary, attrib: str) -> str:
        value = getattr(ms, attrib)
        if isinstance(value, bool):
            return "Yes" if value else "No"
        if isinstance(value, int):
            if not human_readable_nums:
                return str(value)
            units = (
                _PARAMETER_FLOPS_UNITS
                if attrib in _FLOP_ATTRIBS
                else _PARAMETER_NUM_UNITS
            )
            return _human_readable(value, units)
        return str(value)

    def _walk(ms: ModuleSummary) -> None:
        rows.append([_format(ms, a) for a in attribs])
        for child in ms.submodule_summaries.values():
            _walk(child)

    _walk(module_summary)
    headers = [_ATTRIB_TO_COL_HEADER[a] for a in attribs]
    widths = [
        max(len(headers[i]), *(len(r[i]) for r in rows)) for i in range(len(headers))
    ]
    lines = [
        " | ".join(h.ljust(w) for h, w in zip(headers, widths)),
        "-+-".join("-" * w for w in widths),
    ]
    for r in rows:
        lines.append(" | ".join(c.ljust(w) for c, w in zip(r, widths)))
    table = "\n".join(lines)
    if module_summary.flops_forward >= 0:
        table += (
            "\nRemark for FLOPs calculation: (1) Only operations XLA compiles "
            "are counted; multiplies and adds count separately (a dot of "
            "(m,k)x(k,n) is 2mkn FLOPs). (2) Backward FLOPs are the cost of "
            "value_and_grad of the mean of the module output, minus the "
            "forward cost."
        )
    return table

"""Delta-snapshot streaming: O(changed) telemetry units for the push wire.

ISSUE 16 tentpole. A host that streams its registry to a router every
second cannot afford to re-serialise the full snapshot each tick — a
daemon's registry holds hundreds of series (per-tenant counters, span
paths, 64-bucket histograms) of which a quiet tick touches a handful.
:meth:`Registry.delta_since` (``registry.py``) produces the diff;
this module owns everything around it:

* :func:`collect` — one *stream delta*: the registry diff plus the
  timeline events recorded since the cursor (the flight-recorder leg of a
  push, consumed by ``router.fleet_chrome_trace()``), under ONE opaque
  :class:`StreamCursor`;
* :class:`DeltaAccumulator` — the receive side: folds deltas back into an
  absolute view whose :meth:`DeltaAccumulator.snapshot` is shaped exactly
  like ``Registry.snapshot()`` (same keys, same percentile estimator), so
  delta∘delta∘... == the snapshot you would have fetched — the algebra
  ``tests/obs/test_delta.py`` pins;
* :func:`delta_nbytes` — serialised size of a delta, the quantity the
  ``config12_obs_delta_bytes`` bench row compares against a full snapshot.

Cost model: nothing here runs unless something *calls* it — importing this
module adds no instrumentation, no threads, and nothing to the disabled
path (``tests/obs/test_host_overhead.py`` imports it and re-pins the PR 7
zero-allocation guarantee). A ``collect`` call while obs is disabled is
legal and returns an empty delta.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from torcheval_tpu.obs import registry as _registry
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.obs.registry import (
    HISTOGRAM_BUCKETS,
    DeltaCursor,
    Registry,
    percentile_from_buckets,
)

__all__ = [
    "StreamCursor",
    "collect",
    "DeltaAccumulator",
    "delta_nbytes",
]


class StreamCursor:
    """Opaque position in the obs stream: the registry's
    :class:`~torcheval_tpu.obs.registry.DeltaCursor` plus the all-time
    timeline event index. Created by :func:`collect`; never constructed or
    inspected by callers (the publisher thread holds one per subscription)."""

    __slots__ = ("registry_cursor", "events_seen")

    def __init__(
        self, registry_cursor: DeltaCursor, events_seen: int
    ) -> None:
        self.registry_cursor = registry_cursor
        self.events_seen = events_seen


def collect(
    cursor: Optional[StreamCursor] = None,
    *,
    registry: Optional[Registry] = None,
    include_events: bool = True,
    max_events: int = 2048,
) -> Tuple[Dict[str, Any], StreamCursor]:
    """One stream delta: ``(delta, new_cursor)``.

    ``delta`` is the registry diff (see ``Registry.delta_since``) with an
    ``"events"`` list appended — the timeline events recorded since the
    cursor, newest ``max_events`` of them (a compile storm must not turn
    one push into a megabyte; the trim is counted in ``"events_trimmed"``
    so the receiver knows the recorder saw more than it shipped)."""
    reg = registry or _registry.default_registry
    rdelta, rcursor = reg.delta_since(
        cursor.registry_cursor if cursor is not None else None
    )
    events_seen = cursor.events_seen if cursor is not None else 0
    if rdelta["full"]:
        # a generation bump (obs.reset()) cleared the timeline ring too:
        # rewind the event cursor so post-reset events aren't skipped
        # while the all-time index catches back up to the stale offset
        events_seen = 0
    if include_events:
        events, total = _trace.events_since(events_seen)
        trimmed = 0
        if len(events) > max_events:
            trimmed = len(events) - max_events
            events = events[-max_events:]
        rdelta["events"] = events
        rdelta["events_trimmed"] = trimmed
        events_seen = total
    else:
        rdelta["events"] = []
        rdelta["events_trimmed"] = 0
    return rdelta, StreamCursor(rcursor, events_seen)


def delta_nbytes(delta: Dict[str, Any]) -> int:
    """Serialised (compact JSON, UTF-8) size of a delta — the wire cost a
    push pays, and the quantity the bench's delta-vs-snapshot row reports."""
    return len(
        json.dumps(delta, separators=(",", ":"), default=str).encode()
    )


def _dense(sparse) -> List[int]:
    out = [0] * HISTOGRAM_BUCKETS
    for i, c in sparse:
        out[i] = c
    return out


class DeltaAccumulator:
    """Folds a sequence of deltas back into an absolute registry view.

    The receive side of the push channel: the router keeps one per
    subscribed host. :meth:`apply` is associative with the registry's diff
    — applying every delta since a cursor reproduces, exactly, the snapshot
    the registry would have served at the last delta's instant (bucket
    counts included, which is why histogram deltas are shipped per-bucket
    and sum-exact). A delta marked ``"full"`` (first push, or the host
    reset its registry) replaces the accumulated state instead of adding to
    it. Not thread-safe — callers serialise (the subscription reader thread
    is the only writer)."""

    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # key -> [buckets(list), count, sum]
        self._histos: Dict[str, list] = {}
        # key -> [buckets(list), count, total_seconds, max_seconds]
        self._spans: Dict[str, list] = {}
        self.events: List[Dict[str, Any]] = []
        self.events_trimmed = 0
        self.applied = 0
        self.last_seq: Optional[int] = None

    def apply(self, delta: Dict[str, Any]) -> None:
        if delta.get("full"):
            self._counters.clear()
            self._gauges.clear()
            self._histos.clear()
            self._spans.clear()
        for k, d in delta.get("counters", {}).items():
            self._counters[k] = self._counters.get(k, 0.0) + d
        for k, v in delta.get("gauges", {}).items():
            self._gauges[k] = v
        for k, d in delta.get("histograms", {}).items():
            h = self._histos.get(k)
            if h is None:
                h = self._histos[k] = [[0] * HISTOGRAM_BUCKETS, 0, 0.0]
            for i, c in d["buckets"]:
                h[0][i] += c
            h[1] += d["count"]
            h[2] += d["sum"]
        for k, d in delta.get("spans", {}).items():
            s = self._spans.get(k)
            if s is None:
                s = self._spans[k] = [[0] * HISTOGRAM_BUCKETS, 0, 0.0, 0.0]
            for i, c in d["buckets"]:
                s[0][i] += c
            s[1] += d["count"]
            s[2] += d["total_seconds"]
            s[3] = max(s[3], d["max_seconds"])
        self.events.extend(delta.get("events", ()))
        self.events_trimmed += delta.get("events_trimmed", 0)
        self.applied += 1
        self.last_seq = delta.get("seq", self.last_seq)

    def snapshot(self) -> Dict[str, Any]:
        """The accumulated state in ``Registry.snapshot()`` shape (same
        percentile estimator over the same reconstructed buckets)."""
        return {
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "histograms": {
                k: {
                    "count": h[1],
                    "sum": h[2],
                    "p50": percentile_from_buckets(h[0], h[1], 0.50),
                    "p95": percentile_from_buckets(h[0], h[1], 0.95),
                    "p99": percentile_from_buckets(h[0], h[1], 0.99),
                }
                for k, h in self._histos.items()
            },
            "spans": {
                k: {
                    "count": s[1],
                    "total_seconds": s[2],
                    "max_seconds": s[3],
                    "p50": percentile_from_buckets(s[0], s[1], 0.50),
                    "p95": percentile_from_buckets(s[0], s[1], 0.95),
                    "p99": percentile_from_buckets(s[0], s[1], 0.99),
                }
                for k, s in self._spans.items()
            },
        }

"""``torcheval_tpu.obs``: observability for the whole eval stack.

One subsystem, four pieces (ISSUE 1 tentpole):

* **Registry** (``registry.py``) — thread-safe process-wide counters,
  gauges and nested span timers; JSON snapshot + Prometheus exposition
  (``export.py``).
* **Profiler annotation** (``annotate.py``) — ``Metric.update/compute/
  merge_state``, ``MetricCollection``, ``ShardedEvaluator`` and every ops
  kernel entry point carry ``jax.named_scope`` names into XLA traces, plus
  host spans/``TraceAnnotation`` while enabled. Disabled path is one global
  read per call — no jit-traced branching anywhere.
* **Recompile watchdog** (``recompile.py``) — per-entry-point abstract
  signature → trace counts through :func:`~torcheval_tpu.obs.recompile.
  watched_jit`; warns once per entry point on retrace storms. Always on
  (bookkeeping runs only at trace time).
* **Collective accounting** — ``metrics/toolkit.py`` and
  ``ops/dist_curves.py`` report sync rounds, payload bytes per
  ``Reduction`` lane, wall time and world size into the registry, so the
  two-collective-round invariant is an observable, not only a test
  assertion.

The resilience layer (ISSUE 5) reports here too:
``toolkit.sync.timeouts{policy=raise|local}`` (sync deadline expiries and
degraded-mode falls), ``resilience.checkpoint.{saves,restores,bytes}`` and
``bootstrap.retries`` — see docs/robustness.md.

Usage::

    from torcheval_tpu import obs
    obs.enable()
    ... run the eval loop ...
    print(obs.to_json(indent=2))        # or obs.prometheus_text()
    obs.snapshot()["counters"]["toolkit.sync.rounds"]
"""

from torcheval_tpu.obs.export import prometheus_text, to_json
from torcheval_tpu.obs.recompile import (
    retrace_threshold,
    set_retrace_threshold,
    trace_counts,
    watched_jit,
)
from torcheval_tpu.obs.registry import (
    Registry,
    counter,
    default_registry,
    disable,
    enable,
    enabled,
    gauge,
    reset,
    snapshot,
    span,
)

__all__ = [
    "Registry",
    "counter",
    "default_registry",
    "disable",
    "enable",
    "enabled",
    "gauge",
    "prometheus_text",
    "reset",
    "retrace_threshold",
    "set_retrace_threshold",
    "snapshot",
    "span",
    "to_json",
    "trace_counts",
    "watched_jit",
]

"""``torcheval_tpu.obs``: the eval stack's flight recorder.

One subsystem, grown from a counter registry (ISSUE 1) into four legs
(ISSUE 7):

* **Registry** (``registry.py``) — thread-safe process-wide counters,
  gauges, log2-bucket histograms (p50/p95/p99 in ``snapshot()``) and
  nested span timers; JSON snapshot + Prometheus exposition with proper
  ``# TYPE histogram`` families (``export.py``).
* **Event timeline** (``trace.py``) — a bounded ring of structured events
  (``ts, dur, name, kind, labels``) fed by every registry span and by
  hooks at each dispatch site: window open/append/valve/close and
  window-step dispatch/retire (``metrics/deferred.py``), ``watched_jit``
  trace vs cache-hit, sync rounds per lane (``metrics/toolkit.py``),
  checkpoint save/restore and chaos injections (``resilience/``).
  ``obs.chrome_trace()`` exports Chrome/Perfetto ``trace_event`` JSON.
* **Device cost attribution** (``cost.py``) — at every ``watched_jit``
  compile (window steps included), ``cost_analysis()`` /
  ``memory_analysis()`` feed ``obs.cost.{flops,bytes_accessed,hbm_bytes}
  {entry=}`` gauges plus a ``jit.compile/<entry>`` span, so the BENCH
  dispatch-equivalent rows sit next to what each program costs on device.
* **Cross-rank aggregation** (``distributed.py``) — ``obs.sync_snapshot()``
  merges every rank's registry (counters summed, gauges rank-labelled,
  histograms bucket-summed, timeline rank-tagged) over the toolkit
  allgather funnel in ONE collective round, honoring the PR 5
  ``timeout_s`` / degraded-local semantics.

The recompile watchdog (``recompile.py``) and profiler annotation
(``annotate.py``) ride along unchanged in contract: always-on trace-time
bookkeeping, one-global-read disabled paths everywhere.

The serving daemon (``torcheval_tpu.serve``, ISSUE 8) feeds the same four
legs: per-tenant ``serve.*`` counters/histograms (inventory in
docs/observability.md), ``serve.tenant.step{tenant=}`` spans that land as
rank-tagged tenant bars in the Chrome trace, and a daemon
``health(sync=True)`` view built on :func:`sync_snapshot`'s one-collective
cross-rank merge.

Since ISSUE 16 the subsystem also *streams*: delta snapshots
(``stream.py`` — ``Registry.delta_since`` cursors, O(changed) per tick)
ride a push frame on the serve wire to ``EvalRouter.fleet_status()``;
``obs/slo.py`` declares latency objectives over the rolling histograms
(``Slo``, ``register_slo``) with edge-triggered burn alarms through the
thread-safe ``obs.on_alarm(cb)`` hook registry; ``obs/httpd.py`` serves
``GET /metrics`` (Prometheus) and ``GET /health`` from a stdlib HTTP
thread (``EvalDaemon(metrics_port=...)``). See docs/observability.md
("Fleet telemetry").

Usage::

    from torcheval_tpu import obs
    obs.enable()
    ... run the eval loop ...
    print(obs.to_json(indent=2))          # or obs.prometheus_text()
    open("trace.json", "w").write(obs.chrome_trace())
    obs.sync_snapshot(timeout_s=30, on_failure="local")  # cluster view
"""

from torcheval_tpu.obs import recompile as _recompile_mod
from torcheval_tpu.obs import trace as _trace_mod
from torcheval_tpu.obs.distributed import sync_snapshot
from torcheval_tpu.obs.export import prometheus_text, to_json
from torcheval_tpu.obs.recompile import (
    retrace_threshold,
    set_retrace_threshold,
    trace_counts,
    watched_jit,
)
from torcheval_tpu.obs.registry import (
    Histogram,
    Registry,
    counter,
    default_registry,
    disable,
    enable,
    enabled,
    gauge,
    histo,
    set_label_cardinality_cap,
    snapshot,
    span,
)
from torcheval_tpu.obs.httpd import MetricsServer
from torcheval_tpu.obs.slo import (
    Slo,
    evaluate_slos,
    fire_alarm,
    on_alarm,
    register_slo,
    remove_alarm,
    unregister_slo,
)
from torcheval_tpu.obs.stream import DeltaAccumulator, StreamCursor
from torcheval_tpu.obs.stream import collect as collect_delta
from torcheval_tpu.obs.trace import chrome_trace
from torcheval_tpu.obs.trace import events as timeline_events
from torcheval_tpu.obs.trace import set_capacity as set_timeline_capacity
from torcheval_tpu.utils.telemetry import reset_once_keys as _reset_once_keys


def reset() -> None:
    """ONE consistent reset across the whole obs subsystem (ISSUE 7
    satellite): drops every registry instrument (counters, gauges,
    histograms, spans — cost gauges included), clears the event timeline
    ring, clears recompile-watchdog bookkeeping AND re-arms its
    once-per-entry storm warnings, and forgets every telemetry
    ``log_once`` key (watchdog + degraded-sync warnings fire again;
    API-usage keys re-log too — fresh-run semantics). Before this lived in
    one place, a "reset" left stale watchdog state warning-suppressed
    while the counters it explained were gone."""
    default_registry.reset()
    _trace_mod.clear()
    _recompile_mod.reset()
    _reset_once_keys()


__all__ = [
    "DeltaAccumulator",
    "Histogram",
    "MetricsServer",
    "Registry",
    "Slo",
    "StreamCursor",
    "chrome_trace",
    "collect_delta",
    "counter",
    "default_registry",
    "disable",
    "enable",
    "enabled",
    "evaluate_slos",
    "fire_alarm",
    "gauge",
    "histo",
    "on_alarm",
    "prometheus_text",
    "register_slo",
    "remove_alarm",
    "reset",
    "retrace_threshold",
    "set_label_cardinality_cap",
    "set_retrace_threshold",
    "set_timeline_capacity",
    "snapshot",
    "span",
    "sync_snapshot",
    "timeline_events",
    "to_json",
    "trace_counts",
    "unregister_slo",
    "watched_jit",
]

"""Host-side observability registry: named counters, gauges and span timers.

The eval stack's in-library instrumentation lives here (ISSUE 1 tentpole):
every layer — metric state machine, collection, evaluator, ops kernels,
sync toolkit — reports into ONE process-wide :class:`Registry` so a run can
answer "where did the time / bytes / dispatches go" from library data alone,
instead of ad-hoc prints in ``bench.py``.

Design constraints, in order:

* **Zero overhead while disabled.** Instrumented call sites gate on
  :func:`enabled` — a single module-global read — and do nothing else. No
  objects are allocated, no locks taken, no strings formatted. The flag is
  host-only and never read inside jit-traced code (annotation of traced code
  is resolved at trace time, ``obs/annotate.py``).
* **Thread-safe.** Metrics stream from data-loader threads and the async
  warn helper (``utils/tracing.py``) runs on daemon threads; one registry
  lock serialises structural mutation, and span nesting state is
  thread-local.
* **Host-side only.** Counters hold Python numbers. Device-time attribution
  is the profiler's job (``jax.named_scope`` baked into kernel HLO +
  ``jax.profiler.TraceAnnotation`` around dispatches); the registry tracks
  host wall time, call counts and byte volumes — the quantities XLA traces
  cannot see.

Instruments:

* **Counter** — monotone accumulator (``inc``); e.g. sync rounds, payload
  bytes, kernel calls.
* **Gauge** — last-written value (``set``); e.g. participating world size.
* **Histogram** — fixed log2-bucket latency/size distribution (``record``):
  O(buckets) memory forever, mergeable across ranks by bucket summation
  (every process shares the same static edges), p50/p95/p99 in
  ``snapshot()`` and proper ``# TYPE histogram`` Prometheus exposition.
* **Span timer** — aggregated wall-time statistics per span *path*. Spans
  nest: a span opened while another is active on the same thread records
  under ``"outer/inner"``, so time attributes hierarchically
  (``collection.update/metric.update/BinaryAUROC``). Each span path also
  feeds a log2 latency histogram (same bucket scheme), so ``snapshot()``
  reports percentiles, not only min/max/sum.

All instruments key on ``(name, labels)`` where labels are an optional small
dict (e.g. ``lane="SUM"``) — the Prometheus label model, which
``obs/export.py`` serialises directly. Spans recorded on the process-wide
default registry additionally feed the event timeline ring
(``obs/trace.py``) through a module-level sink, so the flight recorder sees
every span as a Chrome-trace complete event for free.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# Module-level enable flag. Read directly (`if not _enabled: return`) by the
# instrumentation helpers; mutate only through enable()/disable() so future
# hooks (e.g. starting a profiler server) have one choke point.
_enabled: bool = False


def enabled() -> bool:
    """True when observability collection is on (one global read)."""
    return _enabled


def enable() -> None:
    """Turn on registry collection and span/profiler annotation."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn off collection. Already-recorded values are kept (snapshot them
    first if needed); instrumented call sites revert to the no-op path."""
    global _enabled
    _enabled = False


_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# ---------------------------------------------------------- label cardinality
# Per-instrument-name cap on DISTINCT label sets (ISSUE 15 satellite). The
# registry holds every (name, labels) series forever — per-tenant labels
# under churn (thousands of tenants over a daemon's lifetime) would grow the
# maps without bound, and per-SLICE labels (millions of cohorts) would be a
# memory bomb: slice results flow through compute(), never through obs
# labels. Past the cap, NEW label sets for a name are dropped (existing
# series keep recording), counted into ``obs.labels.dropped{name=}`` and
# warned once per name — loud, bounded, and impossible to mistake for data.
_LABEL_SETS_CAP = 1024
_DROPPED_NAME = "obs.labels.dropped"


def set_label_cardinality_cap(cap: int) -> int:
    """Set the per-name distinct-label-set cap (returns the previous one).
    Applies to series CREATION: lowering the cap does not evict existing
    series. Test hook + escape hatch for unusually wide fleets."""
    global _LABEL_SETS_CAP
    if not isinstance(cap, int) or cap < 1:
        raise ValueError(f"label cardinality cap must be an int >= 1, got {cap!r}.")
    prev = _LABEL_SETS_CAP
    _LABEL_SETS_CAP = cap
    return prev


def format_key(name: str, labels: _LabelKey) -> str:
    """``name`` or ``name{k=v,...}`` — the snapshot-key spelling shared by
    :meth:`Registry.snapshot` and the cross-rank merge (``obs/distributed``),
    so local and cluster views correlate 1:1."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


# Sink wired by ``obs/trace.py`` at import: spans recorded on the DEFAULT
# registry (the only one the library reports into) are mirrored into the
# event timeline ring as complete events. Signature:
# ``(path, labels, t0_perf_counter, seconds) -> None``.
_span_sink: Optional[Callable[[str, _LabelKey, float, float], None]] = None


# ------------------------------------------------------- histogram buckets
# One static log2 bucket scheme for every histogram in the process (and the
# fleet: merging across ranks is bucket summation ONLY because the edges are
# compile-time constants, never data-dependent). Bucket ``i`` counts values
# in ``(2^(MIN_EXP+i), 2^(MIN_EXP+i+1)]``; the range spans ~7.5e-9 (under
# any measurable host latency in seconds) to ~1.4e11 (covers byte sizes and
# chunk counts too). O(buckets) memory per series, forever.
HISTOGRAM_MIN_EXP = -27
HISTOGRAM_BUCKETS = 64


def bucket_index(value: float) -> int:
    """Fixed log2 bucket for ``value`` (<=0 and NaN clamp to the first
    bucket, +inf to the last — ``math.frexp`` reports exponent 0 for
    non-finite input, which would otherwise mis-bucket them mid-range)."""
    if value <= 0.0 or value != value:
        return 0
    if value == math.inf:
        return HISTOGRAM_BUCKETS - 1
    m, e = math.frexp(value)  # value = m * 2^e, 0.5 <= m < 1
    # value in (2^(e-1), 2^e] -> upper edge 2^e, except the exact power of
    # two 2^(e-1) (m == 0.5), which belongs UNDER its own edge so the
    # Prometheus cumulative-le contract (count of values <= le) holds
    idx = e - 1 - HISTOGRAM_MIN_EXP
    if m == 0.5:
        idx -= 1
    if idx < 0:
        return 0
    if idx >= HISTOGRAM_BUCKETS:
        return HISTOGRAM_BUCKETS - 1
    return idx


def bucket_upper_edge(i: int) -> float:
    """Inclusive upper bound of bucket ``i``."""
    return 2.0 ** (HISTOGRAM_MIN_EXP + i + 1)


def percentile_from_buckets(
    buckets, count: int, q: float
) -> float:
    """Estimate the ``q``-quantile (0..1) from log2 bucket counts by linear
    interpolation inside the containing bucket. Shared by local snapshots
    and the cross-rank merge (bucket-summed histograms keep the same
    estimator)."""
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0.0
    for i, c in enumerate(buckets):
        if not c:
            continue
        if cum + c >= target:
            lower = bucket_upper_edge(i - 1) if i > 0 else 0.0
            upper = bucket_upper_edge(i)
            frac = (target - cum) / c
            return lower + frac * (upper - lower)
        cum += c
    return bucket_upper_edge(HISTOGRAM_BUCKETS - 1)


class Histogram:
    """Fixed-edge log2 histogram: O(buckets) memory, mergeable by bucket
    summation (identical static edges on every process)."""

    __slots__ = ("buckets", "count", "sum")

    def __init__(self) -> None:
        self.buckets: List[int] = [0] * HISTOGRAM_BUCKETS
        self.count = 0
        self.sum = 0.0

    def record(self, value: float) -> None:
        self.buckets[bucket_index(value)] += 1
        self.count += 1
        # a single inf/NaN observation must not poison the series' _sum
        # forever (Prometheus _sum lines and cross-rank merges both
        # propagate it); the clamped bucket above still counts the event
        if math.isfinite(value):
            self.sum += value

    def percentile(self, q: float) -> float:
        return percentile_from_buckets(self.buckets, self.count, q)


class Counter:
    """Monotone accumulator. ``inc`` must never be fed negative deltas."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"counter increments must be >= 0, got {delta}.")
        self.value += delta


class Gauge:
    """Last-written value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class SpanStats:
    """Aggregated wall-time statistics for one span path, plus the log2
    latency buckets behind the snapshot's p50/p95/p99."""

    __slots__ = ("count", "total_seconds", "max_seconds", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total_seconds = 0.0
        self.max_seconds = 0.0
        self.buckets: List[int] = [0] * HISTOGRAM_BUCKETS

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_seconds += seconds
        if seconds > self.max_seconds:
            self.max_seconds = seconds
        self.buckets[bucket_index(seconds)] += 1


class _Span:
    """Context manager for one span instance; see :meth:`Registry.span`."""

    __slots__ = ("_registry", "_name", "_labels", "_path", "_t0")

    def __init__(self, registry: "Registry", name: str, labels: _LabelKey):
        self._registry = registry
        self._name = name
        self._labels = labels
        self._path = None
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        stack = self._registry._span_stack()
        self._path = (
            f"{stack[-1]}/{self._name}" if stack else self._name
        )
        stack.append(self._path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        seconds = time.perf_counter() - self._t0
        stack = self._registry._span_stack()
        # pop OUR frame even if an inner span leaked (exception safety)
        while stack and stack[-1] != self._path:
            stack.pop()
        if stack:
            stack.pop()
        self._registry._record_span(
            self._path, self._labels, seconds, t0=self._t0
        )


class DeltaCursor:
    """Opaque position token for :meth:`Registry.delta_since`.

    Holds the generation the registry was in when the cursor was issued, a
    strictly-increasing sequence number (monotonic even across
    :meth:`Registry.reset` — a reset bumps the generation, never rewinds the
    sequence), and the per-series baseline values the next delta diffs
    against. The baseline lives here, not in the registry: the registry has
    no per-key dirty tracking and must not grow per-subscriber state."""

    __slots__ = ("gen", "seq", "base")

    def __init__(self, gen: int, seq: int, base: Dict[tuple, Any]) -> None:
        self.gen = gen
        self.seq = seq
        self.base = base


class Registry:
    """Thread-safe collection of counters, gauges and span timers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], Gauge] = {}
        self._histos: Dict[Tuple[str, _LabelKey], Histogram] = {}
        self._spans: Dict[Tuple[str, _LabelKey], SpanStats] = {}
        # distinct LABELED series created per instrument name, across all
        # instrument kinds — the label-cardinality guard's admission count
        self._label_sets: Dict[str, int] = {}
        # bumped by reset(); lets delta_since detect a cursor issued against
        # state that no longer exists and answer with a full diff instead of
        # a nonsensical (negative-counter) incremental one
        self._generation = 0
        self._local = threading.local()

    # ------------------------------------------------- label-cardinality cap
    def _admit_labels_locked(self, name: str, labels: _LabelKey) -> bool:
        """Called under the lock when a series is about to be CREATED:
        unlabeled series and the drop-accounting counter itself always
        admit; labeled series admit until the per-name cap."""
        if not labels or name == _DROPPED_NAME:
            return True
        n = self._label_sets.get(name, 0)
        if n >= _LABEL_SETS_CAP:
            return False
        self._label_sets[name] = n + 1
        return True

    def _count_dropped(self, name: str) -> None:
        """Outside the lock: account + warn once per capped name."""
        # literal name (== _DROPPED_NAME): the doc-drift lint scans for it
        self.counter("obs.labels.dropped", instrument=name)
        from torcheval_tpu.utils.telemetry import log_once

        log_once(
            f"obs.labels.capped:{name}",
            "obs registry: instrument %r exceeded the per-name label "
            "cardinality cap (%d distinct label sets); new label sets are "
            "dropped (existing series keep recording). High-cardinality "
            "dimensions (per-slice cohorts!) belong in compute() results, "
            "not obs labels. See docs/observability.md.",
            name,
            _LABEL_SETS_CAP,
        )

    # ------------------------------------------------------------ instruments
    def counter(self, name: str, delta: float = 1.0, **labels: Any) -> None:
        """Increment counter ``name`` (created on first use) by ``delta``."""
        key = (name, _label_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                if not self._admit_labels_locked(name, key[1]):
                    c = None
                else:
                    c = self._counters[key] = Counter()
            if c is not None:
                c.inc(delta)
                return
        self._count_dropped(name)

    def gauge(self, name: str, value: float, **labels: Any) -> None:
        """Set gauge ``name`` (created on first use) to ``value``."""
        key = (name, _label_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                if not self._admit_labels_locked(name, key[1]):
                    g = None
                else:
                    g = self._gauges[key] = Gauge()
            if g is not None:
                g.set(value)
                return
        self._count_dropped(name)

    def histo(self, name: str, value: float, **labels: Any) -> None:
        """Record ``value`` into histogram ``name`` (created on first use)."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histos.get(key)
            if h is None:
                if not self._admit_labels_locked(name, key[1]):
                    h = None
                else:
                    h = self._histos[key] = Histogram()
            if h is not None:
                h.record(value)
                return
        self._count_dropped(name)

    def span(self, name: str, **labels: Any) -> _Span:
        """Context manager timing a host-side span.

        Spans opened while another span is active on the same thread record
        under the joined path ``"outer/inner"`` — nested attribution with no
        double counting (the outer span still includes the inner's time, as
        a profiler trace would)."""
        return _Span(self, name, _label_key(labels))

    def observe_span(self, path: str, seconds: float, **labels: Any) -> None:
        """Record an already-measured duration under span ``path`` (no
        nesting — the caller measured around something that already ran,
        e.g. the compile time detected inside a watched_jit dispatch)."""
        self._record_span(
            path,
            _label_key(labels),
            seconds,
            t0=time.perf_counter() - seconds,
        )

    # --------------------------------------------------------------- plumbing
    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record_span(
        self,
        path: str,
        labels: _LabelKey,
        seconds: float,
        t0: Optional[float] = None,
    ) -> None:
        key = (path, labels)
        dropped = False
        with self._lock:
            s = self._spans.get(key)
            if s is None:
                if not self._admit_labels_locked(path, labels):
                    dropped = True
                else:
                    s = self._spans[key] = SpanStats()
            if s is not None:
                s.record(seconds)
        if dropped:
            self._count_dropped(path)
            return
        # default-registry spans mirror into the event timeline ring
        # (obs/trace.py): the sink call sits OUTSIDE the registry lock
        if _span_sink is not None and self is default_registry:
            _span_sink(
                path,
                labels,
                t0 if t0 is not None else time.perf_counter() - seconds,
                seconds,
            )

    # ----------------------------------------------------------------- export
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy as plain JSON-serialisable data:
        ``{"counters": {...}, "gauges": {...}, "histograms": {...},
        "spans": {...}}``.

        Keys are ``name`` or ``name{k=v,...}`` when labelled (the Prometheus
        spelling, so snapshot keys and exposition lines correlate 1:1).
        Span entries and histograms carry p50/p95/p99 estimated from the
        log2 buckets — latency distributions, not only min/max/sum."""
        fmt = format_key
        with self._lock:
            return {
                "counters": {
                    fmt(n, lb): c.value for (n, lb), c in self._counters.items()
                },
                "gauges": {
                    fmt(n, lb): g.value for (n, lb), g in self._gauges.items()
                },
                "histograms": {
                    fmt(n, lb): {
                        "count": h.count,
                        "sum": h.sum,
                        "p50": h.percentile(0.50),
                        "p95": h.percentile(0.95),
                        "p99": h.percentile(0.99),
                    }
                    for (n, lb), h in self._histos.items()
                },
                "spans": {
                    fmt(n, lb): {
                        "count": s.count,
                        "total_seconds": s.total_seconds,
                        "max_seconds": s.max_seconds,
                        "p50": percentile_from_buckets(
                            s.buckets, s.count, 0.50
                        ),
                        "p95": percentile_from_buckets(
                            s.buckets, s.count, 0.95
                        ),
                        "p99": percentile_from_buckets(
                            s.buckets, s.count, 0.99
                        ),
                    }
                    for (n, lb), s in self._spans.items()
                },
            }

    def _items(self) -> list:
        """``[(kind, name, labels, value), ...]`` — export helper. The list
        is MATERIALISED under the lock and returned: a generator yielding
        under the lock would hold it across the consumer's formatting work
        (stalling every instrumented thread for a whole export) and leak it
        outright if the consumer abandoned iteration. Span values are
        ``(count, total_seconds, max_seconds, buckets)``; histogram values
        ``(buckets, count, sum)`` — buckets copied as tuples so the consumer
        never aliases live mutable state."""
        with self._lock:
            return self._items_locked()

    def _items_locked(self) -> list:
        out: list = [
            ("counter", n, lb, c.value)
            for (n, lb), c in self._counters.items()
        ]
        out.extend(
            ("gauge", n, lb, g.value)
            for (n, lb), g in self._gauges.items()
        )
        out.extend(
            ("histo", n, lb, (tuple(h.buckets), h.count, h.sum))
            for (n, lb), h in self._histos.items()
        )
        out.extend(
            (
                "span",
                n,
                lb,
                (s.count, s.total_seconds, s.max_seconds, tuple(s.buckets)),
            )
            for (n, lb), s in self._spans.items()
        )
        return out

    # ---------------------------------------------------------------- deltas
    def delta_since(self, cursor: Optional[DeltaCursor]) -> tuple:
        """Diff the registry against ``cursor`` → ``(delta, new_cursor)``.

        ``delta`` is a plain JSON-serialisable dict carrying ONLY the series
        that changed since the cursor was issued — the O(changed) unit the
        obs push channel ships instead of full snapshots
        (``obs/stream.py`` folds deltas back into snapshots):

        * ``counters`` — increments (``new - base``; > 0 by monotonicity);
        * ``gauges`` — new absolute values (a gauge is last-write-wins, a
          numeric difference would be meaningless);
        * ``histograms`` / ``spans`` — sparse per-bucket count increments
          (``[[index, +n], ...]``) plus count/sum (span: count/total/max)
          increments; bucket increments sum exactly to the count increment.

        ``cursor=None`` (or a cursor from before the last :meth:`reset` —
        detected by generation) yields a FULL diff with ``"full": True``.
        The returned cursor's ``seq`` strictly increases across calls on the
        same cursor chain, including across resets."""
        with self._lock:
            # one critical section for both: a reset() between reading the
            # items and the generation would mislabel old values as new-gen
            gen = self._generation
            items = self._items_locked()
        fresh = cursor is None or cursor.gen != gen
        base: Dict[tuple, Any] = {} if fresh else cursor.base
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        histos: Dict[str, Any] = {}
        spans: Dict[str, Any] = {}
        new_base: Dict[tuple, Any] = {}
        for kind, name, lb, value in items:
            bkey = (kind, name, lb)
            new_base[bkey] = value
            prev = base.get(bkey)
            key = format_key(name, lb)
            if kind == "counter":
                d = value - (prev or 0.0)
                if d != 0.0:
                    counters[key] = d
            elif kind == "gauge":
                if prev is None or value != prev:
                    gauges[key] = value
            elif kind == "histo":
                buckets, count, total = value
                pb, pc, ps = prev if prev is not None else ((), 0, 0.0)
                if count != pc:
                    histos[key] = {
                        "buckets": [
                            [i, c - (pb[i] if i < len(pb) else 0)]
                            for i, c in enumerate(buckets)
                            if c != (pb[i] if i < len(pb) else 0)
                        ],
                        "count": count - pc,
                        "sum": total - ps,
                    }
            else:  # span
                count, total, mx, buckets = value
                pc, pt, pm, pb = prev if prev is not None else (0, 0.0, 0.0, ())
                if count != pc:
                    spans[key] = {
                        "buckets": [
                            [i, c - (pb[i] if i < len(pb) else 0)]
                            for i, c in enumerate(buckets)
                            if c != (pb[i] if i < len(pb) else 0)
                        ],
                        "count": count - pc,
                        "total_seconds": total - pt,
                        # max is monotone within a generation: ship the new
                        # absolute max, the accumulator takes max() over it
                        "max_seconds": mx,
                    }
        seq = 1 if cursor is None else cursor.seq + 1
        delta = {
            "v": 1,
            "gen": gen,
            "seq": seq,
            "full": bool(fresh),
            "counters": counters,
            "gauges": gauges,
            "histograms": histos,
            "spans": spans,
        }
        return delta, DeltaCursor(gen, seq, new_base)

    def reset(self) -> None:
        """Drop every instrument (fresh registry semantics). Live span
        contexts on other threads finish into fresh entries. Outstanding
        :class:`DeltaCursor` holders observe the generation bump and get a
        full diff on their next :meth:`delta_since`."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histos.clear()
            self._spans.clear()
            self._label_sets.clear()
            self._generation += 1


# The process-wide default registry every library call site reports into.
default_registry = Registry()


def counter(
    name: str,
    delta: float = 1.0,
    *,
    registry: Optional[Registry] = None,
    **labels: Any,
) -> None:
    """Increment a counter on the default registry IF obs is enabled —
    the guarded spelling library call sites use."""
    if not _enabled:
        return
    (registry or default_registry).counter(name, delta, **labels)


def gauge(
    name: str,
    value: float,
    *,
    registry: Optional[Registry] = None,
    **labels: Any,
) -> None:
    """Set a gauge on the default registry IF obs is enabled."""
    if not _enabled:
        return
    (registry or default_registry).gauge(name, value, **labels)


def histo(
    name: str,
    value: float,
    *,
    registry: Optional[Registry] = None,
    **labels: Any,
) -> None:
    """Record into a histogram on the default registry IF obs is enabled."""
    if not _enabled:
        return
    (registry or default_registry).histo(name, value, **labels)


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


def span(name: str, **labels: Any):
    """Span on the default registry IF obs is enabled; a shared no-op
    context manager (no allocation) otherwise."""
    if not _enabled:
        return _NULL_SPAN
    return default_registry.span(name, **labels)


def snapshot() -> Dict[str, Any]:
    """Snapshot the default registry (works whether or not obs is enabled)."""
    return default_registry.snapshot()


def reset() -> None:
    """Reset the default registry."""
    default_registry.reset()

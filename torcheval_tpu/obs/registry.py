"""Host-side observability registry: named counters, gauges and span timers.

The eval stack's in-library instrumentation lives here (ISSUE 1 tentpole):
every layer — metric state machine, collection, evaluator, ops kernels,
sync toolkit — reports into ONE process-wide :class:`Registry` so a run can
answer "where did the time / bytes / dispatches go" from library data alone,
instead of ad-hoc prints in ``bench.py``.

Design constraints, in order:

* **Zero overhead while disabled.** Instrumented call sites gate on
  :func:`enabled` — a single module-global read — and do nothing else. No
  objects are allocated, no locks taken, no strings formatted. The flag is
  host-only and never read inside jit-traced code (annotation of traced code
  is resolved at trace time, ``obs/annotate.py``).
* **Thread-safe.** Metrics stream from data-loader threads and the async
  warn helper (``utils/tracing.py``) runs on daemon threads; one registry
  lock serialises structural mutation, and span nesting state is
  thread-local.
* **Host-side only.** Counters hold Python numbers. Device-time attribution
  is the profiler's job (``jax.named_scope`` baked into kernel HLO +
  ``jax.profiler.TraceAnnotation`` around dispatches); the registry tracks
  host wall time, call counts and byte volumes — the quantities XLA traces
  cannot see.

Instruments:

* **Counter** — monotone accumulator (``inc``); e.g. sync rounds, payload
  bytes, kernel calls.
* **Gauge** — last-written value (``set``); e.g. participating world size.
* **Span timer** — aggregated wall-time statistics per span *path*. Spans
  nest: a span opened while another is active on the same thread records
  under ``"outer/inner"``, so time attributes hierarchically
  (``collection.update/metric.update/BinaryAUROC``).

All three key on ``(name, labels)`` where labels are an optional small dict
(e.g. ``lane="SUM"``) — the Prometheus label model, which ``obs/export.py``
serialises directly.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

# Module-level enable flag. Read directly (`if not _enabled: return`) by the
# instrumentation helpers; mutate only through enable()/disable() so future
# hooks (e.g. starting a profiler server) have one choke point.
_enabled: bool = False


def enabled() -> bool:
    """True when observability collection is on (one global read)."""
    return _enabled


def enable() -> None:
    """Turn on registry collection and span/profiler annotation."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn off collection. Already-recorded values are kept (snapshot them
    first if needed); instrumented call sites revert to the no-op path."""
    global _enabled
    _enabled = False


_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotone accumulator. ``inc`` must never be fed negative deltas."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, delta: float = 1.0) -> None:
        if delta < 0:
            raise ValueError(f"counter increments must be >= 0, got {delta}.")
        self.value += delta


class Gauge:
    """Last-written value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class SpanStats:
    """Aggregated wall-time statistics for one span path."""

    __slots__ = ("count", "total_seconds", "max_seconds")

    def __init__(self) -> None:
        self.count = 0
        self.total_seconds = 0.0
        self.max_seconds = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_seconds += seconds
        if seconds > self.max_seconds:
            self.max_seconds = seconds


class _Span:
    """Context manager for one span instance; see :meth:`Registry.span`."""

    __slots__ = ("_registry", "_name", "_labels", "_path", "_t0")

    def __init__(self, registry: "Registry", name: str, labels: _LabelKey):
        self._registry = registry
        self._name = name
        self._labels = labels
        self._path = None
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        stack = self._registry._span_stack()
        self._path = (
            f"{stack[-1]}/{self._name}" if stack else self._name
        )
        stack.append(self._path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        seconds = time.perf_counter() - self._t0
        stack = self._registry._span_stack()
        # pop OUR frame even if an inner span leaked (exception safety)
        while stack and stack[-1] != self._path:
            stack.pop()
        if stack:
            stack.pop()
        self._registry._record_span(self._path, self._labels, seconds)


class Registry:
    """Thread-safe collection of counters, gauges and span timers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, _LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, _LabelKey], Gauge] = {}
        self._spans: Dict[Tuple[str, _LabelKey], SpanStats] = {}
        self._local = threading.local()

    # ------------------------------------------------------------ instruments
    def counter(self, name: str, delta: float = 1.0, **labels: Any) -> None:
        """Increment counter ``name`` (created on first use) by ``delta``."""
        key = (name, _label_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
            c.inc(delta)

    def gauge(self, name: str, value: float, **labels: Any) -> None:
        """Set gauge ``name`` (created on first use) to ``value``."""
        key = (name, _label_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge()
            g.set(value)

    def span(self, name: str, **labels: Any) -> _Span:
        """Context manager timing a host-side span.

        Spans opened while another span is active on the same thread record
        under the joined path ``"outer/inner"`` — nested attribution with no
        double counting (the outer span still includes the inner's time, as
        a profiler trace would)."""
        return _Span(self, name, _label_key(labels))

    # --------------------------------------------------------------- plumbing
    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record_span(
        self, path: str, labels: _LabelKey, seconds: float
    ) -> None:
        key = (path, labels)
        with self._lock:
            s = self._spans.get(key)
            if s is None:
                s = self._spans[key] = SpanStats()
            s.record(seconds)

    # ----------------------------------------------------------------- export
    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy as plain JSON-serialisable data:
        ``{"counters": {...}, "gauges": {...}, "spans": {...}}``.

        Keys are ``name`` or ``name{k=v,...}`` when labelled (the Prometheus
        spelling, so snapshot keys and exposition lines correlate 1:1)."""

        def fmt(name: str, labels: _LabelKey) -> str:
            if not labels:
                return name
            inner = ",".join(f"{k}={v}" for k, v in labels)
            return f"{name}{{{inner}}}"

        with self._lock:
            return {
                "counters": {
                    fmt(n, lb): c.value for (n, lb), c in self._counters.items()
                },
                "gauges": {
                    fmt(n, lb): g.value for (n, lb), g in self._gauges.items()
                },
                "spans": {
                    fmt(n, lb): {
                        "count": s.count,
                        "total_seconds": s.total_seconds,
                        "max_seconds": s.max_seconds,
                    }
                    for (n, lb), s in self._spans.items()
                },
            }

    def _items(self) -> list:
        """``[(kind, name, labels, value), ...]`` — export helper. The list
        is MATERIALISED under the lock and returned: a generator yielding
        under the lock would hold it across the consumer's formatting work
        (stalling every instrumented thread for a whole export) and leak it
        outright if the consumer abandoned iteration."""
        with self._lock:
            out: list = [
                ("counter", n, lb, c.value)
                for (n, lb), c in self._counters.items()
            ]
            out.extend(
                ("gauge", n, lb, g.value)
                for (n, lb), g in self._gauges.items()
            )
            out.extend(
                ("span", n, lb, (s.count, s.total_seconds, s.max_seconds))
                for (n, lb), s in self._spans.items()
            )
            return out

    def reset(self) -> None:
        """Drop every instrument (fresh registry semantics). Live span
        contexts on other threads finish into fresh entries."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._spans.clear()


# The process-wide default registry every library call site reports into.
default_registry = Registry()


def counter(
    name: str,
    delta: float = 1.0,
    *,
    registry: Optional[Registry] = None,
    **labels: Any,
) -> None:
    """Increment a counter on the default registry IF obs is enabled —
    the guarded spelling library call sites use."""
    if not _enabled:
        return
    (registry or default_registry).counter(name, delta, **labels)


def gauge(
    name: str,
    value: float,
    *,
    registry: Optional[Registry] = None,
    **labels: Any,
) -> None:
    """Set a gauge on the default registry IF obs is enabled."""
    if not _enabled:
        return
    (registry or default_registry).gauge(name, value, **labels)


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


def span(name: str, **labels: Any):
    """Span on the default registry IF obs is enabled; a shared no-op
    context manager (no allocation) otherwise."""
    if not _enabled:
        return _NULL_SPAN
    return default_registry.span(name, **labels)


def snapshot() -> Dict[str, Any]:
    """Snapshot the default registry (works whether or not obs is enabled)."""
    return default_registry.snapshot()


def reset() -> None:
    """Reset the default registry."""
    default_registry.reset()

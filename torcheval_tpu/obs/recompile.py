"""Recompile watchdog: per-entry-point abstract-signature → trace counts.

Retrace storms are the silent killer of dispatch-floor wins: a jitted entry
point fed a drifting shape signature (unpadded final batches, python-scalar
arguments that vary per step, accidental weak-type flips) recompiles every
few calls, and the loop silently runs at compile speed instead of dispatch
speed. Nothing in JAX warns by default.

The watchdog hooks the one place a retrace cannot hide: the *traced python
function* of a ``jax.jit`` entry point only executes when the jit cache
misses. :func:`watched_jit` wraps the function with a probe that records the
call's **abstract signature** in two halves — the static half (pytree
structure + non-array leaf values: distinct statics are distinct *programs*)
and the dynamic half (``(shape, dtype, weak_type)`` per array leaf). A storm
is :func:`retrace_threshold` distinct DYNAMIC signatures for one jit
instance under ONE static configuration — anything looser would misreport
legitimate program diversity (several collections sharing a label, several
metric classes' folds behind one entry) as a storm. On a storm the watchdog
warns ONCE per entry point through the telemetry logger
(``utils/telemetry.py::log_once``), naming the entry point and the most
recent signature so the drifting argument is identifiable.

Cost model: bookkeeping runs only at trace time (already paying an XLA
compile, milliseconds at minimum), so the watchdog is always on — there is
no per-dispatch overhead to gate. The jit-cache *hit* path is byte-identical
to a plain ``jax.jit`` call. While obs is enabled, trace counts are mirrored
into the registry (``recompile.traces{entry=...}``) so snapshots carry them.

The probe also enters ``jax.named_scope(name)`` around the traced body, so
every op the kernel lowers carries the entry point's name in XLA profiler
traces — device-time attribution per kernel for free (scope entry happens at
trace time only; see ``obs/annotate.py``).
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from torcheval_tpu.obs import cost as _cost
from torcheval_tpu.obs import registry as _registry
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.obs.annotate import annotated_call
from torcheval_tpu.utils.telemetry import log_once, reset_once_keys

_WARN_KEY_PREFIX = "torcheval_tpu.obs.recompile/"

_lock = threading.Lock()
# entry-point name -> {abstract signature -> trace count}
_traces: Dict[str, Dict[Any, int]] = {}
# every LIVE watched_jit instance's per-static-key signature store, so
# reset() can clear them: a reset that re-arms the storm warnings but keeps
# stale per-instance signature sets would re-fire instantly on the next
# single trace (ISSUE 15 regression — per-slice oracle loops in one test
# leaked storm state into a later test's churn-free assertion). Held
# WEAKLY (review finding): a dropped wrapper's store must be collectable
# with its closure, not pinned by this registry forever — dynamic
# watched_jit factories (ops/topk.py per-config lowerings, user wrappers)
# would otherwise accumulate dead stores without bound.
_group_stores: "weakref.WeakSet" = weakref.WeakSet()
_threshold = 8


class _GroupStore(dict):
    """A watched_jit instance's static-key -> {dynamic signatures} store.
    A dict subclass ONLY so :data:`_group_stores` can reference it weakly
    (plain dicts have no ``__weakref__`` slot). Identity hash/eq restore
    set-membership semantics dict removes: the WeakSet must treat two
    (possibly both-empty, hence dict-equal) stores as distinct members."""

    __hash__ = object.__hash__

    def __eq__(self, other):
        return self is other

    def __ne__(self, other):
        return self is not other


def retrace_threshold() -> int:
    """Distinct abstract signatures per entry point before the watchdog
    warns (default 8 — a steady eval loop sees 1-3: warmup shapes plus the
    final partial batch)."""
    return _threshold


def set_retrace_threshold(n: int) -> None:
    if n < 2:
        raise ValueError(f"retrace threshold must be >= 2, got {n}.")
    global _threshold
    _threshold = n


def split_signature(args: tuple, kwargs: dict) -> Tuple[Any, Any]:
    """``(static_key, dynamic_sig)`` — the two halves of a jit cache key.

    ``static_key`` is the pytree structure plus every non-array leaf's value
    (how static arguments key the jit cache: distinct statics are distinct
    *programs*, not retraces of one program). ``dynamic_sig`` is
    ``(shape, dtype, weak_type)`` per array(-ish) leaf. weak_type matters:
    a python-scalar-fed leaf (weak f32) and a committed f32 array retrace
    separately in jax's cache, and that flip is one of the storm patterns
    this watchdog exists to name."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    dynamic = []
    static = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            weak = getattr(leaf, "weak_type", None)
            if weak is None:
                weak = getattr(
                    getattr(leaf, "aval", None), "weak_type", False
                )
            dynamic.append((tuple(leaf.shape), str(leaf.dtype), bool(weak)))
        else:
            try:
                hash(leaf)
                static.append(leaf)
            except TypeError:
                static.append(type(leaf).__name__)
    return (str(treedef), tuple(static)), tuple(dynamic)


def abstract_signature(args: tuple, kwargs: dict) -> Tuple[Any, ...]:
    """Full hashable jit-cache-shaped key for a call (static + dynamic
    halves of :func:`split_signature` together)."""
    static_key, dynamic = split_signature(args, kwargs)
    return (static_key, dynamic)


def record_trace(
    name: str,
    args: tuple,
    kwargs: dict,
    groups: Optional[Dict[Any, set]] = None,
) -> None:
    """Record one (re)trace of entry point ``name``. Called from trace-time
    probes only.

    ``groups`` is the calling ``watched_jit`` instance's own per-static-key
    store, and is what the storm warning fires on: a storm is many distinct
    DYNAMIC signatures for the SAME program — one jit instance, one static
    configuration. Counting any looser than that misreports legitimate
    program diversity as a storm (the concat and stacked fold dispatchers
    sharing the \"deferred.fold\" label, or several metric classes' folds
    sharing one dispatcher with distinct static fold_fns, each trace
    exactly once). The module-wide ``_traces`` table keeps the full
    per-label view for :func:`trace_counts`/export.

    A cost-capture re-lowering (``obs/cost.py``) re-runs the traced body
    purely for analysis — not a real compile, so it is invisible here."""
    if _cost.capturing():
        return
    static_key, dynamic = split_signature(args, kwargs)
    with _lock:
        per_entry = _traces.setdefault(name, {})
        full = (static_key, dynamic)
        per_entry[full] = per_entry.get(full, 0) + 1
        total = sum(per_entry.values())
        if groups is None:
            distinct = 0
        else:
            seen = groups.setdefault(static_key, set())
            seen.add(dynamic)
            distinct = len(seen)
    _registry.counter("recompile.traces", entry=name)
    _trace.instant("watched_jit.trace", kind="jit", entry=name)
    if distinct >= _threshold:
        log_once(
            _WARN_KEY_PREFIX + name,
            "Retrace storm on jitted entry point %r: %d traces, %d distinct "
            "abstract signatures for one static configuration (threshold "
            "%d). A drifting shape/dtype/weak-type argument is recompiling "
            "this entry point per call — pad batches to a fixed shape or "
            "hoist the varying argument. Most recent signature: %r",
            name,
            total,
            distinct,
            _threshold,
            (static_key, dynamic),
        )


def trace_counts() -> Dict[str, Dict[str, int]]:
    """``{entry point: {"traces": total, "distinct_signatures": n}}`` —
    snapshot of the watchdog's bookkeeping (always on, obs flag or not)."""
    with _lock:
        return {
            name: {
                "traces": sum(d.values()),
                "distinct_signatures": len(d),
            }
            for name, d in _traces.items()
        }


def reset(*, rearm_warnings: bool = True) -> None:
    """Clear trace bookkeeping — the module table AND every watched_jit
    instance's per-static-key signature store (and by default re-arm the
    once-per-entry warnings) — fresh-run semantics for tests and
    long-lived processes. Clearing the instance stores matters: a re-armed
    warning over surviving signature sets would re-fire on the very next
    trace of an entry an earlier run legitimately stormed."""
    with _lock:
        _traces.clear()
        for groups in list(_group_stores):
            groups.clear()
    if rearm_warnings:
        reset_once_keys(_WARN_KEY_PREFIX)


def watched_jit(
    fun: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    **jit_kwargs,
) -> Callable:
    """Drop-in ``jax.jit`` replacement for library entry points.

    Adds, on top of ``jax.jit(fun, **jit_kwargs)``:

    * retrace counting + the watchdog warning (trace-time only);
    * ``jax.named_scope`` around the traced body — XLA profiler attribution
      per entry point with zero run-time cost;
    * while obs is enabled: a ``TraceAnnotation`` + registry span around
      each dispatch, a ``jit.calls{entry=...}`` counter, a timeline event
      per dispatch (trace vs cache hit), and — on calls that actually
      traced — a ``jit.compile/<entry>`` span measuring the compile-bearing
      dispatch plus device cost attribution (``obs/cost.py``:
      ``obs.cost.{flops,bytes_accessed,hbm_bytes}{entry=}``). Disabled
      path: one module-global read on top of the plain jitted call.

    Usable as ``@watched_jit``, ``@watched_jit(name=...)``, or
    ``functools.partial``-style with jit kwargs
    (``watched_jit(f, static_argnames=("n",))``).
    """
    if fun is None:
        return lambda f: watched_jit(f, name=name, **jit_kwargs)
    label = name or getattr(fun, "__qualname__", None) or repr(fun)
    # THIS instance's static-key -> {dynamic signatures} store: the storm
    # warning counts retraces of one program (one jit instance, one static
    # configuration), never across instances that share a label. Registered
    # (weakly) module-wide so reset() clears it with the rest of the
    # bookkeeping while a dropped wrapper's store stays collectable.
    groups: Dict[Any, set] = _GroupStore()
    with _lock:
        _group_stores.add(groups)
    # trace-detection cell: the probe flips it, the obs-enabled dispatch
    # wrapper clears-then-checks it around each call, so a compile-bearing
    # dispatch is distinguishable from a cache hit without touching jit
    # internals. A benign race under concurrent dispatch of one entry
    # (worst case: one missed or spurious cost capture), never corruption.
    state = {"traced": False}

    @functools.wraps(fun)
    def probe(*args, **kwargs):
        state["traced"] = True
        record_trace(label, args, kwargs, groups)
        with jax.named_scope(label):
            return fun(*args, **kwargs)

    jitted = jax.jit(probe, **jit_kwargs)

    @functools.wraps(fun)
    def call(*args, **kwargs):
        if not _registry._enabled:
            return jitted(*args, **kwargs)
        reg = _registry.default_registry
        reg.counter("jit.calls", entry=label)
        state["traced"] = False
        t0 = time.perf_counter()
        out = annotated_call(f"jit/{label}", jitted, args, kwargs)
        if state["traced"]:
            state["traced"] = False
            # this dispatch paid the XLA compile: record it as a span (the
            # compile-time attribution the cost gauges sit beside) and pull
            # the program's device cost off the lowered/compiled objects
            compile_s = time.perf_counter() - t0
            # observe_span also lands the timeline complete event via the
            # registry's span sink — one "jit.compile/<entry>" bar per
            # compile-bearing dispatch
            reg.observe_span(f"jit.compile/{label}", compile_s)
            _cost.capture(label, jitted, args, kwargs)
        else:
            _trace.instant(
                "watched_jit.cache_hit", kind="jit", entry=label
            )
        return out

    # expose the underlying jit object (and its lower/eval_shape, which
    # HLO-inspecting tests and tooling call directly on jit entry points)
    call.jitted = jitted
    call.lower = jitted.lower
    call.eval_shape = jitted.eval_shape
    call.__obs_entry__ = label
    return call

"""Cross-rank obs aggregation: every rank's registry + timeline merged into
one queryable cluster view in ONE collective round (ISSUE 7 tentpole leg 4).

The fault tests already dump per-rank obs snapshots as separate JSON files;
answering "which rank is the straggler" then means hand-joining four
documents. :func:`sync_snapshot` does the join in-library, over the same
toolkit allgather funnel every metric sync rides — so it inherits the chaos
hooks, the deadline watchdog and the degraded-local policy of PR 5 for
free, and its wire cost is observable (exactly ONE
``toolkit.sync.rounds`` increment).

Wire: each rank pickles a structured dump of its default registry
(counters/gauges/histograms/spans as ``(name, labels, value)`` items) plus
its timeline events, pads it into a fixed ``max_bytes`` buffer with an
8-byte length header, and ONE ``_allgather_stacked`` round moves every
rank's buffer (fixed-size payloads are what make a single round possible —
a variable-length exchange needs a length round first, like the object
lane's two rounds). A rank whose dump exceeds the budget degrades in
stages — events dropped first (they dominate), then everything but a stub —
and flags itself ``truncated``; it never raises one-sidedly (that would
hang the peers mid-collective) and never sends more than ``max_bytes``.

Merge semantics, per instrument:

* **counters** — summed across ranks (same ``(name, labels)`` series);
* **gauges** — last-write-wins has no cross-rank meaning, so each rank's
  value keeps its identity under an appended ``rank=`` label;
* **histograms** — bucket-summed (the fixed log2 edges are identical on
  every process by construction), percentiles re-estimated on the merged
  buckets;
* **spans** — counts and totals summed, max of max, buckets summed;
* **timeline events** — rank-tagged and concatenated, ordered by
  ``(rank, ts)``; per-process ``perf_counter`` clocks are NOT comparable,
  so no cross-rank time alignment is attempted (Chrome trace renders each
  rank as its own process row via the ``rank`` pid).

Failure semantics (the PR 5 contract): ``timeout_s`` bounds the single
round; on expiry — or a transport error from a dead peer —
``on_failure="raise"`` raises the :class:`~torcheval_tpu.metrics.toolkit.
SyncError` while ``"local"`` warns once, bumps
``toolkit.sync.timeouts{policy=local}`` and returns the LOCAL single-rank
view with ``"degraded": True``, so a monitoring loop keeps reporting
through a preemption instead of wedging.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torcheval_tpu.obs import registry as _registry
from torcheval_tpu.obs import trace as _trace
from torcheval_tpu.obs.registry import (
    format_key,
    percentile_from_buckets,
)

_HEADER_BYTES = 8
DEFAULT_MAX_BYTES = 1 << 20  # per-rank snapshot budget; see sync_snapshot()


def _local_payload(rank: int) -> Dict[str, Any]:
    """This rank's registry + timeline as a plain picklable structure
    (items keep the registry's ``(name, labels, value)`` form so the merge
    is structural, not string-parsing)."""
    counters: List[Tuple[str, tuple, float]] = []
    gauges: List[Tuple[str, tuple, float]] = []
    histos: List[Tuple[str, tuple, Any]] = []
    spans: List[Tuple[str, tuple, Any]] = []
    for kind, name, labels, value in _registry.default_registry._items():
        if kind == "counter":
            counters.append((name, labels, value))
        elif kind == "gauge":
            gauges.append((name, labels, value))
        elif kind == "histo":
            histos.append((name, labels, value))
        else:
            spans.append((name, labels, value))
    return {
        "rank": rank,
        "counters": counters,
        "gauges": gauges,
        "histos": histos,
        "spans": spans,
        "events": _trace.events(),
        "truncated": False,
    }


def _encode(payload: Dict[str, Any], max_bytes: int) -> np.ndarray:
    """Fixed-size wire buffer: 8-byte little-endian length + pickle. Over
    budget, stage down (drop events, then everything but a stub) — NEVER
    raise one-sidedly, never exceed ``max_bytes``."""
    budget = max_bytes - _HEADER_BYTES
    stages = [
        payload,
        {**payload, "events": [], "truncated": True},
        {"rank": payload["rank"], "counters": [], "gauges": [], "histos": [],
         "spans": [], "events": [], "truncated": True},
    ]
    raw = b""
    for stage in stages:
        raw = pickle.dumps(stage)
        if len(raw) <= budget:
            break
    if len(raw) > budget:
        # even the stub does not fit (absurdly small max_bytes): send an
        # empty buffer — the peers decode it as None and drop this rank
        # from the merge, which still beats crashing mid-collective
        raw = b""
    buf = np.zeros(max_bytes, dtype=np.uint8)
    buf[:_HEADER_BYTES] = np.frombuffer(
        len(raw).to_bytes(_HEADER_BYTES, "little"), dtype=np.uint8
    )
    buf[_HEADER_BYTES : _HEADER_BYTES + len(raw)] = np.frombuffer(
        raw, dtype=np.uint8
    )
    return buf


def _decode(buf: np.ndarray) -> Optional[Dict[str, Any]]:
    try:
        n = int.from_bytes(buf[:_HEADER_BYTES].tobytes(), "little")
        if n <= 0 or n > buf.size - _HEADER_BYTES:
            return None
        return pickle.loads(buf[_HEADER_BYTES : _HEADER_BYTES + n].tobytes())
    except Exception:
        return None


def _merge(
    payloads: List[Dict[str, Any]],
    world_size: int,
    *,
    degraded: bool = False,
) -> Dict[str, Any]:
    """Fold per-rank payloads into one cluster view (see module doc for the
    per-instrument semantics)."""
    counters: Dict[Tuple[str, tuple], float] = {}
    gauges: Dict[Tuple[str, tuple], float] = {}
    histos: Dict[Tuple[str, tuple], list] = {}  # [buckets, count, sum]
    spans: Dict[Tuple[str, tuple], list] = {}  # [count, total, max, buckets]
    events: List[Dict[str, Any]] = []
    truncated_ranks: List[int] = []
    for p in payloads:
        rank = p.get("rank", 0)
        if p.get("truncated"):
            truncated_ranks.append(rank)
        for name, labels, value in p.get("counters", ()):
            key = (name, tuple(labels))
            counters[key] = counters.get(key, 0.0) + value
        for name, labels, value in p.get("gauges", ()):
            key = (name, tuple(labels) + (("rank", str(rank)),))
            gauges[key] = value
        for name, labels, value in p.get("histos", ()):
            buckets, count, total = value
            key = (name, tuple(labels))
            acc = histos.get(key)
            if acc is None:
                histos[key] = [list(buckets), count, total]
            else:
                for i, c in enumerate(buckets):
                    acc[0][i] += c
                acc[1] += count
                acc[2] += total
        for name, labels, value in p.get("spans", ()):
            count, total, mx, buckets = value
            key = (name, tuple(labels))
            acc = spans.get(key)
            if acc is None:
                spans[key] = [count, total, mx, list(buckets)]
            else:
                acc[0] += count
                acc[1] += total
                acc[2] = max(acc[2], mx)
                for i, c in enumerate(buckets):
                    acc[3][i] += c
        for e in p.get("events", ()):
            events.append({**e, "rank": rank})
    events.sort(key=lambda e: (e.get("rank", 0), e.get("ts", 0.0)))
    return {
        "world_size": world_size,
        "ranks": sorted(p.get("rank", 0) for p in payloads),
        "degraded": degraded,
        "truncated_ranks": sorted(truncated_ranks),
        "counters": {
            format_key(n, lb): v for (n, lb), v in counters.items()
        },
        "gauges": {format_key(n, lb): v for (n, lb), v in gauges.items()},
        "histograms": {
            format_key(n, lb): {
                "count": count,
                "sum": total,
                "p50": percentile_from_buckets(buckets, count, 0.50),
                "p95": percentile_from_buckets(buckets, count, 0.95),
                "p99": percentile_from_buckets(buckets, count, 0.99),
            }
            for (n, lb), (buckets, count, total) in histos.items()
        },
        "spans": {
            format_key(n, lb): {
                "count": count,
                "total_seconds": total,
                "max_seconds": mx,
                "p50": percentile_from_buckets(buckets, count, 0.50),
                "p95": percentile_from_buckets(buckets, count, 0.95),
                "p99": percentile_from_buckets(buckets, count, 0.99),
            }
            for (n, lb), (count, total, mx, buckets) in spans.items()
        },
        "events": events,
    }


def sync_snapshot(
    *,
    timeout_s: Optional[float] = None,
    on_failure: str = "raise",
    processes: Optional[Sequence[int]] = None,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> Dict[str, Any]:
    """Merge every rank's obs registry and timeline into one cluster view
    over exactly ONE collective round.

    ``timeout_s`` / ``on_failure`` follow the PR 5 sync contract
    (``"local"`` degrades to this rank's view with ``"degraded": True``);
    ``processes`` restricts the exchange to a toolkit subgroup;
    ``max_bytes`` is the per-rank wire budget and MUST be identical on
    every calling rank (it fixes the collective's buffer shape — that is
    what makes one round sufficient). At world size 1 no collective runs;
    the local view is returned in the same shape.
    """
    # toolkit is imported lazily: obs must stay importable without pulling
    # the whole metrics stack (and toolkit itself imports obs.registry)
    from torcheval_tpu.metrics import toolkit as tk

    if max_bytes <= _HEADER_BYTES:
        raise ValueError(f"max_bytes must be > {_HEADER_BYTES}, got {max_bytes}.")
    tk._check_failure_policy(on_failure)
    group = tk._resolve_group(processes)
    world = len(group) if group is not None else tk._world_size()
    rank = tk._process_index()
    # the merge is itself a sync API: span + timeline event like every
    # other sync entry point (fires at world size 1 too — the flight
    # recorder shows the snapshot was taken even when no collective ran)
    with _registry.span("obs.sync_snapshot", world=world):
        local = _local_payload(rank)
        if world == 1:
            return _merge([local], 1)
        buf = _encode(local, max_bytes)
        try:
            with tk._sync_deadline(timeout_s):
                gathered = tk._allgather_stacked(
                    buf, group, "obs-snapshot", "obs"
                ).reshape(world, max_bytes)
        except tk.SyncError as err:
            tk._sync_failure(err, on_failure)
            return _merge([local], 1, degraded=True)
        payloads = [p for r in range(world) if (p := _decode(gathered[r]))]
        return _merge(payloads, world)

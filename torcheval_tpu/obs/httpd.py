"""Stdlib-only HTTP scrape endpoint: Prometheus ``/metrics`` + ``/health``.

ISSUE 16 satellite. A daemon operator points a Prometheus scraper (or
``curl``) at the serving host without adding a single dependency::

    daemon = EvalDaemon(metrics_port=0).start()   # port 0: ephemeral
    # daemon.metrics_address -> ("127.0.0.1", 43121)

or standalone around any registry::

    srv = MetricsServer(port=0).start()
    urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics")

Routes:

* ``GET /metrics`` — ``obs.prometheus_text()`` (proper ``# TYPE``
  families, text exposition format 0.0.4);
* ``GET /health`` — JSON from the wired ``health_provider`` (the daemon
  wires :meth:`EvalDaemon.load_report`), or a minimal
  ``{"ok": true}`` when standalone.

One ``ThreadingHTTPServer`` on a daemon thread: scrapes never touch the
serving path, a slow scraper blocks only its own connection, and
``close()`` is idempotent. Binding is loopback by default — this is an
operator port, not a public one; pass ``host="0.0.0.0"`` deliberately.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from torcheval_tpu.obs import export as _export
from torcheval_tpu.obs.registry import Registry

__all__ = ["MetricsServer"]


class MetricsServer:
    """Serve ``/metrics`` (Prometheus) and ``/health`` (JSON) on a
    background thread. ``port=0`` binds an ephemeral port (read ``.port``
    after :meth:`start`)."""

    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[Registry] = None,
        health_provider: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> None:
        self._host = host
        self._bind_port = port
        self._registry = registry
        self._health_provider = health_provider
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        registry = self._registry
        health_provider = self._health_provider

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib handler name)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = _export.prometheus_text(registry).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif path == "/health":
                        report = (
                            health_provider()
                            if health_provider is not None
                            else {"ok": True}
                        )
                        body = json.dumps(report, default=str).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown path")
                        return
                except Exception as exc:  # a broken provider must not 500
                    # the whole server into silence — report it as the body
                    body = json.dumps(
                        {"ok": False, "error": repr(exc)}
                    ).encode()
                    ctype = "application/json"
                    self.send_response(500)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # scrapes must not spam stderr

        self._httpd = ThreadingHTTPServer(
            (self._host, self._bind_port), _Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="torcheval-tpu-metrics-httpd",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        """The bound port (meaningful after :meth:`start`)."""
        if self._httpd is None:
            return self._bind_port
        return self._httpd.server_address[1]

    @property
    def address(self) -> tuple:
        """``(host, port)`` as bound."""
        return (self._host, self.port)

    def close(self) -> None:
        """Stop serving and release the port. Idempotent."""
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

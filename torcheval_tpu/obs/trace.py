"""Event timeline: a bounded, thread-safe ring of structured events plus
Chrome/Perfetto ``trace_event`` JSON export — the flight-recorder leg of the
obs subsystem (ISSUE 7 tentpole).

Since the whole eval window became ONE donated pjit program (PR 6) and curve
sync became 3 collectives (PR 4), flat counters cannot answer *when* things
happened or *how long each instance took* — only how many and how much in
total. The timeline records every individual occurrence:

* every **span** recorded on the default registry (``registry._span_sink``
  mirrors span closes here), so ``collection.update``, window-step
  dispatches, sync API calls and checkpoint save/restore all appear as
  Chrome complete events with their real start time and duration;
* explicit **instants/completes** from the dispatch-site hooks —
  ``deferred.window.{open,append,valve,close}``,
  ``deferred.window_step.{dispatch,retire}``, ``watched_jit`` trace vs
  cache-hit, ``jit.compile/<entry>``, ``toolkit.sync.round`` (per lane and
  round), ``resilience.checkpoint.*`` and ``resilience.chaos`` injections.

Cost model: every hook gates on the obs enable flag — ONE module-global
read on the disabled path, no allocation, no lock (the PR 6 host-diet µs
numbers must not move; ``tests/obs/test_host_overhead.py`` pins it). While
enabled, an append is one lock acquisition and one ``deque.append``; the
ring is bounded (default 16384 events), so a multi-hour run records the
newest window of activity in O(capacity) memory and counts what it dropped.

Timestamps are ``time.perf_counter`` seconds relative to a module-load
epoch — monotonic and high-resolution, but NOT comparable across processes
(``obs.sync_snapshot`` rank-tags merged events instead of aligning clocks).

Usage::

    obs.enable()
    ... run ...
    open("trace.json", "w").write(obs.chrome_trace())
    # chrome://tracing or https://ui.perfetto.dev loads it directly
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from torcheval_tpu.obs import registry as _registry

DEFAULT_CAPACITY = 16384

_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_dropped = 0
# perf_counter epoch for this process: event ts are seconds since this
_epoch = time.perf_counter()


class Event:
    """One timeline entry: ``ts``/``dur`` are seconds relative to the module
    epoch (``dur == 0`` marks an instant), ``kind`` is the coarse category
    (span / window / jit / compile / sync / checkpoint / chaos), ``labels``
    a small str->value dict, ``tid`` the recording thread."""

    __slots__ = ("ts", "dur", "name", "kind", "labels", "tid")

    def __init__(
        self,
        ts: float,
        dur: float,
        name: str,
        kind: str,
        labels: Dict[str, Any],
        tid: int,
    ) -> None:
        self.ts = ts
        self.dur = dur
        self.name = name
        self.kind = kind
        self.labels = labels
        self.tid = tid

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ts": self.ts,
            "dur": self.dur,
            "name": self.name,
            "kind": self.kind,
            "labels": dict(self.labels),
            "tid": self.tid,
        }


def _append(event: Event) -> None:
    global _dropped
    with _lock:
        if len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(event)


def instant(name: str, kind: str = "instant", **labels: Any) -> None:
    """Record a zero-duration event IF obs is enabled (one global read and
    nothing else on the disabled path)."""
    if not _registry._enabled:
        return
    _append(
        Event(
            time.perf_counter() - _epoch,
            0.0,
            name,
            kind,
            labels,
            threading.get_ident(),
        )
    )


def complete(
    name: str, t0: float, seconds: float, kind: str = "span", **labels: Any
) -> None:
    """Record a duration event whose start was ``t0`` (a ``perf_counter``
    reading) IF obs is enabled."""
    if not _registry._enabled:
        return
    _append(
        Event(
            t0 - _epoch,
            seconds,
            name,
            kind,
            labels,
            threading.get_ident(),
        )
    )


def _on_span(path: str, labels, t0: float, seconds: float) -> None:
    """Registry span sink: default-registry span closes become timeline
    complete events (labels arrive as the registry's sorted tuple form)."""
    _append(
        Event(
            t0 - _epoch,
            seconds,
            path,
            "span",
            dict(labels),
            threading.get_ident(),
        )
    )


# wire the sink: every span recorded on the default registry (only ever
# while obs is enabled — the disabled span() returns a no-op context)
# mirrors into this ring
_registry._span_sink = _on_span


def events() -> List[Dict[str, Any]]:
    """Snapshot of the ring, oldest first, as plain dicts."""
    with _lock:
        return [e.as_dict() for e in _ring]


def event_count() -> int:
    with _lock:
        return len(_ring)


def events_since(offset: int):
    """``(events, total)`` — events whose all-time index is ``>= offset``,
    plus the all-time count (``dropped + ring``), read under ONE lock so the
    pair is consistent. The obs stream's timeline cursor: a subscriber holds
    the last ``total`` it saw and gets only newer events on the next delta
    (events already evicted from the ring are simply gone — bounded memory
    wins over completeness, same contract as the ring itself). An ``offset``
    ahead of ``total`` (ring was :func:`clear`-ed, e.g. ``obs.reset()``)
    rewinds to the whole ring."""
    with _lock:
        total = _dropped + len(_ring)
        if offset > total:
            offset = 0
        start = max(0, offset - _dropped)
        return [e.as_dict() for e in list(_ring)[start:]], total


def dropped() -> int:
    """Events evicted since the last :func:`clear` (ring overflow)."""
    with _lock:
        return _dropped


def capacity() -> int:
    return _ring.maxlen or 0


def set_capacity(n: int) -> None:
    """Resize the ring (keeps the newest ``n`` events; a shrink counts the
    evicted events as dropped — the export's ``dropped_events`` must own up
    to every event the recorder lost)."""
    global _ring, _dropped
    if n < 1:
        raise ValueError(f"timeline capacity must be >= 1, got {n}.")
    with _lock:
        _dropped += max(0, len(_ring) - n)
        _ring = deque(_ring, maxlen=n)


def clear() -> None:
    """Drop every recorded event and the dropped-event count."""
    global _dropped
    with _lock:
        _ring.clear()
        _dropped = 0


def _process_rank() -> int:
    """Chrome-trace pid: the jax process index when a backend is up (so a
    multi-rank merge groups rows per rank), else 0 — never initialises a
    backend just to export a trace."""
    try:
        import jax

        if jax.distributed.is_initialized():
            return jax.process_index()
    except Exception:
        pass
    return 0


def chrome_trace(
    extra_events: Optional[List[Dict[str, Any]]] = None,
    *,
    indent: Optional[int] = None,
) -> str:
    """The timeline as Chrome/Perfetto ``trace_event`` JSON (a string that
    ``chrome://tracing`` / ``ui.perfetto.dev`` load directly).

    Duration events export as phase ``"X"`` (ts/dur in microseconds),
    instants as phase ``"i"`` (thread scope). ``extra_events`` lets a
    cross-rank merge append rank-tagged event dicts (each may carry a
    ``"rank"`` used as the pid)."""
    pid = _process_rank()
    out = []
    merged = events()
    if extra_events:
        merged = merged + list(extra_events)
    for e in merged:
        entry: Dict[str, Any] = {
            "name": e["name"],
            "cat": e["kind"],
            "pid": e.get("rank", pid),
            "tid": e["tid"],
            "ts": round(e["ts"] * 1e6, 3),
            "args": e["labels"],
        }
        if e["dur"] > 0.0:
            entry["ph"] = "X"
            entry["dur"] = round(e["dur"] * 1e6, 3)
        else:
            entry["ph"] = "i"
            entry["s"] = "t"
        out.append(entry)
    doc = {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "torcheval_tpu.obs",
            "dropped_events": dropped(),
        },
    }
    return json.dumps(doc, indent=indent, default=str)

"""SLO objectives and burn alarms over the registry's rolling histograms.

ISSUE 16 tentpole (4). An operator declares latency objectives against
instruments that already exist::

    slo = obs.Slo(
        "submit_p99",
        instrument="serve.submit.latency",
        threshold_s=0.250,
        window_s=60.0,
        budget=0.01,          # <=1% of observations may exceed threshold
    )
    obs.register_slo(slo)
    obs.on_alarm(lambda payload: page_someone(payload))

Evaluation (:func:`evaluate_slos`, called explicitly or by every obs push
publisher tick) windows the *cumulative* log2 histograms by sampling: each
evaluation remembers ``(t, buckets, count)`` per series and diffs the
current sample against the newest sample older than ``window_s`` — the
bucket difference is exactly the observations recorded inside the window
(the same sum-exact bucket algebra the delta stream uses). From the
windowed buckets:

* ``bad`` = observations in buckets whose upper edge exceeds
  ``threshold_s`` (bucketed, so the effective threshold rounds DOWN to the
  containing bucket's lower edge — conservative: never under-counts);
* ``burn_rate`` = ``(bad / total) / budget`` — 1.0 means the error budget
  is being consumed exactly at the sustainable rate; recorded as
  ``slo.burn_rate{objective=}`` (max across the instrument's label sets);
* a series whose burn rate reaches 1.0 **breaches**: counted once per
  transition into ``slo.breach{objective=,tenant=}`` (tenant label only
  when the series carries one — the label-cardinality cap applies as
  usual) and fired once per transition through the alarm hooks. Breaches
  are edge-triggered: a stuck-bad series alarms once, not once per
  evaluation, and re-arms only after the window slides clean.

The alarm-hook registry (:func:`on_alarm` / :func:`remove_alarm`) is
thread-safe and deliberately generic — ``{"kind": "slo.breach", ...}``
today, ROADMAP item 4(c)'s ``drift.alarm`` tomorrow. A raising callback is
logged and dropped, never allowed to take down the publisher thread that
evaluated the objective.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from torcheval_tpu.obs import registry as _registry
from torcheval_tpu.obs.registry import Registry, bucket_upper_edge

__all__ = [
    "Slo",
    "register_slo",
    "unregister_slo",
    "registered_slos",
    "evaluate_slos",
    "on_alarm",
    "remove_alarm",
    "fire_alarm",
]


# ------------------------------------------------------------- alarm hooks
_alarm_lock = threading.Lock()
_alarm_cbs: List[Callable[[Dict[str, Any]], None]] = []


def on_alarm(cb: Callable[[Dict[str, Any]], None]) -> None:
    """Register ``cb(payload: dict)`` to run on every alarm (SLO breaches
    today; any subsystem may :func:`fire_alarm`). Idempotent per callback."""
    with _alarm_lock:
        if cb not in _alarm_cbs:
            _alarm_cbs.append(cb)


def remove_alarm(cb: Callable[[Dict[str, Any]], None]) -> None:
    """Unregister a callback (no-op if absent)."""
    with _alarm_lock:
        try:
            _alarm_cbs.remove(cb)
        except ValueError:
            pass


def fire_alarm(payload: Dict[str, Any]) -> None:
    """Invoke every registered alarm hook with ``payload``. Callbacks run
    on the CALLER's thread (for SLOs: the evaluating thread — keep them
    cheap); one raising callback is logged and skipped, the rest still
    fire."""
    with _alarm_lock:
        cbs = list(_alarm_cbs)
    for cb in cbs:
        try:
            cb(payload)
        except Exception:
            from torcheval_tpu.utils.telemetry import log_once

            log_once(
                f"obs.alarm.cb_error:{cb!r}",
                "obs alarm callback %r raised; alarm dropped for this "
                "callback (others still fire).",
                cb,
            )


class Slo:
    """One service-level objective over a histogram/span instrument.

    ``objective`` names the SLO (label value on its instruments);
    ``instrument`` is the registry histogram (or span path) it watches;
    observations above ``threshold_s`` inside the trailing ``window_s``
    consume the error ``budget`` (fraction, e.g. ``0.01`` = 1%).
    ``min_count`` suppresses evaluation until the window holds that many
    observations (default 1 — a single terrible request CAN breach, which
    is what a p99-style objective with a tiny budget means)."""

    def __init__(
        self,
        objective: str,
        *,
        instrument: str,
        threshold_s: float,
        window_s: float = 60.0,
        budget: float = 0.01,
        min_count: int = 1,
    ) -> None:
        if threshold_s <= 0.0:
            raise ValueError(
                f"threshold_s must be > 0, got {threshold_s!r}."
            )
        if window_s <= 0.0:
            raise ValueError(f"window_s must be > 0, got {window_s!r}.")
        if not 0.0 < budget <= 1.0:
            raise ValueError(
                f"budget must be in (0, 1], got {budget!r}."
            )
        self.objective = objective
        self.instrument = instrument
        self.threshold_s = float(threshold_s)
        self.window_s = float(window_s)
        self.budget = float(budget)
        self.min_count = int(min_count)
        # per-series sample history: label-key -> deque[(t, buckets, count)]
        self._samples: Dict[tuple, deque] = {}
        self._breached: Dict[tuple, bool] = {}
        self._lock = threading.Lock()

    # threshold -> first bucket index counted as "bad" (upper edge beyond
    # the threshold: conservative, the containing bucket counts entirely)
    def _first_bad_bucket(self) -> int:
        for i in range(_registry.HISTOGRAM_BUCKETS):
            if bucket_upper_edge(i) > self.threshold_s:
                return i
        return _registry.HISTOGRAM_BUCKETS - 1

    def evaluate(
        self,
        *,
        registry: Optional[Registry] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Evaluate every label set of ``instrument`` against the window.

        Returns ``{"objective":, "burn_rate": max-across-series,
        "breaches": [series-key, ...] (new transitions this call),
        "series": {key: {"burn_rate":, "bad":, "total":, "breached":}}}``,
        records ``slo.burn_rate`` / ``slo.breach`` into the registry, and
        fires the alarm hooks once per new breach."""
        reg = registry or _registry.default_registry
        t = time.monotonic() if now is None else now
        first_bad = self._first_bad_bucket()
        series: Dict[str, Dict[str, Any]] = {}
        new_breaches: List[str] = []
        max_burn = 0.0
        with self._lock:
            seen = set()
            for kind, name, lb, value in reg._items():
                if name != self.instrument:
                    continue
                if kind == "histo":
                    buckets, count = value[0], value[1]
                elif kind == "span":
                    count, buckets = value[0], value[3]
                else:
                    continue
                seen.add(lb)
                dq = self._samples.get(lb)
                if dq is None:
                    dq = self._samples[lb] = deque()
                dq.append((t, buckets, count))
                # baseline: the newest sample at or beyond the window edge
                while len(dq) >= 2 and dq[1][0] <= t - self.window_s:
                    dq.popleft()
                if dq[0][0] <= t - self.window_s:
                    base_b, base_c = dq[0][1], dq[0][2]
                else:
                    base_b, base_c = (), 0  # series younger than window
                total = count - base_c
                bad = sum(
                    buckets[i] - (base_b[i] if i < len(base_b) else 0)
                    for i in range(first_bad, len(buckets))
                )
                burn = 0.0
                if total >= self.min_count and total > 0:
                    burn = (bad / total) / self.budget
                max_burn = max(max_burn, burn)
                was = self._breached.get(lb, False)
                breached = burn >= 1.0
                self._breached[lb] = breached
                key = _registry.format_key(name, lb)
                series[key] = {
                    "burn_rate": burn,
                    "bad": bad,
                    "total": total,
                    "breached": breached,
                }
                if breached and not was:
                    new_breaches.append(key)
                    labels = {"objective": self.objective}
                    tenant = dict(lb).get("tenant")
                    if tenant is not None:
                        labels["tenant"] = tenant
                    reg.counter("slo.breach", **labels)
            # forget series the registry dropped (reset): re-arm them
            for lb in list(self._samples):
                if lb not in seen:
                    del self._samples[lb]
                    self._breached.pop(lb, None)
        reg.gauge("slo.burn_rate", max_burn, objective=self.objective)
        result = {
            "objective": self.objective,
            "burn_rate": max_burn,
            "breaches": new_breaches,
            "series": series,
        }
        for key in new_breaches:
            fire_alarm(
                {
                    "kind": "slo.breach",
                    "objective": self.objective,
                    "series": key,
                    "instrument": self.instrument,
                    "threshold_s": self.threshold_s,
                    "window_s": self.window_s,
                    "budget": self.budget,
                    "burn_rate": series[key]["burn_rate"],
                    "ts": time.time(),
                }
            )
        return result


# --------------------------------------------------------- module registry
_slo_lock = threading.Lock()
_slos: List[Slo] = []


def register_slo(slo: Slo) -> Slo:
    """Add ``slo`` to the process-wide set :func:`evaluate_slos` walks
    (the obs push publisher evaluates them every tick). Returns it."""
    with _slo_lock:
        if slo not in _slos:
            _slos.append(slo)
    return slo


def unregister_slo(slo: Slo) -> None:
    with _slo_lock:
        try:
            _slos.remove(slo)
        except ValueError:
            pass


def registered_slos() -> List[Slo]:
    with _slo_lock:
        return list(_slos)


def evaluate_slos(
    *, registry: Optional[Registry] = None, now: Optional[float] = None
) -> List[Dict[str, Any]]:
    """Evaluate every registered SLO; returns their result dicts. Safe to
    call with none registered (returns ``[]`` without touching the
    registry) — the publisher tick's steady-state cost."""
    out = []
    for slo in registered_slos():
        out.append(slo.evaluate(registry=registry, now=now))
    return out


def _reset_for_tests() -> None:
    """Drop registered SLOs and alarm hooks (test isolation)."""
    with _slo_lock:
        _slos.clear()
    with _alarm_lock:
        _alarm_cbs.clear()

"""Profiler annotation: attribute device and host time per metric / kernel.

Two annotation mechanisms compose, each applied where it is free:

* ``jax.named_scope(name)`` — prefixes the HLO op names of everything traced
  under it, so the **XLA profiler** attributes device time per metric and
  per kernel. Scope entry costs nothing at run time: it executes only while
  *tracing*, and traces are cached. Kernel entry points therefore bake their
  scope in unconditionally (``obs/recompile.py::watched_jit`` wraps the
  traced body), and jit-traced code never branches on the obs flag.
* ``jax.profiler.TraceAnnotation(name)`` + a registry span — host-side, per
  call. These DO cost per call, so they are gated on the module enable flag
  (one global read; the disabled path is ``if not _enabled: return fn(...)``
  and allocates nothing).

Inside someone else's trace (e.g. ``MetricCollection``'s fused step calling
member ``update``s with tracer state), host timing would measure *trace*
time once and never again — misleading — and ``TraceAnnotation`` would
annotate the tracing host thread, not execution. So instrumented methods
detect an active trace (``jax.core.trace_state_clean``) and fall back to
``named_scope`` alone, which is exactly the annotation that matters there.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from torcheval_tpu.obs import registry as _registry


def _trace_state_clean() -> bool:
    try:
        return jax.core.trace_state_clean()
    except Exception:  # private-ish API; absent => assume eager
        return True


def annotated_call(name: str, fn: Callable, args, kwargs):
    """Run ``fn(*args, **kwargs)`` under full annotation (enabled path)."""
    if not _trace_state_clean():
        # inside an outer trace: pure scope annotation only (trace-safe,
        # baked into the outer program's HLO names)
        with jax.named_scope(name):
            return fn(*args, **kwargs)
    with jax.profiler.TraceAnnotation(name):
        with jax.named_scope(name):
            with _registry.default_registry.span(name):
                return fn(*args, **kwargs)


def traced(name: str) -> Callable[[Callable], Callable]:
    """Decorator: annotate a host-side entry point (method or function).

    Disabled path: one module-global read, then straight through."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _registry._enabled:
                return fn(*args, **kwargs)
            return annotated_call(name, fn, args, kwargs)

        wrapper.__obs_wrapped__ = fn
        return wrapper

    return deco


# methods of the Metric protocol that get per-class span/scope annotation
_PROTOCOL_METHODS = ("update", "compute", "merge_state")


def _protocol_wrapper(method: str, fn: Callable) -> Callable:
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        if not _registry._enabled:
            return fn(self, *args, **kwargs)
        # name by the RUNTIME class: intermediate bases (e.g.
        # _BinaryCurveMetric) define the method, but attribution belongs to
        # the concrete metric the user constructed
        name = f"metric.{method}/{type(self).__name__}"
        return annotated_call(name, fn, (self,) + args, kwargs)

    wrapper.__obs_wrapped__ = fn
    return wrapper


def instrument_protocol(cls) -> None:
    """Wrap ``update`` / ``compute`` / ``merge_state`` defined BY ``cls``
    (not inherited — each definition is wrapped exactly once, where it
    lives) with per-metric annotation named by the runtime class, e.g.
    ``metric.update/BinaryAUROC``.

    Called from ``Metric.__init_subclass__`` so every concrete metric —
    including user-defined subclasses — is annotated with zero per-call
    cost while obs is disabled."""
    for method in _PROTOCOL_METHODS:
        fn = cls.__dict__.get(method)
        if fn is None or getattr(fn, "__obs_wrapped__", None) is not None:
            continue
        if isinstance(fn, (staticmethod, classmethod)):
            continue  # not the protocol shape; leave exotic overrides alone
        wrapped = _protocol_wrapper(method, fn)
        if getattr(fn, "__isabstractmethod__", False):
            wrapped.__isabstractmethod__ = True
        if wrapped.__doc__ is None:
            # inspect.getdoc's MRO docstring inheritance keys on the class
            # attribute being the original function object; a wrapper breaks
            # that, so materialise the inherited protocol doc explicitly —
            # on the original too, for tooling that inspect.unwrap()s first
            for base in cls.__mro__[1:]:
                base_fn = base.__dict__.get(method)
                doc = getattr(base_fn, "__doc__", None)
                if doc:
                    wrapped.__doc__ = doc
                    fn.__doc__ = doc
                    break
        setattr(cls, method, wrapped)

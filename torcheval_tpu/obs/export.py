"""Registry export: JSON snapshot and Prometheus text exposition.

Two consumers, two formats:

* **JSON** (:func:`to_json`) — one self-describing document per scrape, for
  the bench driver (``bench.py --obs``), log pipelines, and tests.
* **Prometheus text exposition** (:func:`prometheus_text`) — the de-facto
  fleet format (version 0.0.4): ``# TYPE`` headers, labelled sample lines,
  spans flattened to ``_count`` / ``_seconds_total`` / ``_seconds_max``
  (the summary-metric naming convention) plus a proper
  ``# TYPE ... histogram`` family (``torcheval_tpu_span_seconds``) carrying
  each span path's log2 latency buckets. Histogram instruments
  (``obs.histo``) expose as standard histogram families too:
  ``<name>_bucket{le=...}`` cumulative counts, ``<name>_sum``,
  ``<name>_count``. Only populated buckets are emitted (plus the mandatory
  ``+Inf`` line) — the fixed 64-bucket log2 scheme would otherwise bloat
  every scrape; arbitrary increasing ``le`` sets are valid exposition.
  Metric and label names are sanitised to the Prometheus charset
  (``[a-zA-Z_:][a-zA-Z0-9_:]*``) — span paths like
  ``collection.update/metric.update.BinaryAUROC`` become valid names with
  the path preserved in a ``path`` label instead; label values escape
  backslash, double quote and newline per the text-format rules.
"""

from __future__ import annotations

import json
import re
from typing import Optional

from torcheval_tpu.obs.registry import Registry, bucket_upper_edge, default_registry

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _label_pairs(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_LABEL_BAD.sub("_", k)}="{_escape(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def to_json(registry: Optional[Registry] = None, *, indent=None) -> str:
    """The registry snapshot as a JSON document string."""
    reg = registry if registry is not None else default_registry
    return json.dumps(reg.snapshot(), indent=indent, sort_keys=True)


def prometheus_text(registry: Optional[Registry] = None) -> str:
    """Prometheus text-format exposition of the registry.

    Counters get ``# TYPE <name> counter``; gauges ``gauge``; histograms a
    ``histogram`` family (cumulative ``_bucket{le=}`` lines over the
    populated log2 edges, ``_sum``, ``_count``); each span path expands into
    three summary-style lines carrying the path as a ``path`` label plus a
    shared ``torcheval_tpu_span_seconds`` histogram family::

        torcheval_tpu_span_count{path="collection.update"} 12
        torcheval_tpu_span_seconds_total{path="collection.update"} 0.0031
        torcheval_tpu_span_seconds_max{path="collection.update"} 0.0009
        torcheval_tpu_span_seconds_bucket{path="collection.update",le="0.000244141"} 9
    """
    reg = registry if registry is not None else default_registry
    # the text format requires every sample of one metric family to form one
    # contiguous group under its # TYPE header — buffer per family first
    # (span samples for different paths share the span family names, and a
    # histogram family's _bucket/_sum/_count lines all live under ONE header)
    families: dict = {}  # family name -> (kind, [sample lines])

    def emit(kind: str, family: str, sample: str, labels, value: float) -> None:
        fam = families.setdefault(family, (kind, []))
        fam[1].append(f"{sample}{_label_pairs(labels)} {value:g}")

    def emit_histogram(family: str, labels, buckets, count, total) -> None:
        cum = 0
        for i, c in enumerate(buckets):
            if not c:
                continue
            cum += c
            le = (("le", f"{bucket_upper_edge(i):g}"),)
            emit(
                "histogram",
                family,
                family + "_bucket",
                tuple(labels) + le,
                cum,
            )
        emit(
            "histogram",
            family,
            family + "_bucket",
            tuple(labels) + (("le", "+Inf"),),
            count,
        )
        emit("histogram", family, family + "_sum", labels, total)
        emit("histogram", family, family + "_count", labels, count)

    for kind, name, labels, value in reg._items():
        if kind == "counter":
            fam = _metric_name(name)
            emit("counter", fam, fam, labels, value)
        elif kind == "gauge":
            fam = _metric_name(name)
            emit("gauge", fam, fam, labels, value)
        elif kind == "histo":  # (buckets, count, sum)
            buckets, count, total = value
            emit_histogram(_metric_name(name), labels, buckets, count, total)
        else:  # span: (count, total_seconds, max_seconds, buckets)
            count, total, mx, buckets = value
            path_labels = (("path", name),) + tuple(labels)
            emit(
                "counter",
                "torcheval_tpu_span_count",
                "torcheval_tpu_span_count",
                path_labels,
                count,
            )
            emit(
                "counter",
                "torcheval_tpu_span_seconds_total",
                "torcheval_tpu_span_seconds_total",
                path_labels,
                total,
            )
            emit(
                "gauge",
                "torcheval_tpu_span_seconds_max",
                "torcheval_tpu_span_seconds_max",
                path_labels,
                mx,
            )
            emit_histogram(
                "torcheval_tpu_span_seconds", path_labels, buckets, count, total
            )
    lines = []
    for name, (kind, samples) in families.items():
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + ("\n" if lines else "")

"""Registry export: JSON snapshot and Prometheus text exposition.

Two consumers, two formats:

* **JSON** (:func:`to_json`) — one self-describing document per scrape, for
  the bench driver (``bench.py --obs``), log pipelines, and tests.
* **Prometheus text exposition** (:func:`prometheus_text`) — the de-facto
  fleet format (version 0.0.4): ``# TYPE`` headers, labelled sample lines,
  spans flattened to ``_count`` / ``_seconds_total`` / ``_seconds_max``
  (the summary-metric naming convention). Metric and label names are
  sanitised to the Prometheus charset (``[a-zA-Z_:][a-zA-Z0-9_:]*``) —
  span paths like ``collection.update/metric.update.BinaryAUROC`` become
  valid names with the path preserved in a ``path`` label instead.
"""

from __future__ import annotations

import json
import re
from typing import Optional

from torcheval_tpu.obs.registry import Registry, default_registry

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _label_pairs(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_LABEL_BAD.sub("_", k)}="{_escape(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def to_json(registry: Optional[Registry] = None, *, indent=None) -> str:
    """The registry snapshot as a JSON document string."""
    reg = registry if registry is not None else default_registry
    return json.dumps(reg.snapshot(), indent=indent, sort_keys=True)


def prometheus_text(registry: Optional[Registry] = None) -> str:
    """Prometheus text-format exposition of the registry.

    Counters get ``# TYPE <name> counter``; gauges ``gauge``; each span path
    expands into three lines carrying the path as a ``path`` label::

        torcheval_tpu_span_count{path="collection.update"} 12
        torcheval_tpu_span_seconds_total{path="collection.update"} 0.0031
        torcheval_tpu_span_seconds_max{path="collection.update"} 0.0009
    """
    reg = registry if registry is not None else default_registry
    # the text format requires every sample of one metric family to form one
    # contiguous group under its # TYPE header — buffer per family first
    # (span samples for different paths share the three span family names)
    families: dict = {}  # name -> (kind, [sample lines])

    def emit(kind: str, name: str, labels, value: float) -> None:
        fam = families.setdefault(name, (kind, []))
        fam[1].append(f"{name}{_label_pairs(labels)} {value:g}")

    for kind, name, labels, value in reg._items():
        if kind == "counter":
            emit("counter", _metric_name(name), labels, value)
        elif kind == "gauge":
            emit("gauge", _metric_name(name), labels, value)
        else:  # span: (count, total_seconds, max_seconds)
            count, total, mx = value
            path_labels = (("path", name),) + tuple(labels)
            emit("counter", "torcheval_tpu_span_count", path_labels, count)
            emit(
                "counter",
                "torcheval_tpu_span_seconds_total",
                path_labels,
                total,
            )
            emit("gauge", "torcheval_tpu_span_seconds_max", path_labels, mx)
    lines = []
    for name, (kind, samples) in families.items():
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + ("\n" if lines else "")

"""Per-program XLA cost attribution: what each compiled entry point costs
on device (ISSUE 7 tentpole leg 2).

The BENCH floor rows express a leg's host time as dispatch-equivalents, but
nothing in the registry said what each dispatched PROGRAM costs on device.
This module closes that gap: on every ``watched_jit`` compile (the window
step, the fold dispatchers, every ops kernel), :func:`capture` pulls
``cost_analysis()`` off the freshly lowered computation — and
``memory_analysis()`` off its compiled executable — and publishes per-entry
gauges:

* ``obs.cost.flops{entry=}`` — XLA's exact FLOP count for the program
  (multiplies and adds counted separately, the ``tools/flops.py`` unit);
* ``obs.cost.bytes_accessed{entry=}`` — total bytes the program reads and
  writes per execution (the roofline numerator);
* ``obs.cost.hbm_bytes{entry=}`` — resident device memory of one execution:
  argument + output + temp + alias buffer bytes from
  ``CompiledMemoryStats``.

Gauges are last-write-wins per entry label: an entry that recompiles for a
new batch signature reports its NEWEST program's cost (the one the loop is
actually running), while ``obs.cost.captures{entry=}`` counts how many
compiles were attributed. Dispatch-equivalents (BENCH floor rows) finally
sit next to what each program actually costs on device.

Cost model: :func:`capture` runs only (a) while obs is enabled AND (b) at a
call that actually traced — never on the jit cache-hit path, never while
disabled. It re-lowers the entry point to get at the analysis objects
(``jitted.lower(...)``; the analysis-side ``compile()`` may duplicate the
XLA compile the dispatch just paid — accepted: compiles are rare by
construction, milliseconds at minimum, and attribution is opt-in via
``obs.enable()``). The re-lowering re-runs the traced Python body, so the
recompile watchdog suppresses its bookkeeping under :func:`capturing` —
trace counts and storm warnings see only REAL compiles.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict

from torcheval_tpu.obs import registry as _registry

_local = threading.local()


def capturing() -> bool:
    """True while this thread is inside a cost-capture re-lowering — the
    recompile watchdog's probe checks this to keep the analysis pass out of
    its trace counts and storm detection."""
    return getattr(_local, "active", False)


def _sum_property(analysis: Any, key: str) -> float:
    """Total ``key`` across an XLA cost-analysis result, which is a dict of
    properties on recent jaxlibs and a list of per-computation dicts on
    older ones (the ``tools/flops.py`` compatibility rule)."""
    if not analysis:
        return 0.0
    if isinstance(analysis, (list, tuple)):
        return float(sum(c.get(key, 0.0) for c in analysis))
    return float(analysis.get(key, 0.0))


def _memory_bytes(stats: Any) -> float:
    """One execution's resident device bytes from ``CompiledMemoryStats``."""
    if stats is None:
        return 0.0
    total = 0.0
    for attr in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
    ):
        total += float(getattr(stats, attr, 0) or 0)
    return total


def capture(entry: str, jitted: Any, args: tuple, kwargs: Dict[str, Any]) -> None:
    """Attribute the program ``jitted`` just compiled for ``(args, kwargs)``
    to per-entry cost gauges. Called by ``watched_jit`` after a dispatch
    whose probe detected a trace; a failure here must never break the
    dispatch path — it downgrades to a ``obs.cost.capture_errors`` count."""
    if not _registry._enabled:
        return
    reg = _registry.default_registry
    t0 = time.perf_counter()
    _local.active = True
    try:
        lowered = jitted.lower(*args, **kwargs)
        analysis = lowered.cost_analysis()
        reg.gauge(
            "obs.cost.flops", _sum_property(analysis, "flops"), entry=entry
        )
        reg.gauge(
            "obs.cost.bytes_accessed",
            _sum_property(analysis, "bytes accessed"),
            entry=entry,
        )
        try:
            stats = lowered.compile().memory_analysis()
            reg.gauge(
                "obs.cost.hbm_bytes", _memory_bytes(stats), entry=entry
            )
        except Exception:
            # backends without memory stats: flops/bytes gauges stand alone
            pass
        reg.counter("obs.cost.captures", entry=entry)
    except Exception:
        reg.counter("obs.cost.capture_errors", entry=entry)
    finally:
        _local.active = False
        # observe_span also lands the timeline event via the span sink
        reg.observe_span(
            "obs.cost.capture", time.perf_counter() - t0, entry=entry
        )

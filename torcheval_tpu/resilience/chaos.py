"""Env-gated fault injection for the sync path and the serve queue (test-only).

A preemption on a real TPU slice looks, from the surviving processes' point
of view, like one rank silently vanishing (or stalling) between two
collective rounds — the healthy ranks then block forever inside the next
collective. A misbehaving eval *client* looks different: a corrupted batch
(wrong shape, NaN payload) entering a serving daemon's queue. Both fault
families inject here, armed through the environment before launch:

``TORCHEVAL_TPU_CHAOS``
    ``"1"`` arms the hooks; anything else (or unset) leaves them disabled.
    Disabled cost is one cached-config check per hook call — host code on
    paths that are about to block on the network or a queue, so it is free.
``TORCHEVAL_TPU_CHAOS_ACTION``
    Which fault. **Sync-funnel actions** (fire in ``on_sync_round``, at the
    ``toolkit._allgather_stacked`` choke point):

    * ``"kill"`` (default) — ``os._exit(TORCHEVAL_TPU_CHAOS_EXIT_CODE)``,
      modelling a hard preemption: no Python cleanup, no atexit, no goodbye
      to the coordinator.
    * ``"delay"`` — sleep ``TORCHEVAL_TPU_CHAOS_DELAY_S`` seconds before
      entering the round, modelling a straggler.

    **Ingestion actions** (fire in ``on_ingest``, at the serve queue
    boundary — the exact point a real client's bad batch would enter):

    * ``"poison"`` — corrupt the chosen batch's payload before it is
      queued: ``TORCHEVAL_TPU_CHAOS_POISON="nan"`` (default) replaces the
      first float array with all-NaN; ``"shape"`` drops the first array's
      last row, so the batch arrives with mismatched leading dims.
    * ``"ingest_delay"`` — sleep ``TORCHEVAL_TPU_CHAOS_DELAY_S`` before
      queuing the chosen batch, modelling a stalled producer (the fault
      the serve watchdog's idle eviction exists for).
    * ``"load_spike"`` (alias ``"hot_tenant"``) — the elastic-fleet
      driver (ISSUE 19): from the chosen step ON, EVERY admitted batch
      of the chosen tenant pays ``TORCHEVAL_TPU_CHAOS_DELAY_S`` of
      synthetic service time before queuing. Unlike every other
      ingestion action this fires REPEATEDLY, never consumes the
      one-shot budget, and never corrupts the payload — metric results
      stay bit-identical to a fault-free oracle while the host's
      ``serve.submit.latency`` histogram and submit EWMA (and therefore
      its ``load_report`` and the router's placement weight) read
      deterministically hot, which is exactly what the rebalance and
      hot-tenant-split paths need to trigger in tests and drills. Set
      ``TORCHEVAL_TPU_CHAOS_DELAY_S`` explicitly — the 30 s default
      models a straggler, not a cadence multiplier.

    **Host actions** (fire in ``on_host_request``, at the eval wire
    server's request dispatch — the surfaces a whole-host loss presents
    to remote clients; ISSUE 10):

    * ``"host_kill"`` — ``os._exit`` BEFORE processing the chosen
      request: the host vanishes mid-window, the un-acked batch was
      never applied, clients see dead connections from then on.
    * ``"host_partition"`` — from the chosen request on, the server
      reads requests and never answers (nor processes them): TCP is up,
      the service is gone — clients must discover it by deadline, not by
      connection error.
    * ``"ack_drop"`` — process the chosen request fully, then
      ``os._exit`` BEFORE the ack leaves: the exactly-once hard case —
      the client cannot know whether its batch landed, must resend, and
      only server-side sequence dedup (or the batch dying with the
      host's un-checkpointed state) keeps the metric exactly-once.

    Host actions select their request with ``TORCHEVAL_TPU_CHAOS_TENANT``
    and ``TORCHEVAL_TPU_CHAOS_STEP`` (the 1-based index among *submit*
    requests for the matching tenant, counted process-wide at the
    server), fire once per process, and ignore
    ``TORCHEVAL_TPU_CHAOS_RANK`` (the drill arms each host process with
    its own environment).

    **Ack actions** (fire in ``on_host_ack``, at the eval wire server's
    *deferred* ack-writer path — the asynchronous acks of the pipelined
    wire, ISSUE 18; neither kills the process):

    * ``"ack_delay"`` — hold the chosen submit's ack for
      ``TORCHEVAL_TPU_CHAOS_DELAY_S`` seconds before writing it: the
      batch is long applied, the producer's in-flight window stays
      occupied — a slow ack must stall only the window, never corrupt
      the watermark.
    * ``"ack_reorder"`` — write the chosen submit's ack AFTER the next
      ack on the same connection: acks arrive out of seq order, which
      the client's order-independent ack matching and monotonic
      durable-watermark fold must absorb bit-identically.

    Ack actions select their ack with ``TORCHEVAL_TPU_CHAOS_TENANT`` and
    ``TORCHEVAL_TPU_CHAOS_STEP`` (the 1-based index among *submit* acks
    for the matching tenant, counted process-wide), exactly like host
    actions, and fire once per process.

    **Router actions** (fire in ``on_router_op``, at the router's
    control-plane funnel — ISSUE 20):

    * ``"router_kill"`` — ``os._exit`` at the chosen router operation:
      the control plane vanishes mid-stream with its routing table,
      split topology, and replay buffers. Recovery is the journal's job
      (``EvalRouter(journal_dir=)``); the drill restarts the router in a
      fresh process and reconciles against the still-live hosts.

    Router actions select their moment with ``TORCHEVAL_TPU_CHAOS_TENANT``
    (``"*"`` = any tenant), ``TORCHEVAL_TPU_CHAOS_STEP`` (the 1-based
    index among matching hook calls, counted process-wide) and the
    optional ``TORCHEVAL_TPU_CHAOS_POINT`` (``"submit"`` /
    ``"migrate_exported"`` / … — ``"*"`` = any point, the default;
    ``"migrate_exported"`` is the nastiest: the tenant's wire state is
    exported but not yet adopted anywhere). Fire once per process.

    **Checkpoint actions** (fire in ``on_ckpt_saved``, immediately after
    ``resilience.save`` publishes a generation — ISSUE 20):

    * ``"ckpt_corrupt"`` — flip one payload byte (``state.npz``) of the
      just-published checkpoint, modelling silent media corruption of
      the newest generation: the next ``restore_latest_valid`` /
      ``attach(resume="auto")`` must quarantine it and fall back to the
      previous valid generation. The manifest (and its checksum record)
      stays intact, so the corruption is caught by verification, not by
      a missing file.

    Checkpoint actions select their save with
    ``TORCHEVAL_TPU_CHAOS_TENANT`` (a substring of the checkpoint path —
    the daemon's per-tenant directory carries the sanitized tenant id;
    ``"*"`` = any save) and ``TORCHEVAL_TPU_CHAOS_STEP`` (the 1-based
    index among matching saves, counted process-wide). Fire once per
    process.
``TORCHEVAL_TPU_CHAOS_RANK``
    Global process index the fault targets. Required for sync-funnel
    actions (other ranks never act); optional for ingestion actions (when
    set, only that rank injects — a multi-process serve test usually arms
    different per-rank environments instead).
``TORCHEVAL_TPU_CHAOS_ROUND``
    1-based index of the explicit collective round (every
    ``toolkit._allgather_stacked`` call counts one round, process-wide) at
    which a sync-funnel fault fires. A ``sync_and_compute`` is two rounds,
    so round 3 is "entering the descriptor exchange of the second sync".
    Required for sync-funnel actions.
``TORCHEVAL_TPU_CHAOS_TENANT``
    Tenant id an ingestion fault targets (``"*"`` = any tenant). Required
    for ingestion actions.
``TORCHEVAL_TPU_CHAOS_STEP``
    1-based per-tenant batch index at which the ingestion fault fires
    (each tenant's submissions count separately). Required for ingestion
    actions. The fault fires ONCE per process — one corrupted batch, like
    one preemption.
``TORCHEVAL_TPU_CHAOS_POISON``
    ``"nan"`` (default) or ``"shape"`` — see ``"poison"`` above.
``TORCHEVAL_TPU_CHAOS_DELAY_S``
    Straggler/producer-stall sleep, seconds (default 30).
``TORCHEVAL_TPU_CHAOS_EXIT_CODE``
    Exit code for ``kill`` (default 43), so a launcher can tell an injected
    death from a genuine crash.

The hooks live at the two funnels the corresponding real faults pass
through — every explicit cross-process collective round
(``toolkit._allgather_stacked``) and every serve queue admission
(``serve.daemon._submit``) — so the injection points are the real fault
surfaces, not mocks: surviving ranks execute the genuine Gloo collective
and the genuine watchdog path, and a poisoned batch flows through the
genuine validation/quarantine machinery.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional, Tuple

import numpy as np

from torcheval_tpu.obs import registry as _obs_registry
from torcheval_tpu.obs import trace as _obs_trace

_logger = logging.getLogger(__name__)

_ENV_ARM = "TORCHEVAL_TPU_CHAOS"
_ENV_RANK = "TORCHEVAL_TPU_CHAOS_RANK"
_ENV_ROUND = "TORCHEVAL_TPU_CHAOS_ROUND"
_ENV_ACTION = "TORCHEVAL_TPU_CHAOS_ACTION"
_ENV_DELAY = "TORCHEVAL_TPU_CHAOS_DELAY_S"
_ENV_EXIT = "TORCHEVAL_TPU_CHAOS_EXIT_CODE"
_ENV_TENANT = "TORCHEVAL_TPU_CHAOS_TENANT"
_ENV_STEP = "TORCHEVAL_TPU_CHAOS_STEP"
_ENV_POISON = "TORCHEVAL_TPU_CHAOS_POISON"
_ENV_POINT = "TORCHEVAL_TPU_CHAOS_POINT"

_SYNC_ACTIONS = ("kill", "delay")
# load actions fire REPEATEDLY (every matching admitted batch), the rest
# of the ingest family one-shot; both share the ingest env contract
_LOAD_ACTIONS = ("load_spike", "hot_tenant")
_INGEST_ACTIONS = ("poison", "ingest_delay") + _LOAD_ACTIONS
_HOST_ACTIONS = ("host_kill", "host_partition", "ack_drop")
_ACK_ACTIONS = ("ack_delay", "ack_reorder")
_ROUTER_ACTIONS = ("router_kill",)
_CKPT_ACTIONS = ("ckpt_corrupt",)
_POISON_KINDS = ("nan", "shape")


class _ChaosConfig:
    __slots__ = (
        "rank",
        "round",
        "action",
        "delay_s",
        "exit_code",
        "tenant",
        "step",
        "poison",
        "point",
    )

    def __init__(
        self,
        action: str,
        *,
        rank: Optional[int] = None,
        rnd: Optional[int] = None,
        delay_s: float = 30.0,
        exit_code: int = 43,
        tenant: Optional[str] = None,
        step: Optional[int] = None,
        poison: str = "nan",
        point: str = "*",
    ):
        self.action = action
        self.rank = rank
        self.round = rnd
        self.delay_s = delay_s
        self.exit_code = exit_code
        self.tenant = tenant
        self.step = step
        self.poison = poison
        self.point = point


# resolved lazily on first hook; False = disarmed, None = not yet resolved
_config: Optional[object] = None
_rounds_seen = 0
_ingest_fired = False
_load_logged = False  # load_spike: trace/log once, fire every batch
_host_fired = False
_host_submits_seen: dict = {}  # tenant_id -> submit requests observed
_ack_fired = False
_acks_seen: dict = {}  # tenant_id -> submit acks observed
_router_fired = False
_router_ops_seen = 0  # matching router-op hook calls observed
_ckpt_fired = False
_ckpt_saves_seen = 0  # matching checkpoint publishes observed
_lock = threading.Lock()


def _resolve() -> object:
    """Parse the environment once. A malformed configuration disarms with a
    warning rather than raising — chaos must never be able to break a
    production job that merely inherited a stale variable."""
    global _config
    if os.environ.get(_ENV_ARM) != "1":
        _config = False
        return _config
    try:
        action = os.environ.get(_ENV_ACTION, "kill")
        delay_s = float(os.environ.get(_ENV_DELAY, "30"))
        exit_code = int(os.environ.get(_ENV_EXIT, "43"))
        if action in _SYNC_ACTIONS:
            _config = _ChaosConfig(
                action,
                rank=int(os.environ[_ENV_RANK]),
                rnd=int(os.environ[_ENV_ROUND]),
                delay_s=delay_s,
                exit_code=exit_code,
            )
        elif action in _INGEST_ACTIONS:
            poison = os.environ.get(_ENV_POISON, "nan")
            if poison not in _POISON_KINDS:
                raise ValueError(f"unknown poison kind {poison!r}")
            rank_env = os.environ.get(_ENV_RANK)
            _config = _ChaosConfig(
                action,
                rank=int(rank_env) if rank_env is not None else None,
                delay_s=delay_s,
                tenant=os.environ[_ENV_TENANT],
                step=int(os.environ[_ENV_STEP]),
                poison=poison,
            )
        elif action in _HOST_ACTIONS:
            _config = _ChaosConfig(
                action,
                exit_code=exit_code,
                tenant=os.environ[_ENV_TENANT],
                step=int(os.environ[_ENV_STEP]),
            )
        elif action in _ACK_ACTIONS:
            _config = _ChaosConfig(
                action,
                delay_s=delay_s,
                tenant=os.environ[_ENV_TENANT],
                step=int(os.environ[_ENV_STEP]),
            )
        elif action in _ROUTER_ACTIONS:
            _config = _ChaosConfig(
                action,
                exit_code=exit_code,
                tenant=os.environ[_ENV_TENANT],
                step=int(os.environ[_ENV_STEP]),
                point=os.environ.get(_ENV_POINT, "*"),
            )
        elif action in _CKPT_ACTIONS:
            _config = _ChaosConfig(
                action,
                tenant=os.environ[_ENV_TENANT],
                step=int(os.environ[_ENV_STEP]),
            )
        else:
            raise ValueError(f"unknown chaos action {action!r}")
    except (KeyError, ValueError) as e:
        _logger.warning("chaos hooks armed but misconfigured (%s); disarming.", e)
        _config = False
    return _config


def reset_for_tests() -> None:
    """Re-read the environment and restart the round/step bookkeeping
    (test hook)."""
    global _config, _rounds_seen, _ingest_fired, _host_fired, _ack_fired
    global _load_logged, _router_fired, _router_ops_seen
    global _ckpt_fired, _ckpt_saves_seen
    with _lock:
        _config = None
        _rounds_seen = 0
        _ingest_fired = False
        _load_logged = False
        _host_fired = False
        _host_submits_seen.clear()
        _ack_fired = False
        _acks_seen.clear()
        _router_fired = False
        _router_ops_seen = 0
        _ckpt_fired = False
        _ckpt_saves_seen = 0


def on_sync_round() -> None:
    """Called by ``toolkit._allgather_stacked`` before every explicit
    collective round. No-op unless armed for this process at this round."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _SYNC_ACTIONS:
        return
    global _rounds_seen
    with _lock:
        _rounds_seen += 1
        seen = _rounds_seen
    import jax

    if jax.process_index() != cfg.rank or seen != cfg.round:
        return
    if _obs_registry._enabled:
        # the injection is a flight-recorder moment: a per-rank trace (or
        # the pre-kill obs dump the fault tests write) shows exactly which
        # round the fault hit — a kill's event survives only if the rank's
        # snapshot was exported before os._exit, which is the delay/test
        # pattern; the delay action records and lives on
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            rank=cfg.rank,
            round=seen,
        )
    if cfg.action == "kill":
        _logger.warning(
            "chaos: killing rank %d at sync round %d (exit %d)",
            cfg.rank,
            seen,
            cfg.exit_code,
        )
        # a preemption does not run atexit handlers or close collectives
        os._exit(cfg.exit_code)
    _logger.warning(
        "chaos: delaying rank %d at sync round %d by %.1fs",
        cfg.rank,
        seen,
        cfg.delay_s,
    )
    time.sleep(cfg.delay_s)


def _poison_args(args: Tuple, kind: str) -> Tuple:
    """Corrupt one batch's payload the way a broken client would.

    ``"nan"``: the first float-dtype array argument is replaced with
    all-NaN of the same shape/dtype (a NaN-policy violation the daemon's
    ``nan_policy="reject"`` scan catches; under ``"propagate"`` it flows
    into that tenant's results and nobody else's). ``"shape"``: the first
    array argument loses its last leading-axis row, so the batch arrives
    with mismatched leading dims and update validation raises. If no
    argument qualifies, the batch passes through unchanged (and a warning
    says so — a chaos test that poisons nothing should fail loudly, not
    silently pass)."""
    out = list(args)
    if kind == "nan":
        for i, a in enumerate(out):
            arr = np.asarray(a) if hasattr(a, "__array__") else None
            if arr is not None and arr.dtype.kind == "f":
                out[i] = np.full_like(arr, np.nan)
                return tuple(out)
    else:  # shape
        for i, a in enumerate(out):
            arr = np.asarray(a) if hasattr(a, "__array__") else None
            if arr is not None and arr.ndim >= 1 and arr.shape[0] > 1:
                out[i] = arr[:-1]
                return tuple(out)
    _logger.warning(
        "chaos: poison (%s) found no eligible argument; batch unchanged.",
        kind,
    )
    return tuple(out)


def ingest_armed() -> bool:
    """True when an ingestion action is armed for this process — the serve
    daemon's cheap gate for its chaos slow path (when False, ``submit``
    never calls :func:`on_ingest` at all)."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    return cfg is not False and cfg.action in _INGEST_ACTIONS


def host_armed() -> bool:
    """True when a host action is armed for this process — the eval wire
    server's cheap gate (when False, request dispatch never calls
    :func:`on_host_request`)."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    return cfg is not False and cfg.action in _HOST_ACTIONS


def on_host_request(op: str, tenant_id: Optional[str]) -> Optional[str]:
    """Called by the eval wire server before dispatching each request.

    Counts *submit* requests per tenant (process-wide, under the lock so
    concurrent connections cannot double-count one step). At the armed
    tenant's armed step: ``host_kill`` exits HERE (request never
    processed); ``"partition"`` tells the server to go silent from this
    request on; ``"ack_drop"`` tells it to process the request and call
    :func:`host_die` before acking. Fires once per process."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _HOST_ACTIONS:
        return None
    global _host_fired
    if _host_fired or op != "submit" or tenant_id is None:
        return None
    with _lock:
        if _host_fired:
            return None
        seen = _host_submits_seen.get(tenant_id, 0) + 1
        _host_submits_seen[tenant_id] = seen
        if seen != cfg.step or cfg.tenant not in ("*", tenant_id):
            return None
        _host_fired = True
    if _obs_registry._enabled:
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            tenant=tenant_id,
            step=seen,
        )
    if cfg.action == "host_kill":
        host_die("host_kill")
    if cfg.action == "host_partition":
        _logger.warning(
            "chaos: host partitioned at tenant %r submit %d (TCP up, "
            "service silent).",
            tenant_id,
            seen,
        )
        return "partition"
    return "ack_drop"


def ack_armed() -> bool:
    """True when an ack action is armed for this process — the pipelined
    ack writer's cheap gate (when False, the deferred-ack path never
    calls :func:`on_host_ack` at all)."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    return cfg is not False and cfg.action in _ACK_ACTIONS


def on_host_ack(op: str, tenant_id: Optional[str]) -> Optional[str]:
    """Called by the eval wire server's deferred ack writer before each
    pipelined ack leaves. Counts *submit*/*submit_many* acks per tenant
    (process-wide, under the lock). At the armed tenant's armed step it
    returns the armed action (``"ack_delay"`` / ``"ack_reorder"``) for
    the writer to enact — the hook itself never sleeps or kills, so the
    batch's application is already committed either way. Fires once per
    process."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _ACK_ACTIONS:
        return None
    global _ack_fired
    if (
        _ack_fired
        or op not in ("submit", "submit_many")
        or tenant_id is None
    ):
        return None
    with _lock:
        if _ack_fired:
            return None
        seen = _acks_seen.get(tenant_id, 0) + 1
        _acks_seen[tenant_id] = seen
        if seen != cfg.step or cfg.tenant not in ("*", tenant_id):
            return None
        _ack_fired = True
    if _obs_registry._enabled:
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            tenant=tenant_id,
            step=seen,
        )
    _logger.warning(
        "chaos: %s at tenant %r submit ack %d", cfg.action, tenant_id, seen
    )
    return cfg.action


def ack_delay_s() -> float:
    """The armed ``ack_delay`` hold, seconds (the writer sleeps, not the
    hook — see :func:`on_host_ack`)."""
    cfg = _config
    return cfg.delay_s if isinstance(cfg, _ChaosConfig) else 30.0


def host_die(action: str) -> None:
    """The host-loss moment itself: no Python cleanup, no atexit, no
    flush of the daemon's state — exactly what a preempted VM leaves."""
    cfg = _config
    exit_code = cfg.exit_code if isinstance(cfg, _ChaosConfig) else 43
    _logger.warning(
        "chaos: killing host (%s, exit %d)", action, exit_code
    )
    os._exit(exit_code)


def router_armed() -> bool:
    """True when a router action is armed for this process — the
    router's cheap gate (when False, its control-plane paths never call
    :func:`on_router_op` at all)."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    return cfg is not False and cfg.action in _ROUTER_ACTIONS


def on_router_op(point: str, tenant_id: Optional[str]) -> None:
    """Called by the router at its control-plane moments (``"submit"``
    per fan-out decision, ``"migrate_exported"`` between a migration's
    export and its adopt, …). Counts matching calls process-wide under
    the lock; at the armed count, ``router_kill`` exits HERE — the
    routing table, split topology and replay buffers die unsaved, and
    only the journal (fsync'd before every table mutation committed)
    survives. Fires once per process."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _ROUTER_ACTIONS:
        return
    global _router_fired, _router_ops_seen
    if _router_fired:
        return
    if cfg.point not in ("*", point):
        return
    if cfg.tenant not in ("*", tenant_id):
        return
    with _lock:
        if _router_fired:
            return
        _router_ops_seen += 1
        if _router_ops_seen != cfg.step:
            return
        _router_fired = True
    if _obs_registry._enabled:
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            tenant=tenant_id,
            point=point,
            step=cfg.step,
        )
    _logger.warning(
        "chaos: killing router at %s op %d (tenant %r, exit %d)",
        point,
        cfg.step,
        tenant_id,
        cfg.exit_code,
    )
    os._exit(cfg.exit_code)


def ckpt_armed() -> bool:
    """True when a checkpoint action is armed for this process — the
    snapshot writer's cheap gate (when False, ``save`` never calls
    :func:`on_ckpt_saved` at all)."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    return cfg is not False and cfg.action in _CKPT_ACTIONS


def on_ckpt_saved(ckpt_path: str) -> None:
    """Called by ``resilience.save`` immediately after it publishes a
    generation. At the armed save (``TORCHEVAL_TPU_CHAOS_TENANT`` as a
    path substring, ``TORCHEVAL_TPU_CHAOS_STEP`` the 1-based matching
    count), flips one ``state.npz`` payload byte in place — the newest
    generation is now silently corrupt, exactly what
    ``restore_latest_valid`` / ``attach(resume="auto")`` must quarantine
    and fall back from. Fires once per process."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _CKPT_ACTIONS:
        return
    global _ckpt_fired, _ckpt_saves_seen
    if _ckpt_fired:
        return
    if cfg.tenant != "*" and cfg.tenant not in ckpt_path:
        return
    with _lock:
        if _ckpt_fired:
            return
        _ckpt_saves_seen += 1
        if _ckpt_saves_seen != cfg.step:
            return
        _ckpt_fired = True
    payload = os.path.join(ckpt_path, "state.npz")
    try:
        with open(payload, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                _logger.warning(
                    "chaos: ckpt_corrupt found an empty payload at %s; "
                    "nothing flipped.", payload,
                )
                return
            # inside the zip local-file header / first member: restore
            # fails verification (corrupt_payload / checksum_mismatch),
            # never "file missing" — the silent-bit-rot model
            offset = min(12, size - 1)
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ 0xFF]))
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        _logger.warning(
            "chaos: ckpt_corrupt could not touch %s (%s); the drill "
            "should fail loudly, not silently pass.", payload, e,
        )
        return
    if _obs_registry._enabled:
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            path=ckpt_path,
            step=cfg.step,
        )
    _logger.warning(
        "chaos: flipped one payload byte of %s (save %d).",
        ckpt_path,
        cfg.step,
    )


def on_ingest(tenant_id: str, step: int, args: Tuple) -> Tuple:
    """Called by the serve daemon at the queue boundary for a batch that
    PASSED admission (capacity and liveness checks) — a shed batch must
    never consume the one-shot fault. ``step`` is the 1-based index of the
    batch among the tenant's admitted batches, read under the daemon lock
    so concurrent producers cannot double-present one step. Returns the
    (possibly corrupted) args; may sleep first. No-op unless armed for an
    ingestion action matching this tenant and step. The fault fires once
    per process."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False or cfg.action not in _INGEST_ACTIONS:
        return args
    if cfg.action in _LOAD_ACTIONS:
        # load_spike/hot_tenant (ISSUE 19): repeated-fire — every
        # admitted batch of the armed tenant from the armed step ON pays
        # delay_s of synthetic service time. Never one-shot, never
        # corrupting: the elapsed submit (including this sleep) feeds
        # the daemon's submit EWMA and serve.submit.latency histogram,
        # so the host's load_report reads deterministically hot while
        # every metric result stays bit-identical to a fault-free run.
        global _load_logged
        if step < cfg.step or cfg.tenant not in ("*", tenant_id):
            return args
        if cfg.rank is not None:
            import jax

            if jax.process_index() != cfg.rank:
                return args
        with _lock:
            first = not _load_logged
            _load_logged = True
        if first:
            if _obs_registry._enabled:
                _obs_trace.instant(
                    "resilience.chaos",
                    kind="chaos",
                    action=cfg.action,
                    tenant=tenant_id,
                    step=step,
                )
            _logger.warning(
                "chaos: load spike on tenant %r from batch %d "
                "(+%.3fs per batch)",
                tenant_id,
                step,
                cfg.delay_s,
            )
        time.sleep(cfg.delay_s)
        return args
    global _ingest_fired
    if (
        _ingest_fired
        or step != cfg.step
        or cfg.tenant not in ("*", tenant_id)
    ):
        return args
    if cfg.rank is not None:
        import jax

        if jax.process_index() != cfg.rank:
            return args
    with _lock:
        if _ingest_fired:
            return args
        _ingest_fired = True
    if _obs_registry._enabled:
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            tenant=tenant_id,
            step=step,
        )
    if cfg.action == "ingest_delay":
        _logger.warning(
            "chaos: delaying ingestion of tenant %r batch %d by %.1fs",
            tenant_id,
            step,
            cfg.delay_s,
        )
        time.sleep(cfg.delay_s)
        return args
    _logger.warning(
        "chaos: poisoning tenant %r batch %d (%s)",
        tenant_id,
        step,
        cfg.poison,
    )
    return _poison_args(args, cfg.poison)

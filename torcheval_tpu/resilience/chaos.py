"""Env-gated fault injection for the explicit sync path (test-only).

A preemption on a real TPU slice looks, from the surviving processes' point
of view, like one rank silently vanishing (or stalling) between two
collective rounds — the healthy ranks then block forever inside the next
collective. The multiprocess fault-injection tests
(``tests/resilience/test_fault_injection.py``) reproduce exactly that by
arming this module through the environment before launching a world:

``TORCHEVAL_TPU_CHAOS``
    ``"1"`` arms the hooks; anything else (or unset) leaves them disabled.
    Disabled cost is one cached-config check per *collective round* — host
    code on a path that is about to block on the network, so it is free.
``TORCHEVAL_TPU_CHAOS_RANK``
    Global process index the fault targets; other ranks never act.
``TORCHEVAL_TPU_CHAOS_ROUND``
    1-based index of the explicit collective round (every
    ``toolkit._allgather_stacked`` call counts one round, process-wide) at
    which the fault fires. A ``sync_and_compute`` is two rounds, so round 3
    is "entering the descriptor exchange of the second sync".
``TORCHEVAL_TPU_CHAOS_ACTION``
    ``"kill"`` (default) — ``os._exit(TORCHEVAL_TPU_CHAOS_EXIT_CODE)``,
    modelling a hard preemption: no Python cleanup, no atexit, no goodbye
    to the coordinator. ``"delay"`` — sleep ``TORCHEVAL_TPU_CHAOS_DELAY_S``
    seconds before entering the round, modelling a straggler.
``TORCHEVAL_TPU_CHAOS_DELAY_S``
    Straggler sleep, seconds (default 30).
``TORCHEVAL_TPU_CHAOS_EXIT_CODE``
    Exit code for ``kill`` (default 43), so a launcher can tell an injected
    death from a genuine crash.

The hook lives at the one funnel every explicit cross-process collective
round already passes through (``toolkit._allgather_stacked``), so the
injection point is the real preemption surface, not a mock: the surviving
ranks execute the genuine Gloo collective and the genuine watchdog path.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from torcheval_tpu.obs import registry as _obs_registry
from torcheval_tpu.obs import trace as _obs_trace

_logger = logging.getLogger(__name__)

_ENV_ARM = "TORCHEVAL_TPU_CHAOS"
_ENV_RANK = "TORCHEVAL_TPU_CHAOS_RANK"
_ENV_ROUND = "TORCHEVAL_TPU_CHAOS_ROUND"
_ENV_ACTION = "TORCHEVAL_TPU_CHAOS_ACTION"
_ENV_DELAY = "TORCHEVAL_TPU_CHAOS_DELAY_S"
_ENV_EXIT = "TORCHEVAL_TPU_CHAOS_EXIT_CODE"


class _ChaosConfig:
    __slots__ = ("rank", "round", "action", "delay_s", "exit_code")

    def __init__(self, rank: int, rnd: int, action: str, delay_s: float, exit_code: int):
        self.rank = rank
        self.round = rnd
        self.action = action
        self.delay_s = delay_s
        self.exit_code = exit_code


# resolved lazily on first round; False = disarmed, None = not yet resolved
_config: Optional[object] = None
_rounds_seen = 0
_lock = threading.Lock()


def _resolve() -> object:
    """Parse the environment once. A malformed configuration disarms with a
    warning rather than raising — chaos must never be able to break a
    production job that merely inherited a stale variable."""
    global _config
    if os.environ.get(_ENV_ARM) != "1":
        _config = False
        return _config
    try:
        rank = int(os.environ[_ENV_RANK])
        rnd = int(os.environ[_ENV_ROUND])
        action = os.environ.get(_ENV_ACTION, "kill")
        if action not in ("kill", "delay"):
            raise ValueError(f"unknown chaos action {action!r}")
        delay_s = float(os.environ.get(_ENV_DELAY, "30"))
        exit_code = int(os.environ.get(_ENV_EXIT, "43"))
    except (KeyError, ValueError) as e:
        _logger.warning("chaos hooks armed but misconfigured (%s); disarming.", e)
        _config = False
        return _config
    _config = _ChaosConfig(rank, rnd, action, delay_s, exit_code)
    return _config


def reset_for_tests() -> None:
    """Re-read the environment and restart the round counter (test hook)."""
    global _config, _rounds_seen
    with _lock:
        _config = None
        _rounds_seen = 0


def on_sync_round() -> None:
    """Called by ``toolkit._allgather_stacked`` before every explicit
    collective round. No-op unless armed for this process at this round."""
    cfg = _config
    if cfg is None:
        cfg = _resolve()
    if cfg is False:
        return
    global _rounds_seen
    with _lock:
        _rounds_seen += 1
        seen = _rounds_seen
    import jax

    if jax.process_index() != cfg.rank or seen != cfg.round:
        return
    if _obs_registry._enabled:
        # the injection is a flight-recorder moment: a per-rank trace (or
        # the pre-kill obs dump the fault tests write) shows exactly which
        # round the fault hit — a kill's event survives only if the rank's
        # snapshot was exported before os._exit, which is the delay/test
        # pattern; the delay action records and lives on
        _obs_trace.instant(
            "resilience.chaos",
            kind="chaos",
            action=cfg.action,
            rank=cfg.rank,
            round=seen,
        )
    if cfg.action == "kill":
        _logger.warning(
            "chaos: killing rank %d at sync round %d (exit %d)",
            cfg.rank,
            seen,
            cfg.exit_code,
        )
        # a preemption does not run atexit handlers or close collectives
        os._exit(cfg.exit_code)
    _logger.warning(
        "chaos: delaying rank %d at sync round %d by %.1fs",
        cfg.rank,
        seen,
        cfg.delay_s,
    )
    time.sleep(cfg.delay_s)

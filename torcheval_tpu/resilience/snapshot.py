"""Atomic metric checkpoint/restore: the preemption-survival spine.

A multi-hour streaming eval on a preemptible slice loses every accumulated
state on the first preemption unless that state periodically reaches durable
storage. The reference library ships no failure handling at all (SURVEY
§5.3); this module gives every state holder in the stack — a ``Metric``, a
``MetricCollection``, a ``ShardedEvaluator``, or a plain ``{name: Metric}``
dict — one pair of entry points:

``save(obj, directory)``
    Folds any deferred pending chunks first (``Metric._fold_now`` — the
    checkpoint must capture the *logical* state, not the physical
    pending-list representation), snapshots every ``state_dict()`` tree, and
    writes ONE checkpoint directory ``ckpt-<step>/`` containing

    * ``state.npz`` — every array leaf, in (metric key, registered state
      order) enumeration order, exact bytes;
    * ``manifest.json`` — format version, step, the **schema digest**
      (``toolkit._schema_digest_row``'s ordered ``(key, class, state,
      reduction, config-extra)`` scheme — the same digest the sync wire
      validates), a SHA-256 content checksum of the payload, and per-state
      container metadata (list/deque/dict structure, deque ``maxlen``,
      dict keys).

    The write is **temp-then-rename** (torchsnapshot's atomic manifest
    design): everything lands in a hidden ``.tmp-*`` directory, is fsynced,
    and is published with a single ``os.replace`` — a crash at any earlier
    point leaves no ``ckpt-*`` entry, so a reader can never observe a
    partial checkpoint. ``keep_last=N`` rotates old checkpoints after a
    successful publish.

``restore(obj, path)``
    Validates the SHA-256 checksum and the schema digest *before* touching
    any metric state, and raises a structured :class:`CheckpointError`
    (``.reason`` in ``{"not_found", "corrupt_manifest", "corrupt_payload",
    "checksum_mismatch", "schema_mismatch"}``) instead of silently loading
    garbage. On success every metric's ``load_state_dict`` installs the
    restored tree (placed on the metric's current device/sharding), and a
    subsequent ``compute()`` is bit-identical to one taken at save time.

Multi-process note: checkpoints are **per-process** — each rank saves its
local replica into its own directory (state is process-local in the explicit
sync model, and replicated-identical in the SPMD model where any one
process's snapshot is the global truth). 64-bit state dtypes survive the npz
round trip exactly, but installing them through ``load_state_dict`` follows
JAX's ``jax_enable_x64`` setting like every other state write.

Observability: ``resilience.checkpoint.saves`` / ``.restores`` /
``.bytes`` (bytes written per save) in the obs registry.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Tuple
from zipfile import BadZipFile

import numpy as np

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs import trace as _obs_trace
from torcheval_tpu.utils.npz import npz_views

_logger = logging.getLogger(__name__)

_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"
_PAYLOAD = "state.npz"
_CKPT_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"
_CORRUPT_PREFIX = "corrupt-"

__all__ = [
    "CheckpointError",
    "save",
    "restore",
    "restore_latest_valid",
    "quarantine_checkpoint",
    "latest_checkpoint",
    "list_checkpoints",
    "read_extra",
    "discover_checkpoints",
]


class CheckpointError(RuntimeError):
    """Structured checkpoint failure.

    ``reason`` is machine-readable: ``"not_found"`` (no checkpoint at the
    path), ``"corrupt_manifest"`` (unparseable/incomplete manifest),
    ``"corrupt_payload"`` (payload unreadable or missing leaves),
    ``"checksum_mismatch"`` (payload bytes differ from the manifest's
    SHA-256 — bit rot or a torn copy), ``"schema_mismatch"`` (the
    checkpoint was taken from a different metric set/configuration than the
    restore target), ``"unsupported"`` (a state the format cannot carry).
    """

    def __init__(self, reason: str, message: str) -> None:
        super().__init__(f"[{reason}] {message}")
        self.reason = reason


# --------------------------------------------------------------- normalising
def _as_metrics(obj: Any) -> Dict[str, Any]:
    """Normalise every supported state holder to ``{key: Metric}`` — the
    same shape the sync toolkit's collection wire uses, so the schema digest
    is comparable across holder types. A bare ``Metric`` wraps under the
    fixed key ``"metric"`` (matching ``MetricCollection``'s single-metric
    spelling), so ``save(metric)`` → ``restore(fresh_metric)`` round-trips.
    """
    from torcheval_tpu.metrics.collection import MetricCollection
    from torcheval_tpu.metrics.metric import Metric

    if isinstance(obj, Metric):
        return {"metric": obj}
    if isinstance(obj, MetricCollection):
        return obj.metrics
    # ShardedEvaluator (avoid importing parallel here: it pulls mesh setup)
    metrics = getattr(obj, "metrics", None)
    if metrics is not None and all(
        isinstance(m, Metric) for m in dict(metrics).values()
    ):
        return dict(metrics)
    if isinstance(obj, dict) and obj and all(
        isinstance(m, Metric) for m in obj.values()
    ):
        return dict(obj)
    raise TypeError(
        "save/restore accepts a Metric, a MetricCollection, a "
        f"ShardedEvaluator, or a non-empty dict of Metrics; got {type(obj)!r}."
    )


def _schema_digest(metrics: Dict[str, Any]) -> List[int]:
    from torcheval_tpu.metrics.toolkit import _schema_digest_row

    return [int(v) for v in _schema_digest_row(metrics)]


# ------------------------------------------------------------- tree flatten
_JSON_KEY_TYPES = (str, int, float, bool, type(None))


def _flatten_states(
    metrics: Dict[str, Any],
) -> Tuple[Dict[str, np.ndarray], List[dict]]:
    """Flatten every metric's state tree into named npz leaves plus a
    manifest entry per state carrying the container structure."""
    arrays: Dict[str, np.ndarray] = {}
    entries: List[dict] = []
    n = 0

    def leaf(value) -> str:
        nonlocal n
        key = f"a{n}"
        n += 1
        arrays[key] = np.asarray(value)
        return key

    def _sharded_mesh_shape(value) -> Optional[dict]:
        """``{axis: size}`` of the mesh a genuinely *sharded* jax array
        lives on, else ``None`` (host arrays, single-device arrays, and
        mesh-replicated arrays all restore anywhere — only state that is
        actually split across a mesh axis pins the checkpoint to an
        equal axis, see :func:`restore`)."""
        sharding = getattr(value, "sharding", None)
        if sharding is None or getattr(
            sharding, "is_fully_replicated", True
        ):
            return None
        mesh = getattr(sharding, "mesh", None)
        shape = getattr(mesh, "shape", None)
        if not shape:
            return None
        return {str(k): int(v) for k, v in dict(shape).items()}

    for mkey, metric in metrics.items():
        sd = metric.state_dict()
        for name in metric._state_name_to_reduction:
            value = sd[name]
            entry: dict = {"metric": mkey, "state": name}
            mesh_shape = _sharded_mesh_shape(value)
            if mesh_shape is not None:
                entry["sharded_mesh"] = mesh_shape
            if isinstance(value, deque):
                entry["kind"] = "deque"
                entry["maxlen"] = value.maxlen
                entry["leaves"] = [leaf(v) for v in value]
            elif isinstance(value, list):
                entry["kind"] = "list"
                entry["leaves"] = [leaf(v) for v in value]
            elif isinstance(value, dict):
                bad = [k for k in value if not isinstance(k, _JSON_KEY_TYPES)]
                if bad:
                    raise CheckpointError(
                        "unsupported",
                        f"dict state {name!r} of metric {mkey!r} has "
                        f"non-JSON-serialisable keys {bad!r}; checkpointing "
                        "requires str/int/float/bool dict keys.",
                    )
                entry["kind"] = "dict"
                entry["keys"] = list(value.keys())
                entry["leaves"] = [leaf(v) for v in value.values()]
            else:
                entry["kind"] = "array"
                entry["leaves"] = [leaf(value)]
            entries.append(entry)
    return arrays, entries


def _rebuild_state(entry: dict, payload, default) -> Any:
    """Inverse of one :func:`_flatten_states` entry, using the restore
    target's registered ``default`` to re-impose container semantics the
    wire format does not carry (defaultdict factories)."""
    try:
        leaves = [payload[k] for k in entry["leaves"]]
    except KeyError as e:
        raise CheckpointError(
            "corrupt_payload",
            f"payload is missing leaf {e} for state "
            f"{entry['state']!r} of metric {entry['metric']!r}.",
        ) from None
    kind = entry["kind"]
    if kind == "array":
        return leaves[0]
    if kind == "list":
        return leaves
    if kind == "deque":
        return deque(leaves, maxlen=entry.get("maxlen"))
    if kind == "dict":
        out = dict(zip(entry["keys"], leaves))
        if isinstance(default, defaultdict) and default.default_factory:
            d = defaultdict(default.default_factory)
            d.update(out)
            return d
        if isinstance(default, dict):
            # mirror Metric.reset: plain-dict defaults get the reference's
            # missing-key-is-zero semantics after any wholesale state write
            from torcheval_tpu.metrics.metric import _zero_scalar

            d = defaultdict(_zero_scalar)
            d.update(out)
            return d
        return out
    raise CheckpointError(
        "corrupt_manifest", f"unknown state container kind {kind!r}."
    )


# --------------------------------------------------------------- dir layout
def _step_of(name: str) -> Optional[int]:
    if not name.startswith(_CKPT_PREFIX):
        return None
    try:
        return int(name[len(_CKPT_PREFIX):])
    except ValueError:
        return None


def list_checkpoints(directory: str) -> List[str]:
    """Published checkpoint paths under ``directory``, oldest first."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    steps = sorted(
        (s, n) for n in names if (s := _step_of(n)) is not None
    )
    return [os.path.join(directory, n) for _, n in steps]


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest published checkpoint path, or ``None``."""
    ckpts = list_checkpoints(directory)
    return ckpts[-1] if ckpts else None


def _manifest_readable(ckpt: str) -> bool:
    """``True`` when ``ckpt``'s manifest parses and carries every required
    field — the cheap validity probe (no payload scan) rotation and
    discovery use to avoid orphaning/hiding the last valid generation."""
    try:
        _read_manifest(ckpt)
    except CheckpointError:
        return False
    return True


def rotate_checkpoints(directory: str, keep_last: int) -> None:
    """Remove published checkpoints beyond the newest ``keep_last``.
    ``save(keep_last=)`` calls this after its durable publish; callers
    that must defer rotation past their own commit point (the serve
    daemon's abortable idle eviction) call it directly afterwards.

    The last *valid* generation is never a victim (ISSUE 20): when none
    of the retained newest ``keep_last`` dirs has a readable manifest —
    every retained generation is torn or bit-rotted — the newest valid
    older generation is spared, so rotation can never leave a directory
    with zero restorable checkpoints. Quarantined ``corrupt-*`` dirs are
    invisible here by construction (they no longer parse as ``ckpt-*``)
    and are therefore never rotated away either.
    """
    ckpts = list_checkpoints(directory)
    victims = ckpts[:-keep_last]
    if victims and not any(_manifest_readable(c) for c in ckpts[-keep_last:]):
        for c in reversed(victims):
            if _manifest_readable(c):
                victims = [v for v in victims if v != c]
                _logger.warning(
                    "checkpoint rotation spared %s: it is the newest "
                    "generation with a readable manifest (every retained "
                    "newer generation is corrupt).",
                    c,
                )
                break
    for old in victims:
        shutil.rmtree(old, ignore_errors=True)


def discover_checkpoints(root: str) -> Dict[str, str]:
    """Map each immediate subdirectory of ``root`` that holds published
    checkpoints to its newest one: ``{name: ckpt_path}``.

    Checkpoint-root discovery for cluster operators (ISSUE 10): serve
    hosts evict and flush tenants into ``<root>/<tenant_id>``, so after a
    host (or a whole router) is lost, this enumerates every recoverable
    tenant — and its resume point — from shared storage alone, with no
    word from any dead process; re-``attach`` each id with
    ``resume="require"`` to resurrect it. (The router's automatic
    migration path doesn't need the scan: it already knows its tenant
    ids and lets ``attach(resume="auto")`` resolve each directory.)
    Names are the subdirectory names (the daemon's filesystem-safe
    tenant ids). Subdirectories without a published ``ckpt-*`` (e.g.
    only ``.tmp-*`` left by a crash mid-save) are omitted.

    Hardened against bit rot (ISSUE 20 satellite): a generation whose
    ``manifest.json`` is unparseable or truncated is SKIPPED (counted
    into ``resilience.checkpoint.corrupt_skipped{reason=}``) and the
    next-older generation is offered instead — one tenant's torn
    manifest must never raise mid-discovery and hide every *other*
    tenant's recoverable checkpoints. A subdirectory with no readable
    generation at all is omitted like an empty one.
    """
    out: Dict[str, str] = {}
    try:
        names = os.listdir(root)
    except (FileNotFoundError, NotADirectoryError):
        return out
    for name in sorted(names):
        sub = os.path.join(root, name)
        if not os.path.isdir(sub):
            continue
        for ckpt in reversed(list_checkpoints(sub)):
            try:
                _read_manifest(ckpt)
            except CheckpointError as e:
                _logger.warning(
                    "checkpoint discovery skipping %s (%s); trying the "
                    "previous generation.",
                    ckpt,
                    e.reason,
                )
                _obs.counter(
                    "resilience.checkpoint.corrupt_skipped",
                    reason=e.reason,
                )
                continue
            out[name] = ckpt
            break
    return out


_TMP_GC_MIN_AGE_S = 3600.0  # mtime fallback when the writer pid is unknowable


_TMP_NAME = re.compile(
    re.escape(_TMP_PREFIX) + re.escape(_CKPT_PREFIX) + r"\d+-(\d+)$"
)


def _tmp_writer_pid(name: str) -> Optional[int]:
    """The pid a ``.tmp-ckpt-<step>-<pid>`` name embeds, or ``None``.

    The FULL shape must match — a lax ``rsplit("-")`` would read a pid out
    of any foreign ``.tmp-*`` name whose last segment happens to be
    numeric (``.tmp-upload-123``) and, if that unrelated pid is not
    running, the GC would delete a concurrent tool's fresh data instead of
    applying the mtime-age fallback."""
    m = _TMP_NAME.match(name)
    return int(m.group(1)) if m else None


def _gc_stale_tmps(directory: str) -> int:
    """Remove ``.tmp-*`` directories orphaned by a writer that crashed
    between write and rename (they otherwise accumulate forever).

    Called after every durable publish. A tmp dir is stale when its
    embedded writer pid is provably dead (``os.kill(pid, 0)`` raises
    ``ProcessLookupError``) — a *live* writer's in-progress tmp, whatever
    its age, is never touched (its pid answers the probe; so does a
    same-pid process after pid reuse, which errs on the safe side). When
    the pid cannot be parsed (foreign tooling, truncated name), fall back
    to mtime: only dirs older than an hour are reclaimed, so a
    concurrent-looking fresh tmp survives. Returns the number removed."""
    removed = 0
    now = time.time()
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return 0
    for name in names:
        if not name.startswith(_TMP_PREFIX):
            continue
        path = os.path.join(directory, name)
        pid = _tmp_writer_pid(name)
        if pid == os.getpid():
            continue  # our own in-flight write (save() is re-entrant-safe)
        if pid is not None:
            try:
                os.kill(pid, 0)
                continue  # writer (or a pid-reuse doppelganger) is alive
            except ProcessLookupError:
                pass  # provably dead: the crash this GC exists for
            except OSError:
                continue  # EPERM etc.: a live process we cannot signal
        else:
            try:
                if now - os.path.getmtime(path) < _TMP_GC_MIN_AGE_S:
                    continue
            except OSError:
                continue
        shutil.rmtree(path, ignore_errors=True)
        removed += 1
    if removed:
        _logger.warning(
            "checkpoint: reclaimed %d stale .tmp-* dir(s) under %s "
            "(left by a writer that crashed mid-save).",
            removed,
            directory,
        )
        _obs.counter("resilience.checkpoint.tmp_gc", float(removed))
    return removed


def _fsync_file(path: str) -> None:
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# --------------------------------------------------------------------- save
def save(
    obj: Any,
    directory: str,
    *,
    step: Optional[int] = None,
    keep_last: Optional[int] = None,
    extra: Optional[dict] = None,
) -> str:
    """Write one atomic checkpoint of ``obj`` under ``directory``.

    ``step`` defaults to one past the newest existing checkpoint. With
    ``keep_last=N``, older checkpoints beyond the newest ``N`` are removed
    after the new one is durably published (rotation can therefore never
    leave fewer than one complete checkpoint behind). ``extra`` is an
    optional JSON-serialisable dict stored in the manifest (readable back
    via :func:`read_extra`) — it rides the same temp-then-rename publish,
    so metadata like the serve wire's acked-sequence watermark is
    atomically consistent with the state it describes. It does not enter
    the schema digest: restore targets never need to know it. Returns the
    published checkpoint path.
    """
    if keep_last is not None and keep_last < 1:
        # validate BEFORE any side effect: rejecting the argument after the
        # checkpoint has published would hand the caller an error plus a
        # checkpoint it did not expect to exist
        raise ValueError(f"keep_last must be >= 1, got {keep_last}.")
    metrics = _as_metrics(obj)
    for m in metrics.values():
        # capture the logical state: deferred pending chunks fold first
        # (state_dict folds too — this makes the contract explicit and keeps
        # it even if a subclass overrides state_dict)
        m._fold_now()
    with _obs.span("resilience.checkpoint.save"):
        arrays, entries = _flatten_states(metrics)
        os.makedirs(directory, exist_ok=True)
        if step is None:
            existing = [
                s for n in os.listdir(directory)
                if (s := _step_of(n)) is not None
            ]
            step = (max(existing) + 1) if existing else 0
        final = os.path.join(directory, f"{_CKPT_PREFIX}{step:08d}")
        if os.path.exists(final):
            raise CheckpointError(
                "unsupported", f"checkpoint step {step} already exists at {final}."
            )
        tmp = os.path.join(
            directory, f"{_TMP_PREFIX}{_CKPT_PREFIX}{step:08d}-{os.getpid()}"
        )
        os.makedirs(tmp)
        try:
            payload_path = os.path.join(tmp, _PAYLOAD)
            # exact bytes, uncompressed: the checksum (not zlib) is the
            # integrity mechanism, and save sits on the eval hot path
            np.savez(payload_path, **arrays)
            digest = hashlib.sha256()
            with open(payload_path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    digest.update(chunk)
            manifest = {
                "format_version": _FORMAT_VERSION,
                "step": step,
                "schema_digest": _schema_digest(metrics),
                "payload": _PAYLOAD,
                "payload_sha256": digest.hexdigest(),
                "payload_bytes": os.path.getsize(payload_path),
                "metrics": {
                    k: type(m).__qualname__ for k, m in metrics.items()
                },
                "entries": entries,
            }
            if extra is not None:
                manifest["extra"] = extra
            manifest_path = os.path.join(tmp, _MANIFEST)
            with open(manifest_path, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            _fsync_file(payload_path)
            _fsync_dir(tmp)
            # the atomic publish: a crash anywhere above leaves only a
            # .tmp-* directory, which no reader ever considers
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _fsync_dir(directory)
    from torcheval_tpu.resilience import chaos as _chaos

    if _chaos.ckpt_armed():
        # test-only silent-bit-rot injection (ISSUE 20): flips one
        # payload byte of the generation just published, AFTER the
        # durable publish — the reader-side lineage fallback is what is
        # under test, never the writer
        _chaos.on_ckpt_saved(final)
    nbytes = manifest["payload_bytes"] + os.path.getsize(
        os.path.join(final, _MANIFEST)
    )
    _obs.counter("resilience.checkpoint.saves")
    _obs.counter("resilience.checkpoint.bytes", float(nbytes))
    # timeline instant AT the durable publish (the save span covers the
    # whole write; this marks the os.replace moment a restore can rely on)
    _obs_trace.instant(
        "resilience.checkpoint.published",
        kind="checkpoint",
        step=step,
        bytes=nbytes,
    )
    if keep_last is not None:
        rotate_checkpoints(directory, keep_last)
    # reclaim tmp dirs orphaned by a crashed writer — AFTER the durable
    # publish, so a directory that only ever sees failing saves is never
    # mutated by the failures themselves
    _gc_stale_tmps(directory)
    return final


# ------------------------------------------------------------------ restore
def _read_manifest(ckpt: str) -> dict:
    manifest_path = os.path.join(ckpt, _MANIFEST)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise CheckpointError(
            "not_found", f"no manifest at {manifest_path}."
        ) from None
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointError(
            "corrupt_manifest", f"unreadable manifest at {manifest_path}: {e}"
        ) from None
    for field in ("format_version", "schema_digest", "payload_sha256", "entries"):
        if field not in manifest:
            raise CheckpointError(
                "corrupt_manifest",
                f"manifest at {manifest_path} is missing {field!r}.",
            )
    if manifest["format_version"] != _FORMAT_VERSION:
        raise CheckpointError(
            "corrupt_manifest",
            f"unsupported checkpoint format_version "
            f"{manifest['format_version']} (this build reads {_FORMAT_VERSION}).",
        )
    return manifest


def _resolve_ckpt(path: str) -> str:
    """``path`` itself if it is a checkpoint directory, else the newest
    published ``ckpt-*`` under it. Raises ``not_found`` when neither."""
    if os.path.exists(os.path.join(path, _MANIFEST)):
        return path
    nested = latest_checkpoint(path)
    if nested is None:
        raise CheckpointError(
            "not_found", f"no checkpoint found under {path!r}."
        )
    return nested


def read_extra(path: str) -> dict:
    """The ``extra`` metadata dict :func:`save` stored in the manifest at
    ``path`` (a checkpoint directory, or a parent whose newest ``ckpt-*``
    is used); ``{}`` when none was stored. Validates the manifest shape
    (same :class:`CheckpointError` reasons as :func:`restore`) but not the
    payload checksum — reading a watermark must not cost a full payload
    scan."""
    manifest = _read_manifest(_resolve_ckpt(path))
    extra = manifest.get("extra", {})
    if not isinstance(extra, dict):
        raise CheckpointError(
            "corrupt_manifest",
            f"manifest 'extra' at {path!r} is {type(extra).__name__}, "
            "expected a dict.",
        )
    return extra


def _check_mesh_portability(entry: dict, metric, mkey: str) -> None:
    """Enforce the cross-host portability contract (ISSUE 10 satellite):
    replicated state restores anywhere; state that was *sharded* across a
    mesh axis at save time requires the restore target to place it on an
    equal mesh (axis names and sizes), because the global value would
    otherwise be silently re-laid-out across a topology the saver never
    validated — a different device count must be an explicit, structured
    failure, not a quiet resharding."""
    saved_mesh = entry.get("sharded_mesh")
    if saved_mesh is None:
        return
    device = getattr(metric, "_device", None)
    mesh = getattr(device, "mesh", None)
    shape = getattr(mesh, "shape", None)
    current = (
        {str(k): int(v) for k, v in dict(shape).items()} if shape else None
    )
    if current == dict(saved_mesh):
        return
    if current is None and getattr(metric, "_sliced_sync", False):
        # slice-axis-sharded state (ISSUE 17): the payload is the GLOBAL
        # value and the slice layout is mesh-independent (block-range
        # tiles of one logical leading axis), so an UNSHARDED target may
        # restore it replicated — e.g. a single-device debug host reading
        # an 8-shard production checkpoint. A sharded target still
        # requires the equal mesh above: re-tiling onto a topology the
        # saver never validated stays an explicit failure.
        return
    raise CheckpointError(
        "unsupported",
        f"state {entry['state']!r} of metric {mkey!r} was sharded "
        f"across mesh {dict(saved_mesh)!r} at save time but the "
        f"restore target's placement mesh is {current!r} — sharded "
        "state requires an equal mesh axis (replicated state restores "
        "anywhere; see docs/robustness.md, 'Checkpoint portability').",
    )


def _coalesce_restore_h2d(
    trees: Dict[str, Dict[str, Any]], metrics: Dict[str, Any]
) -> None:
    """Replace every host ndarray leaf destined for a plain single device
    with its device-placed twin, transferring ALL of a device's leaves in
    ONE ``jax.device_put`` call (state containers — lists, deques, dicts —
    are walked and updated in place). Metrics without a plain device
    (mesh-sharded placements) are left untouched."""
    import jax

    slots: list = []  # (container, key) aligned with ``leaves``
    leaves: list = []
    device = None
    for mkey, tree in trees.items():
        dev = getattr(metrics[mkey], "_plain_device", None)
        if dev is None:
            continue
        if device is None:
            device = dev
        elif device is not dev:
            return  # heterogeneous placements: keep the per-leaf path
        for sname, value in tree.items():
            if isinstance(value, np.ndarray):
                slots.append((tree, sname))
                leaves.append(value)
            elif isinstance(value, (list, deque)):
                for i, v in enumerate(value):
                    if isinstance(v, np.ndarray):
                        slots.append((value, i))
                        leaves.append(v)
            elif isinstance(value, dict):
                for k, v in value.items():
                    if isinstance(v, np.ndarray):
                        slots.append((value, k))
                        leaves.append(v)
    if not leaves:
        return
    try:
        placed = jax.device_put(leaves, device)
    except Exception:  # noqa: BLE001 - placement trouble surfaces later
        return  # load_state_dict's own placement reports the real error
    for (container, key), arr in zip(slots, placed):
        container[key] = arr


def restore(obj: Any, path: str) -> Any:
    """Restore ``obj``'s metric states from ``path`` — a checkpoint
    directory, or a parent directory whose newest ``ckpt-*`` is used.

    Validation order: manifest parse → payload checksum → schema digest →
    payload decode. Any failure raises :class:`CheckpointError` *before*
    any metric state is written, so a failed restore never leaves ``obj``
    half-loaded. Returns ``obj``.
    """
    metrics = _as_metrics(obj)
    ckpt = _resolve_ckpt(path)
    with _obs.span("resilience.checkpoint.restore"):
        manifest = _read_manifest(ckpt)
        payload_path = os.path.join(ckpt, manifest.get("payload", _PAYLOAD))
        digest = hashlib.sha256()
        try:
            with open(payload_path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    digest.update(chunk)
        except OSError as e:
            raise CheckpointError(
                "corrupt_payload", f"unreadable payload {payload_path}: {e}"
            ) from None
        if digest.hexdigest() != manifest["payload_sha256"]:
            raise CheckpointError(
                "checksum_mismatch",
                f"payload {payload_path} does not match its manifest "
                f"checksum (expected {manifest['payload_sha256']}, got "
                f"{digest.hexdigest()}); refusing to load a torn or "
                "bit-rotted checkpoint.",
            )
        if _schema_digest(metrics) != list(manifest["schema_digest"]):
            raise CheckpointError(
                "schema_mismatch",
                f"checkpoint at {ckpt} was taken from a different metric "
                "set than the restore target (metric keys, classes, state "
                "names, reductions and fold-relevant configuration — e.g. "
                "windowed metrics' window_size — must all match; saved "
                f"metrics: {manifest.get('metrics')}).",
            )
        try:
            # stream, don't materialize (ISSUE 11): the payload maps
            # read-only and every aligned uncompressed leaf decodes as a
            # zero-copy view over the mapped pages (utils/npz.py) — the
            # full host tree is never copied out of the archive. The
            # mmap object stays referenced until the loads below finish
            # (each view pins it via ndarray.base regardless).
            payload_mm = np.memmap(payload_path, dtype=np.uint8, mode="r")
            payload = npz_views(payload_mm)
            trees: Dict[str, Dict[str, Any]] = {k: {} for k in metrics}
            for entry in manifest["entries"]:
                mkey, sname = entry["metric"], entry["state"]
                if mkey not in metrics:
                    raise CheckpointError(
                        "schema_mismatch",
                        f"manifest names unknown metric {mkey!r}.",
                    )
                _check_mesh_portability(entry, metrics[mkey], mkey)
                default = metrics[mkey]._state_name_to_default.get(sname)
                value = _rebuild_state(entry, payload, default)
                if (
                    entry["kind"] == "array"
                    and hasattr(default, "shape")
                    and tuple(value.shape) != tuple(default.shape)
                ):
                    # sliced states (ISSUE 15): the LEADING dim is the
                    # dense slice capacity, which legitimately differs
                    # between a fresh member and a grown checkpoint —
                    # the member's load_state_dict re-derives capacity
                    # and the id table from the restored lanes. Trailing
                    # dims (the real per-slice schema) must still match.
                    resizable = sname in getattr(
                        metrics[mkey], "_lead_resizable_states", ()
                    )
                    if not (
                        resizable
                        and len(value.shape) == len(default.shape)
                        and tuple(value.shape[1:])
                        == tuple(default.shape[1:])
                    ):
                        # config drift the digest cannot see: two replicas
                        # of the same class/state/reduction schema whose
                        # constructor args size the state differently
                        # (e.g. macro accuracy's per-class counters under
                        # a different num_classes)
                        raise CheckpointError(
                            "schema_mismatch",
                            f"state {sname!r} of metric {mkey!r} has shape "
                            f"{tuple(value.shape)} in the checkpoint but "
                            f"{tuple(default.shape)} in the restore target "
                            "— same metric schema, drifted configuration "
                            "(e.g. num_classes/num_tasks)?",
                        )
                trees[mkey][sname] = value
        except (ValueError, OSError, KeyError, BadZipFile) as e:
            raise CheckpointError(
                "corrupt_payload", f"undecodable payload {payload_path}: {e}"
            ) from None
        # coalesced H2D (the ingest-pipeline treatment, ISSUE 11): every
        # plain-single-device metric's leaves ride ONE device_put straight
        # from the mapped file — a migration restore never pays
        # per-leaf transfer dispatches, and on backends where donation is
        # gated off the placed leaves install without any further copy.
        # Sharded placements keep their host views (the SPMD layout is
        # load_state_dict's job).
        _coalesce_restore_h2d(trees, metrics)
        for mkey, tree in trees.items():
            metrics[mkey].load_state_dict(tree)
        del payload, payload_mm
    _obs.counter("resilience.checkpoint.restores")
    _obs_trace.instant(
        "resilience.checkpoint.restored",
        kind="checkpoint",
        step=manifest.get("step", -1),
    )
    return obj


# --------------------------------------------------------- lineage fallback
# the CheckpointError reasons that mean THIS generation's bytes are bad
# (quarantine it and fall back to an older one) — as opposed to
# schema_mismatch/unsupported, which indict the restore TARGET's
# configuration and would fail identically against every generation
_CORRUPT_REASONS = frozenset(
    {"corrupt_manifest", "corrupt_payload", "checksum_mismatch"}
)


def quarantine_checkpoint(ckpt: str) -> Optional[str]:
    """Atomically rename a corrupt generation ``ckpt-<step>`` to
    ``corrupt-ckpt-<step>`` (ISSUE 20): the bytes are preserved for
    forensics — counted, never deleted — while the dir stops parsing as a
    published checkpoint, so every reader (``list_checkpoints``, rotation,
    the zombie-writer watermark pick) forgets it exists. ``corrupt-*``
    names are likewise invisible to the ``.tmp-*`` GC, so a quarantined
    generation outlives any amount of later save churn. Returns the new
    path, or ``None`` when the dir vanished underneath us (a concurrent
    reader already quarantined it — not an error)."""
    parent, name = os.path.split(os.path.normpath(ckpt))
    target = os.path.join(parent, _CORRUPT_PREFIX + name)
    suffix = 1
    while os.path.exists(target):
        suffix += 1
        target = os.path.join(parent, f"{_CORRUPT_PREFIX}{name}.{suffix}")
    try:
        os.rename(ckpt, target)
    except FileNotFoundError:
        return None
    _fsync_dir(parent)
    _logger.warning(
        "checkpoint: quarantined corrupt generation %s -> %s "
        "(preserved for forensics, excluded from every future restore).",
        ckpt,
        target,
    )
    _obs.counter("resilience.checkpoint.corrupt_quarantined")
    _obs_trace.instant(
        "resilience.checkpoint.quarantined", kind="checkpoint", path=target
    )
    return target


def restore_latest_valid(obj: Any, directory: str) -> str:
    """Restore ``obj`` from the newest *valid* generation under
    ``directory``, walking newest→oldest past corrupt ones (ISSUE 20).

    Each generation whose bytes fail validation (``corrupt_manifest`` /
    ``corrupt_payload`` / ``checksum_mismatch``) is quarantined via
    :func:`quarantine_checkpoint` and the walk continues — a bit-flipped
    newest checkpoint degrades the caller to the previous durable
    generation instead of failing the restore outright.
    ``schema_mismatch`` / ``unsupported`` raise immediately: they indict
    the restore target's configuration, not the checkpoint's bytes, and
    quarantining on them would destroy lineage a correctly-configured
    caller could still use. Raises ``CheckpointError("not_found")`` when
    no valid generation remains. Counts each successful restore that had
    to skip at least one corrupt generation into
    ``resilience.checkpoint.fallback_restores``. Returns the path of the
    generation actually restored."""
    skipped = 0
    while True:
        ckpt = latest_checkpoint(directory)
        if ckpt is None:
            raise CheckpointError(
                "not_found",
                f"no valid checkpoint generation remains under "
                f"{directory!r} ({skipped} corrupt generation(s) "
                "quarantined).",
            )
        try:
            restore(obj, ckpt)
        except CheckpointError as e:
            if e.reason not in _CORRUPT_REASONS:
                raise
            quarantine_checkpoint(ckpt)
            skipped += 1
            continue
        if skipped:
            _obs.counter("resilience.checkpoint.fallback_restores")
        return ckpt

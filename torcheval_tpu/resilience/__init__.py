"""``torcheval_tpu.resilience``: failure handling for the eval stack.

Three legs (ISSUE 5 tentpole) — the failure-semantics table lives in
``docs/robustness.md``:

* **Atomic checkpoint/restore** (``snapshot.py``) — ``save``/``restore``
  for any state holder (Metric / MetricCollection / ShardedEvaluator /
  metric dict): temp-then-rename publishes, SHA-256 content checksums, the
  sync wire's schema digest, ``keep_last`` rotation, and structured
  :class:`CheckpointError` rejection of corrupt or mismatched payloads.
* **Sync deadlines + graceful degradation** (``metrics/toolkit.py``) —
  every explicit sync API takes ``timeout_s=`` (a watchdog around the
  blocking collective, raising :class:`SyncTimeoutError` naming the round
  and lane) and ``on_failure="raise"|"local"`` (``"local"`` returns the
  local, unsynced result so a dead rank degrades the report instead of
  hanging the job); ``parallel.init_from_env`` retries coordinator
  connection with bounded exponential backoff.
* **Fault injection** (``chaos.py``) — env-gated test-only hooks that kill
  or delay a chosen rank at a chosen sync round, driving the 4-process
  recovery tests in ``tests/resilience/``.

Obs counters: ``resilience.checkpoint.{saves,restores,bytes}``,
``toolkit.sync.timeouts{policy=}``, ``bootstrap.retries``.
"""

from torcheval_tpu.resilience.snapshot import (
    CheckpointError,
    discover_checkpoints,
    latest_checkpoint,
    list_checkpoints,
    quarantine_checkpoint,
    read_extra,
    restore,
    restore_latest_valid,
    save,
)

__all__ = [
    "CheckpointError",
    "SyncError",
    "SyncRoundError",
    "SyncTimeoutError",
    "discover_checkpoints",
    "latest_checkpoint",
    "list_checkpoints",
    "quarantine_checkpoint",
    "read_extra",
    "restore",
    "restore_latest_valid",
    "save",
]

_TOOLKIT_REEXPORTS = ("SyncError", "SyncRoundError", "SyncTimeoutError")


def __getattr__(name: str):
    # lazy re-export (PEP 562): the sync failure types are DEFINED in
    # metrics/toolkit.py (next to the sync APIs that raise them) and only
    # surfaced here; importing toolkit eagerly would cycle, because toolkit
    # itself imports resilience.chaos for the fault-injection funnel.
    if name in _TOOLKIT_REEXPORTS:
        from torcheval_tpu.metrics import toolkit

        return getattr(toolkit, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

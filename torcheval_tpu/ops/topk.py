"""Streaming top-k selection engine: the L1 kernel behind the large-label
metrics (``_topk_multilabel_stats``, ``reciprocal_rank``'s ``k`` cutoff).

``jax.lax.top_k`` on XLA:TPU lowers to a full variadic sort of the label
axis — at L=10k that is a ~180-pass bitonic network over every row, which is
why BASELINE config 4 sat two orders of magnitude below every other bench
leg (VERDICT item 4). Top-k with k ≪ L does not need a sort: it is a
streaming reduction, the same tile-and-accumulate shape as online
softmax/selection in the flash-attention family (PAPERS.md) and this repo's
own ``ops/pallas_hist.py``. Three lowerings, auto-picked by size and
backend (:func:`_pick_method`):

* ``pallas`` — streaming Pallas TPU kernel (:func:`pallas_topk`): the label
  axis is tiled, and each row block's k running maxima (values AND original
  indices) stay resident in VMEM across every label tile — one pass over L,
  no materialised sort. Per tile the kernel runs k unrolled
  max / min-index selection steps over the (carry ∪ tile) union, which
  reproduces ``lax.top_k``'s exact ordering (values descending, ties by
  lowest index) by construction: ties resolve through a ``min`` over
  ORIGINAL indices, never over lane positions. Carried under GSPMD by a
  ``custom_partitioning`` rule (:func:`sharded_pallas_topk`) — top-k is
  row-independent, so each shard runs the kernel on its local rows and the
  outputs inherit the operand's row sharding; a batch-sharded operand is
  never re-gathered. ``interpret=True`` runs the same kernel on any backend
  (the CPU suite exercises it; forced ``method="pallas"`` off-TPU
  auto-interprets, mirroring ``ops/confusion.py``).
* ``prune`` — XLA threshold-prune fallback (:func:`prune_topk`) for
  non-Pallas backends: estimate the per-row kth value from the 128-wide
  group maxima (the kth-largest group max is a PROVABLE lower bound on the
  kth value — the k best groups each contribute one element above it), mask
  the row against it, take each group's top-``s`` survivors
  (s = min(k, 8)), and finish with one exact ``lax.top_k`` over the ~G·s
  candidates. A correctness valve re-runs exact full-width ``lax.top_k``
  (one batch-level ``lax.cond``, so the fast path never pays for it) when
  any group's survivor count exceeds ``s`` — the only case a candidate in
  the true top-k could have been dropped. Adversarial all-equal rows (every
  element ties the threshold) trip the valve by construction. NOT
  auto-picked on CPU — a measured dead end there (numbers in
  ``_pick_method`` and docs/performance.md §Streaming top-k): XLA:CPU's
  2-D top_k is already a fast partial-selection custom call. Forced
  ``method="prune"`` keeps it exercised and available for backends whose
  top_k lowering is a full sort.
* ``dense`` — ``jax.lax.top_k`` itself, which wins for small L (the sort is
  cheap and fusion-friendly) and is the only path with defined NaN
  behaviour.
* ``sharded_label`` — the block-distributed LABEL-axis engine
  (:func:`sharded_label_topk`, ISSUE 14) for vocabularies that do not fit
  one device (L ~ 10⁶–10⁸): per-shard streaming selection with global
  indices, ONE O(k·shards) candidate all-gather, and an exact 2-key merge
  reproducing ``lax.top_k``'s tie order bit-exactly — the label axis is
  never replicated. Auto-engaged when the committed operand's label axis is
  sharded; composes with the other methods (they run per shard) and with
  batch sharding on multi-axis meshes. Cost model and diagram:
  docs/performance.md §Label-sharded top-k.

Selection thresholds (measured rationale in docs/performance.md §Streaming
top-k): ``_DENSE_L_MAX = 1024`` — below this the full sort beats both
streaming paths' fixed overheads; config 4 (L=10k) sits ~10× past it.
The Pallas carry holds one 128-lane tile, so ``k <= 128``; larger k falls
back to prune/dense.

Exactness contract: all three paths return bit-identical ``(values,
indices)`` to ``jax.lax.top_k`` for NaN-free inputs, including ±inf scores
and arbitrary ties. NaN scores are DEFINED only on the dense path (XLA's
total order); the streaming paths' comparisons ignore NaN lanes, so callers
with possibly-NaN scores must force ``method="dense"`` (the metric layer's
scores are model outputs, NaN-free by the same contract the reference
assumes).

Observability: every engine call increments ``ops.topk.calls{path=}`` while
obs is enabled. The counter fires when the Python entry runs — per call for
eager callers, once per compiled signature for jitted callers — and records
the TRACE-TIME pick. One caveat it shares with ``class_counts``'s auto
route: the auto "pallas" pick is platform-dispatched at lowering, so a
CPU-committed operand on a TPU host executes the dense XLA branch while the
counter still reads ``path=pallas`` — a placement problem the counter
cannot see (check ``x.sharding`` when a "pallas" row is slower than
expected).
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map
from jax.sharding import Mesh, PartitionSpec as _P

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs.recompile import watched_jit

# older shard_map's replication checker false-positives on per-shard kernels
# (see ops/dist_curves.py) — disable it where the knob exists
_SHARD_MAP_KWARGS = (
    {"check_rep": False}
    if "check_rep" in inspect.signature(_shard_map).parameters
    else {}
)

_METHODS = ("auto", "dense", "prune", "pallas", "sharded_label")
# local-shard lowerings the label-sharded engine accepts for its per-shard
# selection (sharded_label composes the OTHER methods, it is not one itself)
_LOCAL_METHODS = ("auto", "dense", "prune", "pallas")

# Below this label-axis width the full-sort lax.top_k wins: the streaming
# paths' fixed costs (tile padding, k selection passes / two-stage sort
# plumbing) exceed a short sort. Config 4's L=10k is ~10x past it; see the
# valve-math comment in bench.py::config4_topk_multilabel for how this
# threshold composes with the deferral budget there.
_DENSE_L_MAX = 1024
# The Pallas carry is one (rows, 128) lane tile; k beyond it falls back.
_PALLAS_MAX_K = 128
# Pallas tiling: label lanes streamed per grid step / rows per block. The
# per-step working set (x block + 2 carry blocks + selection temporaries)
# stays well under VMEM at (128, 512).
_TILE_L = 512
_BLOCK_ROWS = 128
_CARRY_LANES = 128
# Carry-placeholder index base: above every real label index (L < 2**30 for
# any realistic label count) and below the removed-entry sentinel, and made
# unique per lane by adding the lane iota — the min-index tie-break then
# only ever selects a placeholder when no real candidate remains.
_PLACEHOLDER_BASE = 1 << 30
_IDX_SENTINEL = jnp.iinfo(jnp.int32).max
# Prune grouping: group width along the label axis and the per-group
# survivor budget (candidates per group after thresholding).
_PRUNE_GROUP_W = 128
_PRUNE_SURVIVOR_BUDGET = 8


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ----------------------------------------------- shared shard-routing helpers
# The block-range decomposition used by every sharded-state engine in ops/:
# shard ``s`` of ``shards`` owns the contiguous global tile
# ``[s*w, (s+1)*w)`` with ``w = shard_tile_width(total, shards)``. Keeping
# the route a contiguous range (rather than ``idx % shards``) means the
# GLOBAL layout of a sharded axis is identical to the unsharded layout —
# checkpoints, sync alignment and result slicing never see the owner
# permutation a mod-route would impose. ``ops/scatter.py`` routes the sliced
# slice axis with the same helpers ``_sharded_label_program`` routes labels.


def shard_tile_width(total: int, shards: int) -> int:
    """Per-shard tile width of the block-range decomposition of ``total``
    elements over ``shards`` devices (the last tile may be ragged; in-shard
    masking against ``total`` retires padded lanes)."""
    return _round_up(total, shards) // shards


def mesh_platform_of(mesh: Mesh) -> str:
    """The platform a mesh's kernels lower for — resolved from the mesh's
    own devices, not the default backend (``lax.platform_dependent`` cannot
    prune branches inside shard_map, and a mesh names its platform)."""
    return next(iter(mesh.devices.flat)).platform


# ------------------------------------------------------------- path picking
def _prune_plan(l: int, k: int):
    """(group_w, n_groups, survivor_budget, ok). ``ok`` requires enough
    groups for the kth-group-max threshold bound (g >= k) and enough
    candidate capacity (g*s >= k)."""
    w = _PRUNE_GROUP_W
    g = -(-l // w)
    s = min(k, _PRUNE_SURVIVOR_BUDGET)
    ok = l > _DENSE_L_MAX and g >= k and g * s >= k
    return w, g, s, ok


def _pick_method(l: int, k: int, dtype, method: str) -> str:
    """Resolve the lowering for an (N, L) top-k at trace time.

    ``auto``: dense for small L / k >= L / non-f32 operands; the Pallas
    streaming kernel on TPU backends for k <= 128 (platform-dispatched at
    lowering so a CPU-committed array on a TPU host never meets Mosaic);
    dense everywhere else. The threshold-prune path is NOT auto-picked on
    CPU — measured dead end (docs/performance.md §Streaming top-k):
    XLA:CPU lowers 2-D ``lax.top_k`` to a fast partial-selection custom
    call (306 ms at (8192, 10k) k=5) while the batched 3-D TopK the
    grouped prune needs runs 735 ms on the SAME data, so every grouped
    variant loses (best 959 ms). ``prune`` stays available forced — it is
    the exact, valve-guarded fallback for backends whose top_k lowering is
    a full sort.
    """
    if method not in _METHODS:
        raise ValueError(f"method must be one of {_METHODS}, got {method!r}.")
    if method != "auto":
        return method
    if l <= _DENSE_L_MAX or k >= l or dtype != jnp.float32:
        return "dense"
    if k <= _PALLAS_MAX_K and jax.default_backend() == "tpu":
        return "pallas"
    return "dense"


# ------------------------------------------------------- Pallas streaming k
def _topk_kernel(x_ref, vals_ref, idx_ref, *, k: int, l_total: int, tile_l: int):
    """Grid = (row blocks, label tiles), label stream INNERMOST: the output
    blocks — each row's k running maxima (values + indices) — stay resident
    in VMEM across the whole label stream, exactly the accumulator pattern
    of ``ops/pallas_hist.py``.

    Per tile, k unrolled selection steps over the union of the carry
    (128 lanes) and the tile: take the max value, tie-break by the MINIMUM
    ORIGINAL INDEX among max lanes (placeholders and label padding carry
    unique indices above every real label, so they are only ever selected
    when fewer than k real candidates exist — impossible in the final
    result while k <= L), then retire the selected lane (value -> -inf,
    index -> sentinel, so an exhausted union can never re-select it). The
    min-over-indices tie-break — never over lane positions — is what makes
    the result bit-identical to ``lax.top_k``'s value-descending,
    lowest-index-first order at every tile boundary.
    """
    t = pl.program_id(1)
    rows = vals_ref.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, _CARRY_LANES), 1)

    @pl.when(t == 0)
    def _init():
        vals_ref[:] = jnp.full((rows, _CARRY_LANES), -jnp.inf, jnp.float32)
        idx_ref[:] = _PLACEHOLDER_BASE + lane

    x = x_ref[:]  # (rows, tile_l) f32
    gidx = tile_l * t + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    # label padding: value can never win, index stays unique and > real
    x = jnp.where(gidx < l_total, x, -jnp.inf)

    carry_v = vals_ref[:]
    carry_i = idx_ref[:]
    nv = jnp.full((rows, _CARRY_LANES), -jnp.inf, jnp.float32)
    ni = _PLACEHOLDER_BASE + lane
    for j in range(k):
        m = jnp.maximum(
            jnp.max(carry_v, axis=1, keepdims=True),
            jnp.max(x, axis=1, keepdims=True),
        )
        ic = jnp.min(
            jnp.where(carry_v == m, carry_i, _IDX_SENTINEL), axis=1, keepdims=True
        )
        it = jnp.min(
            jnp.where(x == m, gidx, _IDX_SENTINEL), axis=1, keepdims=True
        )
        isel = jnp.minimum(ic, it)  # selected entry's ORIGINAL index
        sel_c = (carry_v == m) & (carry_i == isel)
        sel_t = (x == m) & (gidx == isel)
        nv = jnp.where(lane == j, m, nv)
        ni = jnp.where(lane == j, isel, ni)
        # retire the selected lane entirely: -inf alone would leave its
        # index re-selectable once the union exhausts to all--inf ties
        carry_v = jnp.where(sel_c, -jnp.inf, carry_v)
        carry_i = jnp.where(sel_c, _IDX_SENTINEL, carry_i)
        x = jnp.where(sel_t, -jnp.inf, x)
        gidx = jnp.where(sel_t, _IDX_SENTINEL, gidx)
    vals_ref[:] = nv
    idx_ref[:] = ni


@functools.partial(watched_jit, static_argnames=("k", "interpret"))
def pallas_topk(
    x: jax.Array, k: int, *, interpret: bool = False
) -> tuple:
    """Streaming ``lax.top_k`` replacement: one pass over the label axis,
    per-row top-k state resident in VMEM. ``(values, indices)`` match
    ``jax.lax.top_k(x, k)`` bit-exactly for NaN-free f32 inputs (±inf and
    ties included). ``interpret=True`` runs the kernel in interpret mode on
    any backend — the CPU test suite's path."""
    if x.ndim != 2:
        raise ValueError(f"x must be 2-D (rows, labels), got shape {x.shape}.")
    n, l = x.shape
    if not 1 <= k <= min(l, _PALLAS_MAX_K):
        raise ValueError(
            f"pallas_topk requires 1 <= k <= min(L, {_PALLAS_MAX_K}), "
            f"got k={k} at L={l}."
        )
    x = x.astype(jnp.float32)
    block_rows = min(_BLOCK_ROWS, _round_up(max(n, 1), 8))
    tile_l = min(_TILE_L, _round_up(l, _CARRY_LANES))
    n_pad = _round_up(max(n, 1), block_rows)
    l_pad = _round_up(l, tile_l)
    if (n_pad, l_pad) != (n, l):
        # row padding computes garbage rows sliced away below; label padding
        # is masked inside the kernel by the gidx < l_total guard
        x = jnp.pad(x, ((0, n_pad - n), (0, l_pad - l)))
    vals, idx = pl.pallas_call(
        functools.partial(_topk_kernel, k=k, l_total=l, tile_l=tile_l),
        grid=(n_pad // block_rows, l_pad // tile_l),
        in_specs=[pl.BlockSpec((block_rows, tile_l), lambda i, t: (i, t))],
        out_specs=[
            pl.BlockSpec((block_rows, _CARRY_LANES), lambda i, t: (i, 0)),
            pl.BlockSpec((block_rows, _CARRY_LANES), lambda i, t: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, _CARRY_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, _CARRY_LANES), jnp.int32),
        ],
        interpret=interpret,
    )(x)
    return vals[:n, :k], idx[:n, :k]


# --------------------------------------------------------------- GSPMD rule
# Same situation as ops/pallas_hist.py: pallas_call has no partitioning rule,
# so under GSPMD a batch-sharded score matrix would be all-gathered onto
# every device before the kernel runs. Top-k is row-independent, so the rule
# is even simpler than the histogram's: each shard runs the VMEM kernel on
# its local rows and the outputs inherit the operand's row sharding — no
# collective at all.


def _row_axes(sharding) -> tuple:
    """Mesh axes the row (sample) axis is sharded over; () if replicated."""
    spec = getattr(sharding, "spec", None)
    spec0 = spec[0] if spec else None
    if spec0 is None:
        return ()
    return tuple(spec0) if isinstance(spec0, tuple) else (spec0,)


def _topk_sharding(mesh, axes):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axes if axes else None, None))


def _topk_infer(k, interpret, mesh, arg_shapes, result_shape):
    s = _topk_sharding(mesh, _row_axes(arg_shapes[0].sharding))
    return (s, s)


def _topk_partition(k, interpret, mesh, arg_shapes, result_shape):
    axes = _row_axes(arg_shapes[0].sharding)
    arg_sharding = _topk_sharding(mesh, axes)
    out_sharding = _topk_sharding(mesh, axes)

    def lower_fn(x):
        return pallas_topk(x, k, interpret=interpret)

    return mesh, lower_fn, (out_sharding, out_sharding), (arg_sharding,)


from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402


@functools.partial(custom_partitioning, static_argnums=(1, 2))
def sharded_pallas_topk(x, k, interpret=False):
    """:func:`pallas_topk` with a GSPMD partitioning rule: on a mesh each
    shard selects over its local rows and the outputs stay row-sharded; on
    one device it is exactly ``pallas_topk``."""
    return pallas_topk(x, k, interpret=interpret)


# Shardy rule: the row factor i propagates to both results; the label factor
# j is contracted; each result's k-lane axis is a fresh replicated factor.
# Older jax predates Shardy — there def_partition has no sharding_rule
# parameter and the GSPMD callbacks above are the complete rule.
_def_partition_kwargs = {}
if "sharding_rule" in inspect.signature(
    sharded_pallas_topk.def_partition
).parameters:
    _def_partition_kwargs["sharding_rule"] = "i j -> i k, i k"
sharded_pallas_topk.def_partition(
    infer_sharding_from_operands=_topk_infer,
    partition=_topk_partition,
    **_def_partition_kwargs,
)


# ------------------------------------------------- label-sharded streaming k
# ISSUE 14 tentpole: the engine above keeps the whole label axis resident on
# one device, capping L at what a single chip's VMEM/HBM holds. The
# block-distributed decomposition of *Large Scale Distributed Linear Algebra
# With TPUs* (PAPERS.md), applied to selection instead of matmul: shard the
# LABEL axis across a named mesh axis, run the per-shard streaming kernel on
# each local tile producing k candidates with GLOBAL original indices (shard
# offset added in-shard), exchange only the (k·shards) candidate pairs per
# row with ONE small all-gather, and finish with a narrow exact 2-key merge.
# The label axis is never replicated: per-device peak label-axis bytes are
# N·(L/shards)·4, and the candidate exchange is O(k·shards) bytes per row.
#
# Tie discipline: global indices make the merge's (value desc, index asc)
# 2-key sort reproduce ``lax.top_k``'s order bit-exactly — equal values
# resolve to the MINIMUM global index whatever shard they came from, the PR 3
# sentinel discipline lifted to the mesh (padding/ragged lanes carry value
# -inf and the index sentinel, so a real -inf score always beats padding).


def label_sharding_of(x):
    """``(mesh, label_axis, batch_axes)`` when ``x`` is a committed array
    whose LABEL (second) axis is sharded over exactly one mesh axis of a
    ``NamedSharding``; ``None`` otherwise (including tracers — inside jit the
    caller must pass the mesh explicitly). ``batch_axes`` is the row axis'
    spec entry (a mesh axis name, a tuple of them, or ``None``)."""
    sharding = getattr(x, "sharding", None)
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None or len(spec) < 2 or spec[1] is None:
        return None
    label = spec[1]
    if isinstance(label, tuple):
        if len(label) != 1:
            return None  # multi-axis label sharding: not supported, stay dense
        label = label[0]
    if getattr(mesh, "shape", None) is None or mesh.shape.get(label, 1) < 2:
        return None
    batch = spec[0] if len(spec) else None
    return mesh, label, batch


def _local_label_topk(xs, k_local: int, method: str, interpret, mesh_platform):
    """Per-shard selection over the local label tile — the same lowerings as
    the single-device engine. ``auto`` resolves against the MESH's platform
    at program-build time (``lax.platform_dependent`` cannot prune branches
    inside shard_map, and unlike the single-device entry the mesh names its
    devices, so the pick is exact rather than host-heuristic): the streaming
    Pallas kernel on TPU meshes (k within the carry), the backend's fast
    partial-selection ``top_k`` elsewhere (measured fastest on XLA:CPU —
    see :func:`_pick_method`)."""
    if method == "auto":
        method = (
            "pallas"
            if mesh_platform == "tpu" and k_local <= _PALLAS_MAX_K
            else "dense"
        )
    if method == "dense":
        return jax.lax.top_k(xs, k_local)
    if method == "prune":
        return prune_topk(xs, k_local)
    interp = (mesh_platform != "tpu") if interpret is None else interpret
    return pallas_topk(xs, k_local, interpret=interp)


@functools.lru_cache(maxsize=None)
def _sharded_label_program(
    mesh: Mesh,
    label_axis: str,
    batch_axes,
    k: int,
    l_total: int,
    method: str,
    interpret,
    with_gather: bool,
):
    """Jitted shard_map program per (mesh, label axis, k, L, method); jit
    handles shape-based caching beneath. ``with_gather`` additionally
    gathers a second label-wide operand (per-label relevance) at the
    selected indices INSIDE the shard — the retrieval metrics' path, which
    keeps the gather local so the relevance matrix is never replicated
    either."""
    shards = int(mesh.shape[label_axis])
    w = shard_tile_width(l_total, shards)  # local label-tile width
    k_local = min(k, w)
    mesh_platform = mesh_platform_of(mesh)
    row_spec = batch_axes if batch_axes else None
    in_spec = _P(row_spec, label_axis)
    out_spec = _P(row_spec, None)

    def body(xs, *extras):
        # xs: (rows_local, w) — this shard's label tile
        s = jax.lax.axis_index(label_axis)
        base = (s * w).astype(jnp.int32)
        col = base + jax.lax.broadcasted_iota(jnp.int32, xs.shape, 1)
        # ragged tiles: lanes past L can never win and carry the sentinel
        xs = jnp.where(col < l_total, xs.astype(jnp.float32), -jnp.inf)
        v, li = _local_label_topk(xs, k_local, method, interpret, mesh_platform)
        gi = li + base  # GLOBAL original index, offset added in-shard
        gi = jnp.where(gi < l_total, gi, _IDX_SENTINEL)
        ops = [v, gi]
        if extras:
            # local gather: only this shard's k_local candidates read the
            # relevance tile, so the extra operand stays label-sharded too
            ops.append(jnp.take_along_axis(extras[0], li, axis=1))
        # THE one collective: O(k_local·shards) candidate pairs per row
        gathered = [
            jax.lax.all_gather(o, label_axis, axis=1, tiled=True) for o in ops
        ]
        # exact merge: ascending 2-key sort on (-value, global index) is
        # descending-value with min-global-index tie-break — lax.top_k's
        # order bit-exactly (negation is a bijection on NaN-free floats)
        merged = jax.lax.sort(
            (-gathered[0], gathered[1], *gathered[2:]),
            num_keys=2,
            dimension=1,
        )
        out = (-merged[0][:, :k], merged[1][:, :k])
        if extras:
            out = out + (merged[2][:, :k],)
        return out

    n_in = 2 if with_gather else 1
    n_out = 3 if with_gather else 2

    def impl(x, *extras):
        l_pad = w * shards
        if l_pad != x.shape[1]:
            # pad value is irrelevant: in-shard masking against l_total
            # already retires every padded lane
            pad = ((0, 0), (0, l_pad - x.shape[1]))
            x = jnp.pad(x, pad)
            extras = tuple(jnp.pad(e, pad) for e in extras)
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(in_spec,) * n_in,
            out_specs=(out_spec,) * n_out,
            **_SHARD_MAP_KWARGS,
        )(x, *extras)

    return watched_jit(impl, name="ops.sharded_label_topk"), k_local, w


def sharded_label_topk(
    x,
    k: int,
    *,
    mesh: Mesh = None,
    label_axis: str = None,
    batch_axes=None,
    method: str = "auto",
    interpret=None,
    gather=None,
):
    """Top-k over a LABEL-sharded score matrix: per-shard streaming
    selection + one O(k·shards) candidate all-gather + a narrow exact merge
    — bit-identical to ``jax.lax.top_k`` (values AND tie-ordered indices)
    for NaN-free **f32** inputs, with the label axis never replicated.
    Like the single-device streaming paths, selection happens in f32
    (non-f32 operands are cast and the values return as f32; wide integers
    that collapse in f32 would change values/ties, which is why the
    ``topk()`` auto-pick only engages this path for f32 operands).

    Args:
        x: scores ``(rows, labels)``, label-sharded over ``label_axis`` (or
            pass ``mesh``/``label_axis`` explicitly — required inside jit,
            where operand shardings are invisible).
        k: ``1 <= k <= labels``.
        mesh / label_axis / batch_axes: the mesh decomposition; derived from
            ``x.sharding`` when omitted. ``batch_axes`` keeps row sharding
            composable on multi-axis (batch × label) meshes.
        method: per-shard local lowering (``auto``/``dense``/``prune``/
            ``pallas`` — the single-device engine's methods).
        interpret: Pallas interpret override for the local kernel.
        gather: optional label-wide companion operand ``(rows, labels)``
            (e.g. a relevance matrix) gathered at the selected indices
            inside each shard; returned as a third output ``(rows, k)``.
            Keeps retrieval-metric gathers off the replication path.
    """
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"x must be 2-D (rows, labels), got shape {x.shape}.")
    n, l = x.shape
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if not 1 <= k <= l:
        raise ValueError(f"requires 1 <= k <= L, got k={k} at L={l}.")
    if method not in _LOCAL_METHODS:
        raise ValueError(
            f"method must be one of {_LOCAL_METHODS}, got {method!r}."
        )
    if mesh is None or label_axis is None:
        derived = label_sharding_of(x)
        if derived is None:
            raise ValueError(
                "sharded_label_topk needs a label-sharded operand or an "
                "explicit mesh= and label_axis= (inside jit the operand's "
                "sharding is invisible — always pass them there)."
            )
        d_mesh, d_label, _d_batch = derived
        mesh = mesh if mesh is not None else d_mesh
        label_axis = label_axis if label_axis is not None else d_label
    if str(label_axis) not in mesh.shape:
        raise ValueError(
            f"label_axis {label_axis!r} is not an axis of the mesh "
            f"(axes: {tuple(mesh.shape)})."
        )
    if batch_axes is None:
        # derive the ROW sharding from the committed operand even on
        # explicit-mesh calls (the metric path): dropping it would make the
        # shard_map in_spec P(None, label) all-gather the batch axis on
        # (data × label) meshes — exactly the replication this engine
        # exists to avoid, just on the other axis
        spec = getattr(getattr(x, "sharding", None), "spec", None)
        if spec and len(spec) and spec[0] is not None:
            batch_axes = spec[0]
    if isinstance(batch_axes, list):
        batch_axes = tuple(batch_axes)
    if gather is not None:
        gather = jnp.asarray(gather)
        if gather.shape != x.shape:
            raise ValueError(
                f"gather operand must match x's shape {x.shape}, got "
                f"{gather.shape}."
            )
    program, k_local, w = _sharded_label_program(
        mesh,
        str(label_axis),
        batch_axes,
        k,
        l,
        method,
        interpret,
        gather is not None,
    )
    shards = int(mesh.shape[str(label_axis)])
    if _obs._enabled:
        _obs.counter("ops.topk.calls", path="sharded_label")
        # candidate-exchange accounting: (value f32 + index i32) per
        # candidate, k_local·shards candidates per row (the gather
        # companion adds one more f32 column when present)
        cols = 12 if gather is not None else 8
        _obs.counter(
            "ops.topk.merge_bytes", float(n * shards * k_local * cols)
        )
        _obs.gauge(
            "ops.topk.label_bytes_per_device",
            float(_rows_per_device(mesh, batch_axes, n) * w * 4),
            path="sharded_label",
        )
    if gather is not None:
        return program(x, gather)
    return program(x)


def _rows_per_device(mesh: Mesh, batch_axes, n: int) -> float:
    """Rows resident per device given the batch-axis sharding (1 when the
    row axis is replicated)."""
    if not batch_axes:
        return float(n)
    axes = batch_axes if isinstance(batch_axes, tuple) else (batch_axes,)
    denom = 1
    for a in axes:
        denom *= int(mesh.shape[a])
    return float(n) / max(denom, 1)


# --------------------------------------------------------- threshold-prune
@functools.partial(watched_jit, static_argnames=("k",))
def prune_topk(x: jax.Array, k: int) -> tuple:
    """Exact top-k via threshold-prune — the XLA fallback for non-Pallas
    backends. Replaces one full-width sort with: a per-row kth-value lower
    bound from 128-wide group maxima, a survivor mask against it, one
    narrow per-group ``lax.top_k(s)`` over the masked groups, and one final
    ``lax.top_k`` over the ~G·s candidates.

    Correctness valve: a true top-k member can only be missing from the
    candidates when its group held more than ``s`` survivors; `any` such
    overflow re-runs plain full-width ``lax.top_k`` for the whole batch via
    one ``lax.cond`` (the compiled fast path never executes it). All-equal
    rows — every element tying the threshold — trip the valve by
    construction, which is the adversarial case the test suite pins.

    Matches ``jax.lax.top_k`` bit-exactly (values and tie-broken indices)
    for NaN-free inputs: candidates keep original indices, and candidate
    order (group-major, value-descending, lowest-index-first within ties)
    makes the final ``top_k``'s positional tie-break equivalent to an
    original-index tie-break."""
    if x.ndim != 2:
        raise ValueError(f"x must be 2-D (rows, labels), got shape {x.shape}.")
    n, l = x.shape
    if not 1 <= k <= l:
        raise ValueError(f"requires 1 <= k <= L, got k={k} at L={l}.")
    x = x.astype(jnp.float32)
    w, g, s, ok = _prune_plan(l, k)
    if not ok:
        return jax.lax.top_k(x, k)
    l_pad = g * w
    xp = (
        jnp.pad(x, ((0, 0), (0, l_pad - l)), constant_values=-jnp.inf)
        if l_pad != l
        else x
    )
    gmax = jnp.max(xp.reshape(n, g, w), axis=2)  # (n, g)
    # kth-largest group max <= true kth value: the k best groups each hold
    # one element >= it, so masking against it keeps the whole true top-k
    # (and at least k survivors — the group maxima themselves)
    theta = jax.lax.top_k(gmax, k)[0][:, k - 1 : k]  # (n, 1)
    mask = xp >= theta
    counts = jnp.sum(mask.reshape(n, g, w), axis=2)  # survivors per group
    overflow = jnp.any(counts > s)

    def _dense(xq):
        v, i = jax.lax.top_k(xq, k)
        return v, i

    def _pruned(xq):
        del xq
        xm = jnp.where(mask, xp, -jnp.inf).reshape(n, g, w)
        cand_v, cand_j = jax.lax.top_k(xm, s)  # (n, g, s) within-group
        cand_i = cand_j + (jnp.arange(g, dtype=jnp.int32) * w)[None, :, None]
        vals, pos = jax.lax.top_k(cand_v.reshape(n, g * s), k)
        idx = jnp.take_along_axis(cand_i.reshape(n, g * s), pos, axis=1)
        return vals, idx

    return jax.lax.cond(overflow, _dense, _pruned, x)


# ----------------------------------------------------------------- engine
def topk(x, k: int, *, method: str = "auto", interpret=None) -> tuple:
    """``(values, indices)`` of the k largest entries per row — a drop-in
    ``jax.lax.top_k`` with streaming lowerings for the large-label regime.

    Args:
        x: scores ``(rows, labels)``.
        k: ``1 <= k <= labels``.
        method: ``"auto"`` (pick by size/backend — see :func:`_pick_method`)
            or a forced ``"dense"`` / ``"prune"`` / ``"pallas"``.
        interpret: Pallas interpret-mode override for a forced
            ``"pallas"``; defaults to interpreting off-TPU (the CPU test
            suite's knob), mirroring ``ops/confusion.py``.
    """
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"x must be 2-D (rows, labels), got shape {x.shape}.")
    l = x.shape[1]
    if type(k) is not int:
        raise TypeError(f"Expected `k` to be an integer, but {type(k)} was provided.")
    if not 1 <= k <= l:
        raise ValueError(f"requires 1 <= k <= L, got k={k} at L={l}.")
    # label-sharded operands engage the block-distributed engine: forced via
    # method="sharded_label", or auto-picked when the committed operand's
    # label axis is sharded (tracers never are — inside jit callers route
    # through sharded_label_topk with an explicit mesh). f32 only, like the
    # single-device streaming picks: the sharded kernel selects in f32, and
    # a silent cast would break the drop-in contract for wide-integer
    # operands (distinct ints collapsing in f32 changes values AND ties).
    if method == "sharded_label" or (
        method == "auto"
        and x.dtype == jnp.float32
        and label_sharding_of(x) is not None
    ):
        return sharded_label_topk(x, k, interpret=interpret)
    resolved = _pick_method(l, k, x.dtype, method)
    if resolved == "prune" and not _prune_plan(l, k)[3]:
        # prune's own feasibility gate would fall through to dense inside
        # prune_topk — resolve it HERE so the counter reports the lowering
        # that actually runs, not the one that was asked for
        resolved = "dense"
    # trace-time accounting; for the auto "pallas" pick the actual lowering
    # is platform-dispatched below, so a CPU-committed operand on a TPU
    # host runs dense while this still counts pallas (module docstring)
    _obs.counter("ops.topk.calls", path=resolved)
    if _obs._enabled:
        # resident label-axis footprint per device on the single-device
        # paths (the sharded engine records its own ~1/shards figure) — the
        # cost gauge the bench's dense-vs-sharded ratio assertion reads
        _obs.gauge(
            "ops.topk.label_bytes_per_device",
            float(x.shape[0] * l * 4),
            path=resolved,
        )
    if resolved == "dense":
        return jax.lax.top_k(x, k)
    if resolved == "prune":
        return prune_topk(x.astype(jnp.float32), k)
    # pallas
    if method == "auto":
        # dispatch per LOWERING platform (as class_counts does): a
        # CPU-committed array on a TPU host takes the XLA dense lowering
        # (measured fastest there — see _pick_method), never a Mosaic
        # kernel it cannot compile
        return jax.lax.platform_dependent(
            x.astype(jnp.float32),
            tpu=lambda a: tuple(sharded_pallas_topk(a, k, False)),
            default=lambda a: tuple(jax.lax.top_k(a, k)),
        )
    interp = (jax.default_backend() != "tpu") if interpret is None else interpret
    return sharded_pallas_topk(x.astype(jnp.float32), k, interp)


def topk_values(x, k: int, *, method: str = "auto", interpret=None) -> jax.Array:
    """The values half of :func:`topk`."""
    return topk(x, k, method=method, interpret=interpret)[0]


def topk_indices(x, k: int, *, method: str = "auto", interpret=None) -> jax.Array:
    """The indices half of :func:`topk`."""
    return topk(x, k, method=method, interpret=interpret)[1]

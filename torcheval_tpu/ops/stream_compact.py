"""Pallas TPU stream-compaction kernel: stable masked compress-to-front.

The missing primitive behind every "remove the dead rows" step in the curve
family. The reference compacts with boolean masking (dynamic shapes,
``torcheval/metrics/functional/classification/auroc.py:50-67``); the round-3
TPU design kept static shapes by paying a SECOND full ``lax.sort`` per
compaction to push dead rows behind the live ones
(``ops/summary.py::compact_counts``). That second sort is a ~300-pass
bitonic network over the whole buffer — ~67 ms per 2^24 rows on one v5e —
used as a mover that a single streaming pass replaces.

The kernel is **lane-major end to end** — this is the part that matters on
TPU. Earlier prototypes moved rows onto sublanes so a one-hot matmul could
compact them, and the (1,128)->(128,1) relayouts alone cost 2.3 ns/element
(ablated on chip): every (128,1) value touches 16 native registers at 1/128
lane utilisation. Measured redesign, per 128-element tile:

* the tile's payload columns are copied into an (8, 128) assembly block
  (plain lane-major row copies),
* exclusive ranks of live lanes come from ``mask @ strict-upper-tri``
  (one (``_RANK_BATCH``, 128)x(128, 128) MXU matmul serving a batch of
  ``_RANK_BATCH`` tiles — ``jnp.cumsum`` has no Mosaic lowering; integer
  ranks <= 128 are exact even in bf16),
* ONE lane-contraction matmul ``X(8,128) @ P^T(128,128)`` compacts every
  column at once, in lane-major layout, with
  ``P[r, i] = live[i] & (rank[i] == r)`` and ``Precision.HIGHEST`` —
  bit-exact for arbitrary FINITE f32 payloads (each output lane receives
  exactly one input lane; bf16x3 splits any f32 losslessly). Dead lanes are
  zeroed before the matmul (0 x NaN = NaN otherwise); live lanes must be
  finite — ship non-finite f32 as raw-bit halves via :func:`split_f32_bits`,
* a DYNAMIC lane roll by ``fill % 128`` rotates the compacted run to its
  append phase, and per column TWO lane-masked stores at dynamic sublane
  rows place exactly ``count`` lanes into the staging buffer — no
  read-modify-write, no over-copy garbage,
* each full staging chunk leaves through one DMA (double-buffered, so the
  copy overlaps the next chunk's compute); staging is already lane-major,
  so flushes move bytes untouched.

The TPU Pallas grid runs sequentially on the core, so the staging fill level
carries across grid steps in SMEM and output order is exactly the input
order of the live rows (stable). Payload columns are f32; int32 columns that
can exceed 2^24 (curve counts go to 2^31) are split into exact u16 halves
(:func:`split_i32` / :func:`combine_i32`).

Hardware constraints baked in (probed on v5e, 2026-07-30):

* dynamic-offset HBM DMA slices must be 1024-element aligned -> the flush
  quantum is a multiple of 1024 and staging absorbs arbitrary offsets;
* dynamic LANE-offset VMEM stores do not compile -> the dynamic lane phase
  is realised as a roll + lane-masked stores at dynamic SUBLANE rows
  (both lower and verified);
* MXU matmuls default to bf16 operands -> ``Precision.HIGHEST`` wherever a
  payload value crosses the MXU;
* f32 ``broadcasted_iota`` has no lowering -> integer iota + casts.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torcheval_tpu.obs.recompile import watched_jit

# renamed across jax versions: TPUCompilerParams (<= 0.4.x) -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

if hasattr(pltpu, "store"):

    def _masked_store(ref, c, row, val, mask):
        """Lane-masked (1, 128) store at (c, row) of a (C, R, 128) ref."""
        pltpu.store(ref.at[c, pl.ds(row, 1), :], val, mask=mask)

else:  # jax <= 0.4.x spells the masked store pl.store(ref, idx, val, mask=)

    def _masked_store(ref, c, row, val, mask):
        pl.store(
            ref,
            (pl.ds(c, 1), pl.ds(row, 1), slice(None)),
            val[None],
            mask=mask[None],
        )

# elements per grid step (64 lane-rows of 128)
_BLOCK = 8192
# staging flush quantum: multiple of the 1024-element HBM DMA alignment
_CHUNK = 2048
_CHUNK_ROWS = _CHUNK // 128  # lane-major rows per flushed chunk
# staging rows: chunk + 2 slack rows (one append can spill one row past the
# chunk boundary, plus the row the boundary lands in)
_STAGE_ROWS = _CHUNK_ROWS + 2
# tiles served by one batched mask-load + rank matmul per loop iteration
_RANK_BATCH = 8
_MAX_COLS = 7  # assembly tile has 8 sublane rows; keep one spare


def _compact_kernel(utri_ref, mask_ref, *refs, n_cols: int, unroll: int):
    """Grid = (n // _BLOCK,). refs order:
    inputs:   col_0 .. col_{n-1}                        (blocked (64, 128))
    outputs:  out (ANY, (chunks, n_cols, _CHUNK_ROWS, 128)), nlive (SMEM)
    scratch:  asm (VMEM (8, 128)), stage (VMEM (n_cols, _STAGE_ROWS, 128)),
              fbuf (VMEM (2, n_cols, _CHUNK_ROWS, 128)), fill (SMEM),
              chunks (SMEM), sem (DMA (2,))
    """
    col_refs = refs[:n_cols]
    out_ref = refs[n_cols]
    nlive_ref = refs[n_cols + 1]
    asm_ref = refs[n_cols + 2]
    stage_ref = refs[n_cols + 3]
    fbuf_ref = refs[n_cols + 4]
    fill_ref = refs[n_cols + 5]
    chunks_ref = refs[n_cols + 6]
    sem = refs[n_cols + 7]

    j = pl.program_id(0)
    nsteps = pl.num_programs(0)

    @pl.when(j == 0)
    def _init():
        fill_ref[0, 0] = 0
        chunks_ref[0, 0] = 0

    utri = utri_ref[:]  # (128, 128) strict upper triangle, f32

    def _dma(slot, cidx):
        # out's chunk index leads so this slice never cuts a tiled dim
        return pltpu.make_async_copy(
            fbuf_ref.at[slot],
            out_ref.at[cidx],
            sem.at[slot],
        )

    def _flush():
        """Hand staging[_CHUNK] to the current fbuf slot and start its DMA."""
        cidx = chunks_ref[0, 0]
        slot = jax.lax.rem(cidx, 2)

        # the slot's previous DMA (two flushes ago) must have completed
        @pl.when(cidx >= 2)
        def _wait_prev():
            _dma(slot, cidx - 2).wait()

        for c in range(n_cols):
            fbuf_ref[slot, c] = stage_ref[c, 0:_CHUNK_ROWS, :]
            # carry the slack rows down AFTER the chunk area is copied
            stage_ref[c, 0:2, :] = stage_ref[c, _CHUNK_ROWS:_STAGE_ROWS, :]
        _dma(slot, cidx).start()
        chunks_ref[0, 0] = cidx + 1
        fill_ref[0, 0] = fill_ref[0, 0] - _CHUNK

    def body(t, _):
        # batched across _RANK_BATCH tiles: one mask load + ONE rank matmul
        # serve the next _RANK_BATCH tiles (at the current _RANK_BATCH = 8
        # a 100M-row pass measured 299 ms vs 410 ms unbatched, and the 1B
        # headline leg reached 86.9M preds/s); the store/flush section stays
        # strictly per tile so every staging invariant is unchanged
        mb = mask_ref[pl.ds(_RANK_BATCH * t, _RANK_BATCH), :]  # (B, 128)
        ranksb = jax.lax.dot_general(
            mb, utri, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (B, 128): exclusive ranks of live lanes per tile
        for k in range(_RANK_BATCH):
            _tile(_RANK_BATCH * t + k, mb[k : k + 1, :], ranksb[k : k + 1, :])
        return 0

    def _tile(t, m_row, ranks):
        for c in range(n_cols):
            asm_ref[pl.ds(c, 1), :] = col_refs[c][pl.ds(t, 1), :]
        # Zero every DEAD lane before the payload crosses the MXU: the
        # permutation matmul relies on 0-weight lanes contributing 0, but
        # 0 * NaN = NaN and 0 * inf = NaN — a NaN/±inf value in a dead lane
        # (e.g. the NaN pad scores of the tile straddling the live/padding
        # boundary) would otherwise poison every live output lane of its
        # tile (round-4 verdict weak #1). Live lanes must be finite — the
        # wrapper's contract; ``compact_summary_rows`` ships scores as raw
        # bit halves so even ±inf/NaN scores satisfy it.
        x = jnp.where(m_row > 0.5, asm_ref[:], 0.0)  # (8,128), lane i = row i
        count = jnp.sum(m_row).astype(jnp.int32)
        ri = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
        # P[r, i] = live[i] & (rank[i] == r)
        perm = ((ranks.astype(jnp.int32) == ri) & (m_row > 0.5)).astype(
            jnp.float32
        )
        # compact every column at once, staying lane-major:
        # out[c, r] = sum_i x[c, i] * P[r, i]
        compacted = jax.lax.dot_general(
            x, perm, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (8, 128): lanes [0, count) live
        fill = fill_ref[0, 0]
        row = fill // 128
        phase = jax.lax.rem(fill, 128)
        rotated = pltpu.roll(compacted, phase, 1)
        li = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        end = phase + count
        mask_a = (li >= phase) & (li < end)
        mask_b = li < end - 128
        for c in range(n_cols):
            v = rotated[c : c + 1, :]
            _masked_store(stage_ref, c, row, v, mask_a)
            _masked_store(stage_ref, c, row + 1, v, mask_b)
        fill_ref[0, 0] = fill + count

        @pl.when(fill_ref[0, 0] >= _CHUNK)
        def _maybe_flush():
            _flush()

    # full unroll on the compiled path (Mosaic supports only 1 or
    # num_steps): the per-tile cost is the dependent fill-counter chain,
    # but unrolling still shaves loop control (part of the 410 -> 299 ms
    # measured on a 100M-row pass with the rank batching; outputs
    # bit-identical). Interpret mode keeps the rolled loop — a full unroll
    # there re-executes the traced _RANK_BATCH-tile body all
    # _BLOCK/(128*_RANK_BATCH) times per block and was measured to blow the
    # CPU test suite up ~10x.
    jax.lax.fori_loop(0, _BLOCK // (128 * _RANK_BATCH), body, 0, unroll=unroll)

    @pl.when(j == nsteps - 1)
    def _finish():
        # total live rows BEFORE the drain resets the fill counter
        nlive_ref[0] = chunks_ref[0, 0] * _CHUNK + fill_ref[0, 0]
        # drain the partial chunk (garbage beyond fill; the XLA wrapper
        # overwrites everything past nlive with pad values)
        _flush()
        # wait out every in-flight DMA so buffers are final on return
        cidx = chunks_ref[0, 0]  # count AFTER the drain flush

        @pl.when(cidx >= 2)
        def _w0():
            _dma(jax.lax.rem(cidx, 2), cidx - 2).wait()

        @pl.when(cidx >= 1)
        def _w1():
            _dma(jax.lax.rem(cidx + 1, 2), cidx - 1).wait()


@functools.partial(watched_jit, static_argnames=("n_cols", "interpret"))
def _compact_call(utri, mask2d, cols2d, n_cols: int, interpret: bool):
    rows = mask2d.shape[0]
    n = rows * 128
    nsteps = n // _BLOCK
    out_chunks = n // _CHUNK + 1  # +1: drain slack
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nsteps,),
        in_specs=[pl.BlockSpec((128, 128), lambda j: (0, 0))]
        + [
            pl.BlockSpec((_BLOCK // 128, 128), lambda j: (j, 0))
            for _ in range(n_cols + 1)
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((n_cols, _STAGE_ROWS, 128), jnp.float32),
            pltpu.VMEM((2, n_cols, _CHUNK_ROWS, 128), jnp.float32),
            pltpu.SMEM((1, 1), jnp.int32),
            pltpu.SMEM((1, 1), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out, nlive = pl.pallas_call(
        functools.partial(
            _compact_kernel,
            n_cols=n_cols,
            unroll=1 if interpret else _BLOCK // (128 * _RANK_BATCH),
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(
                (out_chunks, n_cols, _CHUNK_ROWS, 128), jnp.float32
            ),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(utri, mask2d, *cols2d)
    return out, nlive


def _utri128() -> jax.Array:
    r = jnp.arange(128, dtype=jnp.int32)
    return (r[:, None] < r[None, :]).astype(jnp.float32)


def stream_compact(
    mask: jax.Array,
    cols: Sequence[jax.Array],
    *,
    interpret: bool = False,
) -> Tuple[List[jax.Array], jax.Array]:
    """Stable compress-to-front of ``cols`` rows where ``mask`` is nonzero.

    ``mask``: (n,) float/bool/int (nonzero = keep). ``cols``: up to 7
    1-D f32 arrays of the same length. Returns the compacted columns at the
    SAME length — contents past ``n_live`` are garbage; callers overwrite
    them with pad values — plus the ``n_live`` scalar (device, i32).

    Payload contract: values in LIVE lanes must be finite — they cross the
    MXU in the permutation matmul, where a live ``±inf`` would meet the
    zero weights of the other output lanes and turn them NaN. Dead-lane
    values are ignored entirely (NaN/±inf safe: they are zeroed before the
    matmul). To move non-finite f32 payloads exactly, ship their raw bits
    via :func:`split_f32_bits` / :func:`combine_f32_bits` as
    :func:`compact_summary_rows` does for scores.
    """
    n = mask.shape[0]
    n_cols = len(cols)
    if n_cols > _MAX_COLS:
        raise ValueError(f"at most {_MAX_COLS} columns, got {n_cols}.")
    n_pad = max(-(-n // _BLOCK) * _BLOCK, _BLOCK)
    maskf = (mask != 0).astype(jnp.float32)
    if n_pad != n:
        pad = jnp.zeros((n_pad - n,), jnp.float32)
        maskf = jnp.concatenate([maskf, pad])
        cols = [jnp.concatenate([c.astype(jnp.float32), pad]) for c in cols]
    else:
        cols = [c.astype(jnp.float32) for c in cols]
    mask2d = maskf.reshape(-1, 128)
    cols2d = tuple(c.reshape(-1, 128) for c in cols)
    out, nlive = _compact_call(_utri128(), mask2d, cols2d, n_cols, interpret)
    flat = [out[:, c].reshape(-1)[:n] for c in range(n_cols)]
    return flat, nlive[0]


# ------------------------------------------------------------ exact i32 lanes
def split_i32(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Non-negative int32 -> two f32 halves, each < 2^16 (f32-exact)."""
    x = x.astype(jnp.int32)
    return (
        jax.lax.shift_right_logical(x, 16).astype(jnp.float32),
        (x & jnp.int32(0xFFFF)).astype(jnp.float32),
    )


def combine_i32(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Inverse of :func:`split_i32`."""
    return hi.astype(jnp.int32) * jnp.int32(65536) + lo.astype(jnp.int32)


def split_f32_bits(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32 -> two f32 halves holding its RAW BIT PATTERN, each an integer
    < 2^16 (f32-exact). Unlike :func:`split_i32` this is total over all of
    f32 — NaN, ±inf and -0.0 round-trip bit-identically — and the halves are
    always finite, so they can safely cross the MXU permutation matmul."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return (
        jax.lax.shift_right_logical(b, jnp.uint32(16)).astype(jnp.float32),
        (b & jnp.uint32(0xFFFF)).astype(jnp.float32),
    )


def combine_f32_bits(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Inverse of :func:`split_f32_bits`."""
    b = hi.astype(jnp.uint32) * jnp.uint32(65536) + lo.astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(b, jnp.float32)


# --------------------------------------------------- summary-row compaction
from torcheval_tpu.ops.summary import PAD_SCORE  # noqa: E402


@functools.partial(watched_jit, static_argnames=("interpret",))
def compact_summary_rows(
    scores: jax.Array,
    tp: jax.Array,
    fp: jax.Array,
    keep: jax.Array,
    *,
    interpret: bool = False,
):
    """Compact kept (score, tp, fp) rows to the front, stable; rows past the
    live count become (NaN, 0, 0) padding. Returns ``(s, tp, fp, n_live)``
    with arrays the same length as the input — the single-pass replacement
    for ``compact_counts``' second full sort.

    Scores travel as the two 16-bit halves of their raw f32 bits
    (:func:`split_f32_bits`): the kernel's permutation matmul requires
    finite payloads, and scores are the one column that can legally be
    ``-inf`` (log-prob scores, ``ops/summary.py:32-34``) while the padding
    already in the buffer is NaN. Bit transport is exact for every f32,
    costs one extra column (6 of the kernel's 7), and reconstructs the
    original values bit-for-bit on the way out."""
    s_hi, s_lo = split_f32_bits(scores)
    tp_hi, tp_lo = split_i32(tp)
    fp_hi, fp_lo = split_i32(fp)
    (sh, sl, tph, tpl, fph, fpl), n_live = stream_compact(
        keep, [s_hi, s_lo, tp_hi, tp_lo, fp_hi, fp_lo], interpret=interpret
    )
    live = jnp.arange(sh.shape[0], dtype=jnp.int32) < n_live
    s_out = jnp.where(live, combine_f32_bits(sh, sl), PAD_SCORE)
    tp_out = jnp.where(live, combine_i32(tph, tpl), 0)
    fp_out = jnp.where(live, combine_i32(fph, fpl), 0)
    return s_out, tp_out, fp_out, n_live

"""Segment scatter: per-slice delta application, tiled in VMEM or sharded.

The sliced collection's fold (``metrics/sliced.py``) reduces per-sample
delta rows into a leading ``[num_segments]`` slice axis. XLA lowers
``jax.ops.segment_sum`` to a scatter-add that is SERIAL per update row on
CPU and still row-at-a-time on TPU's scatter unit — the documented 0.32x
gap of ``bench.py::config11_sliced``. Two remedies live here:

* :func:`pallas_segment_sum` — the ``pallas_hist``/``pallas_topk``
  accumulate-in-VMEM pattern (PR 3): grid = (segment tiles, sample
  blocks) with the sample stream INNERMOST, so each segment tile's
  ``(seg_tile, d)`` accumulator stays resident in VMEM while every sample
  block streams past it; per step one MXU contraction
  ``one_hot(rows).T @ vals`` replaces N serial scatter rows.
* the ``mesh``/``axis`` route of :func:`segment_scatter` — the slice axis
  block-range-sharded over a named mesh axis (the ``sharded_label_topk``
  playbook, PR 14): shard ``s`` owns global rows ``[s*w, (s+1)*w)``, each
  shard masks the replicated row column into its own range and scatters
  into its LOCAL ``(w, ...)`` tile. No all_to_all is needed — out-of-range
  rows drop by segment-op semantics — and no collective touches
  state-sized operands: the output is born ``P(axis)``-sharded and every
  per-device segment extent is ``num_segments / shards``.

Exactness: the Pallas kernel accumulates in f32 (one-hot matmul), exact
for integer counts while any single segment's total stays <= 2**24 (every
integer up to 2**24 inclusive is float32-exact — the ``pallas_hist``
bound); float sums fall under the documented f32 associativity contract
(docs/performance.md). The XLA path keeps native dtypes. The auto-pick
therefore only swaps in the kernel on TPU backends for "sum" over
narrow (<= 4-byte) lanes within :data:`_PALLAS_MAX_SEGMENTS` — which is a
PER-SHARD bound: sharding is what shrinks a million-cohort extent back
into the kernel's envelope. ``method="pallas"`` forces it anywhere
(interpret mode off-TPU); the CPU test suite proves parity that way.
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as _P

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs.recompile import watched_jit
from torcheval_tpu.ops.topk import (
    _SHARD_MAP_KWARGS,
    _round_up,
    _shard_map,
    mesh_platform_of,
    shard_tile_width,
)

__all__ = [
    "segment_scatter",
    "pallas_segment_sum",
    "sharded_pallas_segment_sum",
]

_METHODS = ("auto", "pallas", "xla")
_REDUCES = ("sum", "max", "min")

_SEGMENT_OPS = {
    "sum": jax.ops.segment_sum,
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}

# byte budget for the per-step VMEM working set (vals block + one-hot
# intermediate + resident accumulator) — well under VMEM (~16 MB/core)
_VMEM_BUDGET_BYTES = 8 * 2**20
# segment rows tiled across the accumulator's sublane dim per grid step
_MAX_SEG_TILE = 512
# delta lanes past this width leave the kernel's envelope (the accumulator
# row stops fitting the tile plan) — auto falls back to XLA
_MAX_TAIL_LANES = 512
# auto-pick ceiling on the (per-shard) segment extent: the one-hot
# contraction is O(N * seg_pad) VPU/MXU work, so past this the serial
# scatter it replaces is no longer the bottleneck being bought back
_PALLAS_MAX_SEGMENTS = 65_536


def _tile_plan(d_pad: int, seg_pad: int):
    """(sample_rows, seg_tile): 128-lane sample rows per grid step and the
    segment-tile height, sized so the ``(rows*128, d_pad)`` vals block plus
    the ``(rows*128, seg_tile)`` one-hot stay inside the VMEM budget with
    rows a multiple of 8 (the f32 sublane count)."""
    seg_tile = min(seg_pad, _MAX_SEG_TILE)
    rows = _VMEM_BUDGET_BYTES // (128 * 4 * (d_pad + seg_tile))
    return max(rows // 8 * 8, 8), seg_tile


def _scatter_kernel(rows_ref, vals_ref, out_ref, *, seg_tile: int):
    # grid = (segment tiles, sample blocks): sample stream INNERMOST, so
    # segment tile j's accumulator stays resident in VMEM across the whole
    # stream instead of round-tripping HBM every step
    j = pl.program_id(0)  # segment-tile index
    i = pl.program_id(1)  # sample-block index

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    rows = rows_ref[:]  # (m, 128) int32 — samples fill whole lane tiles
    vals = vals_ref[:]  # (m, 128, d_pad) f32 — same flat sample order
    # segments of THIS tile: [j*seg_tile, (j+1)*seg_tile)
    segs = j * seg_tile + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, seg_tile), 2
    )
    onehot = (rows[:, :, None] == segs).astype(jnp.float32)  # (m, 128, s)
    # collapse the leading sample dims (layout-preserving: the lane dim is
    # untouched) and contract them on the MXU: (n, s)^T-free dot_general
    n = rows.shape[0] * rows.shape[1]
    out_ref[:] += jax.lax.dot_general(
        onehot.reshape(n, seg_tile),
        vals.reshape(n, vals.shape[-1]),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(watched_jit, static_argnames=("num_segments", "interpret"))
def pallas_segment_sum(
    vals: jax.Array,
    rows: jax.Array,
    num_segments: int,
    *,
    interpret: bool = False,
) -> jax.Array:
    """``jax.ops.segment_sum(vals, rows, num_segments)`` for 2-D f32
    ``vals`` as a Pallas kernel: one-hot MXU contraction per (segment tile,
    sample block) with the accumulator resident in VMEM. Rows outside
    ``[0, num_segments)`` contribute nothing (segment-op drop semantics —
    they match no tile's iota, negative or past the padded extent).

    Layout note: the row column feeds in as ``(n/128, 128)`` — samples fill
    whole (8, 128) tiles. A ``(N, 1)`` operand would be tiled with 128x
    padding (the 8 GB HBM "copy" trap documented in ``pallas_hist``); the
    vals block rides the same flat order as ``(n/128, 128, d)``.
    """
    if vals.ndim != 2 or rows.ndim != 1 or vals.shape[0] != rows.shape[0]:
        raise ValueError(
            "pallas_segment_sum wants vals (N, D) with rows (N,), got "
            f"{vals.shape} / {rows.shape}."
        )
    n, d = vals.shape
    d_pad = _round_up(max(d, 1), 128)
    seg_pad = _round_up(max(num_segments, 1), 8)
    m, seg_tile = _tile_plan(d_pad, seg_pad)
    seg_pad = _round_up(seg_pad, seg_tile)
    block_n = m * 128
    n_pad = _round_up(max(n, 1), block_n)
    # pad with an out-of-range sentinel so padding matches no segment row
    # (negative rows likewise match no iota; rows in [num_segments,
    # seg_pad) land in dead padding rows sliced away below)
    rows_p = jnp.full((n_pad,), seg_pad, jnp.int32)
    vals_p = jnp.zeros((n_pad, d_pad), jnp.float32)
    if n:
        rows_p = rows_p.at[:n].set(rows.astype(jnp.int32))
        vals_p = vals_p.at[:n, :d].set(vals.astype(jnp.float32))
    rows_p = rows_p.reshape(n_pad // 128, 128)
    vals_p = vals_p.reshape(n_pad // 128, 128, d_pad)
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, seg_tile=seg_tile),
        grid=(seg_pad // seg_tile, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((m, 128), lambda j, i: (i, 0)),
            pl.BlockSpec((m, 128, d_pad), lambda j, i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((seg_tile, d_pad), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((seg_pad, d_pad), jnp.float32),
        interpret=interpret,
    )(rows_p, vals_p)
    return out[:num_segments, :d]


def _tail_lanes(shape) -> int:
    out = 1
    for s in shape[1:]:
        out *= int(s)
    return out


def _resolve_method(
    method: str, reduce: str, num_segments: int, vals, platform: str
) -> str:
    """The auto-pick, per backend: the kernel engages only where its
    exactness story holds (sum over <= 4-byte lanes, per-segment totals
    documented f32-exact to 2**24) and its O(N * segments) one-hot work is
    the winning trade (TPU, segment extent inside the envelope). Sharding
    shrinks the PER-SHARD extent, which is how million-cohort capacities
    re-enter this envelope."""
    if method != "auto":
        return method
    eligible = (
        reduce == "sum"
        and platform == "tpu"
        and num_segments <= _PALLAS_MAX_SEGMENTS
        and _tail_lanes(vals.shape) <= _MAX_TAIL_LANES
        and jnp.result_type(vals).itemsize <= 4
    )
    return "pallas" if eligible else "xla"


def _apply_local(vals, rows, num_segments, reduce, resolved, interpret):
    """One local (per-device or per-shard) segment reduction."""
    if resolved == "pallas":
        tail = vals.shape[1:]
        flat = vals.reshape(vals.shape[0], -1)
        out = pallas_segment_sum(
            flat, rows, num_segments, interpret=bool(interpret)
        )
        return out.reshape((num_segments,) + tail).astype(
            jnp.result_type(vals)
        )
    return _SEGMENT_OPS[reduce](vals, rows, num_segments=num_segments)


def _emit_obs(path: str, num_segments: int, vals, shards: int = 1) -> None:
    # counter semantics: one bump per program BUILD when called under a
    # trace (the steady window loop replays the compiled program), one per
    # call when used eagerly — i.e. it proves which path engaged, like
    # ops.topk.calls. The gauge is the capacity observable: resident state
    # bytes PER DEVICE for this scatter's segment extent (~1/shards of the
    # global extent on the sharded path — bench-asserted).
    _obs.counter("ops.scatter.calls", path=path)
    if _obs._enabled:
        per_device_rows = num_segments // max(shards, 1)
        _obs.gauge(
            "ops.scatter.state_bytes_per_device",
            float(
                per_device_rows
                * _tail_lanes(vals.shape)
                * jnp.result_type(vals).itemsize
            ),
            path=path,
        )


def segment_scatter(
    vals: jax.Array,
    rows: jax.Array,
    num_segments: int,
    *,
    reduce: str = "sum",
    method: str = "auto",
    interpret=None,
    mesh: Mesh = None,
    axis: str = None,
):
    """Reduce per-sample delta rows ``vals[i]`` into segment ``rows[i]`` of
    a leading ``[num_segments]`` axis — the ONE entry point the sliced fold
    scatters through, local or sharded.

    Without ``mesh``: ``jax.ops.segment_{sum,max,min}`` (``method="xla"``)
    or the VMEM-tiled kernel (``method="pallas"``, sum only; ``auto``
    engages it on TPU inside the documented envelope). With ``mesh`` +
    ``axis``: ONE ``shard_map`` enters with ``vals``/``rows`` replicated
    and returns the scatter result ``P(axis)``-sharded on its leading
    axis — shard ``s`` masks the row column into its block range
    ``[s*w, (s+1)*w)`` and reduces into its local ``(w, ...)`` tile, so no
    state-sized operand is ever gathered and the per-shard segment extent
    (what the kernel and the int32 index see) is ``num_segments/shards``.
    ``num_segments`` must divide evenly by the axis size (the sliced
    collection keeps its capacity a multiple of the shard count).

    ``interpret=None`` resolves per backend (interpret mode anywhere
    Mosaic isn't, i.e. off-TPU). Rows outside ``[0, num_segments)`` are
    dropped on every path.
    """
    if reduce not in _REDUCES:
        raise ValueError(f"reduce must be one of {_REDUCES}, got {reduce!r}.")
    if method not in _METHODS:
        raise ValueError(f"method must be one of {_METHODS}, got {method!r}.")
    if method == "pallas" and reduce != "sum":
        raise ValueError(
            "method='pallas' supports reduce='sum' only (the one-hot "
            f"contraction has no {reduce!r} form); use method='xla'."
        )
    if (mesh is None) != (axis is None):
        raise ValueError("mesh and axis must be passed together.")
    if mesh is None:
        platform = jax.default_backend()
        resolved = _resolve_method(method, reduce, num_segments, vals, platform)
        interp = (platform != "tpu") if interpret is None else bool(interpret)
        _emit_obs(resolved, num_segments, vals)
        return _apply_local(vals, rows, num_segments, reduce, resolved, interp)

    shards = int(mesh.shape[axis])
    if num_segments % shards:
        raise ValueError(
            f"num_segments {num_segments} is not a multiple of mesh axis "
            f"{axis!r} size {shards}: the block-range route needs equal "
            "per-shard tiles (the sliced collection rounds its capacity up)."
        )
    w = shard_tile_width(num_segments, shards)
    platform = mesh_platform_of(mesh)
    resolved = _resolve_method(method, reduce, w, vals, platform)
    interp = (platform != "tpu") if interpret is None else bool(interpret)
    _emit_obs("sharded", num_segments, vals, shards=shards)

    def body(rows_l, vals_l):
        s = jax.lax.axis_index(axis)
        local = rows_l.astype(jnp.int32) - s * w
        # rows owned by other shards leave [0, w); route them to an
        # explicit dead segment rather than leaning on scatter OOB modes
        # (negative indices would WRAP under gather-style clamping)
        local = jnp.where((local >= 0) & (local < w), local, w)
        out = _apply_local(vals_l, local, w + 1, reduce, resolved, interp)
        return out[:w]

    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(_P(), _P()),
        out_specs=_P(axis),
        **_SHARD_MAP_KWARGS,
    )(rows, vals)


# --------------------------------------------------------------- GSPMD rule
# ``pallas_call`` has no partitioning rule, so under GSPMD a sample-sharded
# operand would be all-gathered onto every device before the kernel runs.
# As with ``sharded_pallas_class_counts``: a segment SUM is a pure
# sample-axis reduction, so each shard runs the VMEM kernel on its local
# samples and the per-shard partials fold with one ``psum`` over exactly
# the mesh axes the sample axis is sharded on — sharded and unsharded
# callers share this one entry point and the partitioner supplies the rest.


def _sample_axes(sharding) -> tuple:
    spec = getattr(sharding, "spec", None)
    spec0 = spec[0] if spec else None
    if spec0 is None:
        return ()
    return tuple(spec0) if isinstance(spec0, tuple) else (spec0,)


def _seg_infer(num_segments, interpret, mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, _P())  # (num_segments, d): replicated


def _seg_partition(num_segments, interpret, mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding

    axes = _sample_axes(arg_shapes[0].sharding)
    arg_shardings = (
        NamedSharding(mesh, _P(axes if axes else None, None)),
        NamedSharding(mesh, _P(axes if axes else None)),
    )
    result_sharding = NamedSharding(mesh, _P())

    def lower_fn(vals, rows):
        local = pallas_segment_sum(
            vals, rows, num_segments, interpret=interpret
        )
        return jax.lax.psum(local, axes) if axes else local

    return mesh, lower_fn, result_sharding, arg_shardings


from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402


@functools.partial(custom_partitioning, static_argnums=(2, 3))
def sharded_pallas_segment_sum(vals, rows, num_segments, interpret=False):
    """``pallas_segment_sum`` with a GSPMD partitioning rule: on a mesh,
    each shard's deltas reduce in VMEM locally and the partials fold with
    one ``psum``; on one device it is exactly ``pallas_segment_sum``."""
    return pallas_segment_sum(vals, rows, num_segments, interpret=interpret)


# Shardy rule: the sample factor i is contracted on both operands; the
# segment-axis factor k and the lane factor j appear replicated in the
# result (the partition callback psums). Older jax predates Shardy — the
# GSPMD callbacks alone are the complete rule there.
_def_partition_kwargs = {}
if "sharding_rule" in inspect.signature(
    sharded_pallas_segment_sum.def_partition
).parameters:
    _def_partition_kwargs["sharding_rule"] = "i j, i -> k j"
sharded_pallas_segment_sum.def_partition(
    infer_sharding_from_operands=_seg_infer,
    partition=_seg_partition,
    **_def_partition_kwargs,
)

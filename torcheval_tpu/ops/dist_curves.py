"""Distributed exact curve reduction over a device mesh: bucket partition +
per-shard sort instead of XLA's gather-based sort partitioning.

The curve kernels (``ops/curves.py``) are one global descending sort plus
scans. XLA's SPMD partitioner handles a partitioned ``lax.sort`` by
all-gathering the operand and sorting the full array on every device
(``docs/distributed.md`` conceded this; SURVEY §7 names the 1B-across-chips
sort as the hard part). This module is the TPU-native fix — the classic
distributed sort recipe, expressed in ``shard_map`` so every step is explicit
and collective traffic is exactly one ``all_to_all`` over the sample rows:

1. **Order keys.** Scores become monotone u32 keys (:func:`_desc_key`):
   ascending key order == descending score order, equal scores == equal keys,
   every NaN (sample or padding sentinel) maps to the max key. Only keys and
   counts travel — curve values never need the f32 score again.
2. **Histogram splitters.** A 2^16-bin histogram over the keys' top 16 bits,
   ``psum``-reduced across the mesh (an all-reduce of a fixed 256 KiB —
   independent of sample count), yields K-quantile splitter bins, so every
   device receives ≈ 1/K of the rows regardless of the score distribution.
3. **Bucket exchange.** Each device sorts locally once, slices its rows into
   K contiguous per-destination buckets, pads each to a static capacity
   ``C = ceil(F·n_local/K)`` (``DIST_CAPACITY_FACTOR``), and one tiled
   ``lax.all_to_all`` delivers bucket *k* of every source to device *k* —
   each sample row crosses the ICI exactly once. Rows beyond a bucket's
   capacity (pathologically skewed distributions: massive ties on few
   values) are *counted* and the caller raises — never silently dropped.
4. **Per-shard merge + offset integration.** Each device now owns a disjoint
   descending score range: one local sort merges its ≤ K·C rows, tie groups
   are intra-shard by construction (equal keys share a bucket), and global
   cumulative TP/FP come from a per-device-totals all-reduce (K elements)
   turned into exclusive prefixes. Trapezoid (AUROC) and step (AUPRC)
   integrals decompose over shards exactly, so a final ``psum`` of scalar
   contributions finishes the job.

Reference behavior matched at mesh scale: the single-sort curve math of
``torcheval/metrics/functional/classification/auroc.py:50-67`` (and
``precision_recall_curve.py:207-230``), which the single-device kernels
already pin against sklearn.

**Multi-axis meshes.** Every kernel runs over ONE named mesh axis — the axis
the sample rows are sharded along — and that axis may be a *subset* of the
mesh (a ``(data, model)`` topology with rows sharded over ``data``). The
``shard_map`` collectives (``psum`` / ``all_to_all``) are bound to that axis
name only, so each slice along the remaining axes runs the same exchange
independently on replicated inputs and the result is replicated over them.
Kernel size K and the exchange capacity come from ``mesh.shape[axis]``, not
the total device count.

**Multiclass (one-vs-all).** :func:`sharded_multiclass_auroc` /
:func:`sharded_multiclass_auprc` reduce an ``(N, C)`` score cache without a
sample gather: per-class order keys and one-hot counts are built locally,
then the binary kernel body is ``vmap``-ed over the class axis. The
collectives batch under vmap — the per-column ``all_to_all`` becomes ONE
tiled collective carrying every class's buckets (a shared bucket exchange,
3 collectives total regardless of C), and the splitter/offset ``psum``
collectives carry ``(C, ...)`` operands. Per-class semantics match the reference's
one-vs-all curve math (``precision_recall_curve.py:207-230`` per class),
i.e. the fused ``multiclass_*_kernel`` path bit-for-bit on clean data.

**Quantized exchange (ISSUE 12, EQuARX-shaped).** With quantization on
(``quantize=True`` on the public kernels, or
``TORCHEVAL_TPU_SYNC_QUANTIZE=1``), the wire shrinks on both collective
legs without adding a single collective:

* the **count columns** of the bucket exchange ride the ``all_to_all`` as
  **int8** instead of int32 — unit counts (binary tp/fp, multiclass
  one-hot rows) are exactly representable, and the merge step's
  ``cumsum(..., dtype=int32)`` widens BEFORE any accumulation, so results
  stay bit-exact (widened accumulation). 12 bytes/row becomes 6;
* the **splitter histogram** all-reduce runs in **bf16** instead of int32
  (half the fixed 256 KiB round). Bin counts above 256 round, which can
  only nudge splitter placement — splitters balance load, they never
  affect values (equal keys still share a bucket; a pathologically
  degraded split can only trip the existing capacity-overflow error
  channel, which falls back to the fused path exactly as before).

Collective structure is unchanged — same 3 ``all_to_all`` transfers, same
``psum`` count, still batched O(1) in the class count under ``vmap``
(HLO-asserted in ``tests/ops/test_dist_curves.py``).

**NaN scores fail loudly.** ``_desc_key`` maps every NaN to the max key, so
a NaN-scored *sample* would sort last and merge into one tie group with the
padding — silently diverging from the fused raw-sample kernels, whose
descending sort places NaN first with each NaN its own tie group (XLA total
order, matching ``torch.sort``). Rather than diverge, the kernels count
NaN-keyed real rows into the returned error channel alongside capacity
overflow: callers see a nonzero count, discard the value, and fall back to
the fused-sort program — whose NaN semantics match the unsharded path
exactly. (Summary-row padding never reaches these kernels; the raw cache
carries real samples only.)
"""

from __future__ import annotations

import functools
import inspect
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs.recompile import watched_jit
from torcheval_tpu.utils.quant import Q8_BLOCK, sync_quantize_mode

# older shard_map's replication checker false-positives on the kernels' scan
# carries (jax <= 0.4.x: "Scan carry input and output got mismatched
# replication types"); disable it where the knob exists — newer jax dropped
# the parameter along with the checker
_SHARD_MAP_KWARGS = (
    {"check_rep": False}
    if "check_rep" in inspect.signature(shard_map).parameters
    else {}
)

# splitter histogram bins: top 16 bits of the order key
_HIST_BINS = 1 << 16
# per-(source, destination) send capacity is ceil(F * n_local / K); under an
# exchangeable row-to-shard assignment the expected load is n_local / K, so
# F=4 absorbs heavy skew while keeping the exchange payload 4x the minimum.
# Overflow is detected exactly and raised by the caller, never dropped.
DIST_CAPACITY_FACTOR = 4

_PAD_KEY = jnp.uint32(0xFFFFFFFF)


def _bucket_capacity(n_local: int, k_devices: int) -> int:
    """Static per-(source, destination) send capacity - ONE definition,
    shared by the traced kernels (via ``_program``) and the obs
    accounting (``_accounted_call``) so the reported exchange bytes
    can never drift from what the kernel actually allocates."""
    return max(1, -(-DIST_CAPACITY_FACTOR * n_local // k_devices))


def _desc_key(s: jax.Array) -> jax.Array:
    """Monotone u32 order key: ascending keys == descending scores; equal
    scores == equal keys; all NaNs (samples and the padding sentinel alike)
    map to the max key so they sort last and bucket together."""
    # canonicalize -0.0 -> +0.0 first: the zeros compare float-equal, so
    # they must share ONE key — distinct keys would split the tie group (and
    # possibly the bucket), silently changing the result vs the
    # float-comparing fused path. where(), not `s + 0.0`: XLA's algebraic
    # simplifier folds add(x, 0) away under jit, sign bit and all.
    s = s.astype(jnp.float32)
    s = jnp.where(s == 0, jnp.float32(0.0), s)
    b = jax.lax.bitcast_convert_type(s, jnp.uint32)
    asc = jnp.where(
        jax.lax.shift_right_logical(b, jnp.uint32(31)) == jnp.uint32(1),
        ~b,
        b | jnp.uint32(0x80000000),
    )
    return jnp.where(jnp.isnan(s), _PAD_KEY, ~asc)


def _q8_blocks(x: jax.Array):
    """Per-:data:`Q8_BLOCK` int8 quantization of a 1-D f32 array (the
    device-side twin of ``utils/quant.q8_parts``): ``(scales, int8)``.
    Requires ``x.shape[-1] % Q8_BLOCK == 0`` (callers guarantee it)."""
    blocks = x.shape[-1] // Q8_BLOCK
    b = x.reshape(blocks, Q8_BLOCK)
    scales = jnp.max(jnp.abs(b), axis=1) / 127.0
    safe = jnp.where(scales == 0.0, jnp.float32(1.0), scales)
    q = jnp.clip(jnp.round(b / safe[:, None]), -127, 127).astype(jnp.int8)
    return scales, q.reshape(-1)


def _qpsum_i8(hist: jax.Array, axis: str, k_devices: int) -> jax.Array:
    """EQuARX-shaped int8-chunked reduce-scatter/all-gather psum of the
    splitter histogram (ROADMAP 1(b)): each leg moves 1 byte/bin instead
    of the int32 psum's 4 (the bf16 psum's halving becomes a quartering)
    at the cost of two SMALL scale collectives (~1.6% of the int8 bytes).

    Structure (all collectives batch under the multiclass ``vmap`` exactly
    like the bucket exchange): quantize the local histogram to int8 blocks
    + f32 scales; one tiled ``all_to_all`` lands every rank's copy of MY
    1/K shard here (+ its scales); dequantize per source, sum in f32 —
    the reduce-scatter leg; re-quantize the reduced shard and ``all_gather``
    it (+ scales) — the all-gather leg. Quantization error is bounded per
    element by ``max|block|/254`` per leg, which can only nudge splitter
    placement — splitters balance load, never values (module doc)."""
    h = hist.shape[-1]
    scales, q = _q8_blocks(hist.astype(jnp.float32))
    q_r = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    sc_r = jax.lax.all_to_all(
        scales, axis, split_axis=0, concat_axis=0, tiled=True
    )
    shard = h // k_devices
    part = q_r.reshape(k_devices, shard // Q8_BLOCK, Q8_BLOCK).astype(
        jnp.float32
    ) * sc_r.reshape(k_devices, shard // Q8_BLOCK, 1)
    reduced = jnp.sum(part, axis=0).reshape(shard)
    sc2, q2 = _q8_blocks(reduced)
    q_g = jax.lax.all_gather(q2, axis, tiled=True)
    sc_g = jax.lax.all_gather(sc2, axis, tiled=True)
    return (
        q_g.reshape(h // Q8_BLOCK, Q8_BLOCK).astype(jnp.float32)
        * sc_g.reshape(h // Q8_BLOCK, 1)
    ).reshape(h)


def _splitter_buckets(
    key: jax.Array, axis: str, k_devices: int, quantize=False
):
    """Per-row destination bucket ids from global histogram splitters.

    The histogram is over the key's top 16 bits; the psum makes it global.
    Quantile targets are computed in f32 — splitters need only balance the
    load, not be exact quantiles. Equal keys always get equal buckets (the
    tie-locality invariant the merge step relies on). ``quantize`` is the
    resolved mode: ``"bf16"`` runs the all-reduce in bf16 (half the fixed
    payload; counts above 256 round), ``"int8"`` runs the chunked qpsum
    (:func:`_qpsum_i8`, quarter the payload) when the bin count divides
    into per-rank Q8 blocks, else falls back to bf16. Either can only
    shift splitter placement, never results (module doc, "Quantized
    exchange")."""
    t = jax.lax.shift_right_logical(key, jnp.uint32(16)).astype(jnp.int32)
    hist = jax.ops.segment_sum(
        jnp.ones_like(t, dtype=jnp.int32),
        t,
        num_segments=_HIST_BINS,
        indices_are_sorted=False,
    )
    if quantize == "int8" and _HIST_BINS % (k_devices * Q8_BLOCK) == 0:
        cum = jnp.cumsum(_qpsum_i8(hist, axis, k_devices))
    elif quantize:
        hist = jax.lax.psum(hist.astype(jnp.bfloat16), axis)
        cum = jnp.cumsum(hist.astype(jnp.float32))
    else:
        hist = jax.lax.psum(hist, axis)
        cum = jnp.cumsum(hist).astype(jnp.float32)
    total = cum[-1]
    targets = total * (
        jnp.arange(1, k_devices, dtype=jnp.float32) / float(k_devices)
    )
    # boundary bins: first bin whose cumulative count reaches each target
    boundaries = jnp.searchsorted(cum, targets, side="left").astype(jnp.int32)
    bucket = jnp.searchsorted(boundaries, t, side="right").astype(jnp.int32)
    return bucket, t


def _exchange(
    cols: Tuple[jax.Array, ...],
    key: jax.Array,
    axis: str,
    k_devices: int,
    capacity: int,
    quantize: bool = False,
):
    """Local sort → per-destination bucket slices (padded to ``capacity``)
    → one tiled all_to_all per column. Returns the received columns (first
    one is the key) and the exact count of rows lost to capacity overflow.

    ``quantize`` ships the count columns as int8 — exact for the unit
    counts every caller passes, and the merge step widens before any
    cumulative sum — so the exchange payload halves with bit-identical
    results (module doc, "Quantized exchange")."""
    if quantize:
        cols = tuple(c.astype(jnp.int8) for c in cols)
    skey, *scols = jax.lax.sort((key, *cols), num_keys=1)
    bucket, _ = _splitter_buckets(skey, axis, k_devices, quantize)
    cnt = jax.ops.segment_sum(
        jnp.ones_like(bucket), bucket, num_segments=k_devices,
        indices_are_sorted=True,
    )
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(cnt)[:-1]]
    )
    sent = jnp.minimum(cnt, capacity)
    overflow = jnp.sum(jnp.maximum(cnt - capacity, 0))
    # pad so a window never clamps back into a neighbouring bucket
    key_p = jnp.concatenate([skey, jnp.full((capacity,), _PAD_KEY)])
    cols_p = [
        jnp.concatenate([c, jnp.zeros((capacity,), c.dtype)]) for c in scols
    ]
    lane = jnp.arange(capacity, dtype=jnp.int32)

    def _windows(arr, pad_value):
        parts = []
        for k in range(k_devices):  # k_devices is static (mesh size)
            w = jax.lax.dynamic_slice(arr, (starts[k],), (capacity,))
            parts.append(jnp.where(lane < sent[k], w, pad_value))
        return jnp.concatenate(parts)

    send = [_windows(key_p, _PAD_KEY)] + [
        _windows(c, jnp.zeros((), c.dtype)) for c in cols_p
    ]
    recv = [
        jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        for x in send
    ]
    return recv, overflow


def _merged_shard(recv_key, recv_tp, recv_fp, axis: str, k_devices: int):
    """Sort the received rows (this shard's value range), compute local
    cumulative counts and global offsets (exclusive prefix of per-shard
    totals via a K-element all-reduce — no sample gather)."""
    key, tp, fp = jax.lax.sort((recv_key, recv_tp, recv_fp), num_keys=1)
    ctp = jnp.cumsum(tp, dtype=jnp.int32)
    cfp = jnp.cumsum(fp, dtype=jnp.int32)
    last = jnp.concatenate([key[1:] != key[:-1], jnp.ones((1,), bool)])
    idx = jax.lax.axis_index(axis)
    onehot = (jnp.arange(k_devices, dtype=jnp.int32) == idx).astype(jnp.int32)
    all_tp = jax.lax.psum(onehot * ctp[-1], axis)  # (K,) per-shard totals
    all_fp = jax.lax.psum(onehot * cfp[-1], axis)
    prevmask = jnp.arange(k_devices, dtype=jnp.int32) < idx
    tp_off = jnp.sum(jnp.where(prevmask, all_tp, 0))
    fp_off = jnp.sum(jnp.where(prevmask, all_fp, 0))
    total_tp = jnp.sum(all_tp)
    total_fp = jnp.sum(all_fp)
    return ctp, cfp, last, tp_off, fp_off, total_tp, total_fp


def _concat_unit_counts(s_list, t_list):
    """Raw sample cache entries → (key, tp, fp) local columns (unit
    counts), concatenated INSIDE the shard so no resharding collective is
    ever needed: every entry arrives as its own local block. Also returns
    the local NaN-keyed row count — real samples whose score is NaN would
    silently take the *padding* sort position (module docstring), so they
    are surfaced through the error channel instead."""
    s = jnp.concatenate(s_list)
    t = jnp.concatenate(t_list).astype(jnp.int32)
    key = _desc_key(s)
    nan_rows = jnp.sum((key == _PAD_KEY).astype(jnp.int32))
    return key, t, 1 - t, nan_rows


def _auroc_body(key, tp, fp, *, axis, k_devices, capacity, quantize=False):
    """Bucket exchange + per-shard merge + offset trapezoid for ONE binary
    problem's (key, tp, fp) columns. Returns ``(value, local_overflow)``.
    The multiclass kernels ``vmap`` this over a leading class axis: the
    collectives batch (one tiled all_to_all per column carries every class's
    buckets), so C classes cost the same number of collective rounds as one."""
    recv, overflow = _exchange(
        (tp, fp), key, axis, k_devices, capacity, quantize
    )
    ctp, cfp, last, tp_off, fp_off, p_tot, n_tot = _merged_shard(
        *recv, axis, k_devices
    )
    big = jnp.iinfo(jnp.int32).max
    # group-end propagation: intra-group points coincide with the group end,
    # giving zero-width trapezoid segments (ops/curves.py invariant)
    tp_end = jax.lax.cummin(jnp.where(last, ctp, big), reverse=True)
    fp_end = jax.lax.cummin(jnp.where(last, cfp, big), reverse=True)
    tp_pts = jnp.concatenate(
        [tp_off[None], tp_off + tp_end]
    ).astype(jnp.float32)
    fp_pts = jnp.concatenate(
        [fp_off[None], fp_off + fp_end]
    ).astype(jnp.float32)
    auc = jax.lax.psum(jnp.trapezoid(tp_pts, fp_pts), axis)
    factor = p_tot.astype(jnp.float32) * n_tot.astype(jnp.float32)
    value = jnp.where(factor == 0, 0.5, auc / jnp.maximum(factor, 1.0))
    return value, overflow


def _auprc_body(key, tp, fp, *, axis, k_devices, capacity, quantize=False):
    """:func:`_auroc_body`'s average-precision (step integral) twin."""
    recv, overflow = _exchange(
        (tp, fp), key, axis, k_devices, capacity, quantize
    )
    ctp, cfp, last, tp_off, fp_off, p_tot, _ = _merged_shard(
        *recv, axis, k_devices
    )
    # per-group TP delta: cumulative at this group end minus the previous
    # group's end (shifted cummax of end-masked cumsum — ops/summary.py)
    prev_tp = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jax.lax.cummax(jnp.where(last, ctp, 0))[:-1]]
    )
    delta_tp = jnp.where(last, ctp - prev_tp, 0).astype(jnp.float32)
    ctp_g = (tp_off + ctp).astype(jnp.float32)
    cfp_g = (fp_off + cfp).astype(jnp.float32)
    prec = ctp_g / jnp.maximum(ctp_g + cfp_g, 1.0)
    ap = jax.lax.psum(jnp.sum(delta_tp * prec), axis)
    total = p_tot.astype(jnp.float32)
    value = jnp.where(total == 0, 0.0, ap / jnp.maximum(total, 1.0))
    return value, overflow


def _auroc_kernel(s_list, t_list, *, axis, k_devices, capacity, quantize):
    key, tp, fp, nan_rows = _concat_unit_counts(s_list, t_list)
    value, overflow = _auroc_body(
        key, tp, fp, axis=axis, k_devices=k_devices, capacity=capacity,
        quantize=quantize,
    )
    return value, jax.lax.psum(overflow + nan_rows, axis)


def _auprc_kernel(s_list, t_list, *, axis, k_devices, capacity, quantize):
    key, tp, fp, nan_rows = _concat_unit_counts(s_list, t_list)
    value, overflow = _auprc_body(
        key, tp, fp, axis=axis, k_devices=k_devices, capacity=capacity,
        quantize=quantize,
    )
    return value, jax.lax.psum(overflow + nan_rows, axis)


def _mc_class_columns(s_list, t_list):
    """Multiclass raw cache entries → per-class (key, tp, fp) column sets
    with a leading class axis: ``(N_i, C)`` score blocks concatenate locally
    (no resharding collective), keys transpose to ``(C, n_local)``, integer
    labels expand to one-vs-all unit counts. Also returns the local count of
    NaN-keyed per-class score ENTRIES (one bad row can contribute up to C)
    for the error channel — same loud-NaN contract as the binary kernels."""
    x = jnp.concatenate(s_list, axis=0)  # (n_local, C)
    lbl = jnp.concatenate(t_list).astype(jnp.int32)
    key = _desc_key(x.T)  # (C, n_local)
    num_classes = x.shape[1]
    onehot = (
        lbl[None, :] == jnp.arange(num_classes, dtype=jnp.int32)[:, None]
    ).astype(jnp.int32)
    nan_entries = jnp.sum((key == _PAD_KEY).astype(jnp.int32))
    return key, onehot, 1 - onehot, nan_entries


def _make_mc_kernel(body):
    """One-vs-all multiclass kernel from a binary body: ``vmap`` over the
    class axis with a SHARED bucket exchange — vmap's collective batching
    rules turn the body's per-column ``all_to_all`` into a single tiled
    collective over ``(C, K·capacity)`` operands and its ``psum`` into one
    ``(C, ...)`` all-reduce, so the collective-round count is independent of
    the class count."""

    def kern(s_list, t_list, *, axis, k_devices, capacity, quantize):
        key, tp, fp, nan_entries = _mc_class_columns(s_list, t_list)
        values, overflows = jax.vmap(
            functools.partial(
                body, axis=axis, k_devices=k_devices, capacity=capacity,
                quantize=quantize,
            )
        )(key, tp, fp)
        return values, jax.lax.psum(jnp.sum(overflows) + nan_entries, axis)

    return kern


_KERNELS = {
    "auroc": _auroc_kernel,
    "auprc": _auprc_kernel,
    "mc_auroc": _make_mc_kernel(_auroc_body),
    "mc_auprc": _make_mc_kernel(_auprc_body),
}


@functools.lru_cache(maxsize=None)
def _program(mesh: Mesh, axis: str, which: str, quantize=False):
    """Jitted shard_map program per (mesh, axis, metric); jit handles
    shape-based caching beneath. Capacity is static per trace (derived from
    the local row count). ``axis`` may be a subset of a multi-axis mesh: the
    kernel is sized from ``mesh.shape[axis]``, its collectives are bound to
    that axis name only, and the out_spec replicates the scalar results over
    the remaining axes (each slice computes them identically on replicated
    inputs)."""
    k_devices = int(mesh.shape[axis])
    kern = _KERNELS[which]

    def impl(s_list, t_list):
        n_local = sum(int(s.shape[0]) for s in s_list) // k_devices
        capacity = _bucket_capacity(n_local, k_devices)
        f = functools.partial(
            kern, axis=axis, k_devices=k_devices, capacity=int(capacity),
            quantize=quantize,
        )
        return shard_map(
            f,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(), P()),
            **_SHARD_MAP_KWARGS,
        )(s_list, t_list)

    suffix = {"bf16": "_q8", "int8": "_q8i8"}.get(quantize, "")
    return watched_jit(impl, name=f"dist_curves.{which}{suffix}")


def _accounted_call(
    which: str,
    s_list,
    t_list,
    mesh: Mesh,
    axis: str,
    quantize=None,  # None=env | False | True/"bf16" | "int8"
):
    """Dispatch the distributed program with collective accounting: one
    all_to_all exchange per call, whose per-device send payload is derived
    from the same static capacity formula the kernel uses (3 i32/u32
    columns of ``k_devices * capacity`` rows, times the class count for the
    multiclass kernels' shared exchange). Wall time is the host-side
    dispatch span — the collectives themselves run inside the compiled
    program and are attributed by the XLA profiler via the entry point's
    ``named_scope``. ``quantize`` resolves the per-call override against
    TORCHEVAL_TPU_SYNC_QUANTIZE (the same knob the metric-sync wire
    reads; ``"int8"`` — per call or in the env — additionally swaps the
    splitter-histogram psum for the chunked int8 qpsum, ROADMAP 1(b)) and
    is part of the compiled-program cache key."""
    quantize = sync_quantize_mode(quantize)
    program = _program(mesh, axis, which, quantize)
    s_list, t_list = list(s_list), list(t_list)
    if not _obs.enabled():
        return program(s_list, t_list)
    k = int(mesh.shape[axis])
    n_local = sum(int(s.shape[0]) for s in s_list) // k
    capacity = _bucket_capacity(n_local, k)
    n_cols = int(s_list[0].shape[1]) if s_list[0].ndim == 2 else 1
    codec = {"bf16": "q8", "int8": "q8i8"}.get(quantize, "raw")
    with _obs.span(f"ops.dist_curves.{which}"):
        out = program(s_list, t_list)
    _obs.counter("dist_curves.exchanges", kernel=which, codec=codec)
    # bytes entering the all_to_all per device: key + tp + fp columns
    # (u32 key always; int8 counts under the quantized exchange)
    row_bytes = 4 + 2 * (1 if quantize else 4)
    _obs.counter(
        "dist_curves.exchange_send_bytes",
        row_bytes * k * capacity * n_cols,
        kernel=which,
        codec=codec,
    )
    # participating devices = the sharded axis's extent, not the mesh size:
    # remaining mesh axes replicate the exchange, they don't join it
    _obs.gauge("dist_curves.world_size", k)
    return out


def sharded_binary_auroc(
    s_list: List[jax.Array],
    t_list: List[jax.Array],
    *,
    mesh: Mesh,
    axis: str = "data",
    quantize=None,  # None=env | False | True/"bf16" | "int8"
) -> Tuple[jax.Array, jax.Array]:
    """Exact AUROC over a mesh-sharded raw sample cache without gathering
    the samples. Returns ``(value, error_rows)`` — a nonzero count means
    the score distribution overloaded a bucket past the send capacity OR
    the cache holds NaN-scored rows (whose sort position here would diverge
    from the fused kernels'; module docstring); either way the value is
    untrustworthy and callers must raise or fall back. ``quantize``
    engages the int8/bf16 exchange (module doc, "Quantized exchange");
    ``None`` defers to TORCHEVAL_TPU_SYNC_QUANTIZE."""
    return _accounted_call("auroc", s_list, t_list, mesh, axis, quantize)


def sharded_binary_auprc(
    s_list: List[jax.Array],
    t_list: List[jax.Array],
    *,
    mesh: Mesh,
    axis: str = "data",
    quantize=None,  # None=env | False | True/"bf16" | "int8"
) -> Tuple[jax.Array, jax.Array]:
    """Exact average precision over a mesh-sharded raw cache; see
    :func:`sharded_binary_auroc` for the error-channel and ``quantize``
    contracts."""
    return _accounted_call("auprc", s_list, t_list, mesh, axis, quantize)


def sharded_multiclass_auroc(
    s_list: List[jax.Array],
    t_list: List[jax.Array],
    *,
    mesh: Mesh,
    axis: str = "data",
    quantize=None,  # None=env | False | True/"bf16" | "int8"
) -> Tuple[jax.Array, jax.Array]:
    """Exact one-vs-all per-class AUROC over a mesh-sharded raw multiclass
    cache (``(N_i, C)`` score blocks + ``(N_i,)`` integer labels, every
    block sharded along ``axis``) without gathering the samples. Returns
    ``((C,) per-class values, error_rows)`` — same error-channel contract
    as :func:`sharded_binary_auroc` (bucket overflow in any class, or
    NaN-scored per-class entries, make the values untrustworthy; fall back
    to the fused one-vs-all program); ``quantize`` as there — the shared
    exchange stays 3 collectives, just narrower."""
    return _accounted_call("mc_auroc", s_list, t_list, mesh, axis, quantize)


def sharded_multiclass_auprc(
    s_list: List[jax.Array],
    t_list: List[jax.Array],
    *,
    mesh: Mesh,
    axis: str = "data",
    quantize=None,  # None=env | False | True/"bf16" | "int8"
) -> Tuple[jax.Array, jax.Array]:
    """Exact one-vs-all per-class average precision over a mesh-sharded raw
    multiclass cache; see :func:`sharded_multiclass_auroc`."""
    return _accounted_call("mc_auprc", s_list, t_list, mesh, axis, quantize)


# ------------------------------------------------------ resident sketch path
# ISSUE 13(c): approx-mode curve metrics hold their state AS a histogram
# (``torcheval_tpu.sketch``), so the distributed reduction degenerates from
# the 3-collective bucket exchange to ONE psum of fixed-size count arrays —
# the resident histogram is consumed directly, with no re-bucketing pass and
# no per-sample traffic at all. Exactness note: the sketch psum is NEVER
# quantized — bucket counts are the metric state itself (bucket add must be
# exact), unlike the splitter histogram above, which only balances load.
@functools.lru_cache(maxsize=None)
def _sketch_program(
    mesh: Mesh, axis: str, bucket_bits: int, num_classes: Optional[int]
):
    from torcheval_tpu.sketch.histogram import (
        mc_score_hist_fold,
        score_hist_fold,
    )

    def impl(s_list, t_list):
        def kern(s_l, t_l):
            if num_classes is None:
                tp, fp, nan = score_hist_fold(
                    jnp.concatenate(s_l), jnp.concatenate(t_l), bucket_bits
                )
            else:
                tp, fp, nan = mc_score_hist_fold(
                    jnp.concatenate(s_l, axis=0),
                    jnp.concatenate(t_l),
                    bucket_bits,
                    num_classes,
                )
            return (
                jax.lax.psum(tp, axis),
                jax.lax.psum(fp, axis),
                jax.lax.psum(nan, axis),
            )

        return shard_map(
            kern,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(), P(), P()),
            **_SHARD_MAP_KWARGS,
        )(s_list, t_list)

    return watched_jit(impl, name="dist_curves.sketch_fold")


def sharded_sketch_counts(
    s_list: List[jax.Array],
    t_list: List[jax.Array],
    *,
    mesh: Mesh,
    axis: str = "data",
    bucket_bits: int,
    num_classes: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fold a mesh-sharded raw staging cache straight into GLOBAL sketch
    histograms: per-shard ``segment_sum`` + one exact int32 ``psum`` round
    — no sample ever crosses the ICI. Returns replicated
    ``(tp, fp, nan_count)`` (``(B,)`` binary / ``(C, B)`` one-vs-all with
    ``num_classes``); the caller bucket-adds them into its resident state.
    Unlike the exact kernels there is no overflow error channel — the
    histogram is fixed-size by construction."""
    program = _sketch_program(mesh, str(axis), bucket_bits, num_classes)
    s_list, t_list = list(s_list), list(t_list)
    if not _obs.enabled():
        return program(s_list, t_list)
    k = int(mesh.shape[axis])
    with _obs.span("ops.dist_curves.sketch_fold"):
        out = program(s_list, t_list)
    family = "binary" if num_classes is None else "multiclass"
    _obs.counter("dist_curves.sketch_folds", family=family)
    _obs.gauge("dist_curves.world_size", k)
    return out

from torcheval_tpu.ops.confusion import (
    class_counts,
    confusion_matrix_counts,
    topk_onehot,
)
from torcheval_tpu.ops.curves import (
    binary_auprc_kernel,
    binary_auroc_kernel,
    multiclass_prc_points_kernel,
    prc_points_kernel,
)

__all__ = [
    "binary_auprc_kernel",
    "binary_auroc_kernel",
    "class_counts",
    "confusion_matrix_counts",
    "multiclass_prc_points_kernel",
    "prc_points_kernel",
    "topk_onehot",
]

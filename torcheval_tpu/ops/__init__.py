from torcheval_tpu.ops.confusion import (
    class_counts,
    confusion_matrix_counts,
    topk_onehot,
)

__all__ = [
    "class_counts",
    "confusion_matrix_counts",
    "topk_onehot",
]

from torcheval_tpu.ops.confusion import (
    class_counts,
    confusion_matrix_counts,
    topk_onehot,
)
from torcheval_tpu.ops.curves import (
    binary_auprc_kernel,
    binary_auroc_kernel,
    multiclass_prc_points_kernel,
    prc_points_kernel,
)
from torcheval_tpu.ops.scatter import (
    pallas_segment_sum,
    segment_scatter,
    sharded_pallas_segment_sum,
)
from torcheval_tpu.ops.topk import (
    label_sharding_of,
    pallas_topk,
    prune_topk,
    sharded_label_topk,
    topk,
    topk_indices,
    topk_values,
)

__all__ = [
    "binary_auprc_kernel",
    "binary_auroc_kernel",
    "class_counts",
    "confusion_matrix_counts",
    "label_sharding_of",
    "multiclass_prc_points_kernel",
    "pallas_segment_sum",
    "pallas_topk",
    "prc_points_kernel",
    "prune_topk",
    "segment_scatter",
    "sharded_label_topk",
    "sharded_pallas_segment_sum",
    "topk",
    "topk_indices",
    "topk_onehot",
    "topk_values",
]

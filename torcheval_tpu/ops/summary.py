"""Threshold-summary compaction: the bounded-memory path to 1B-sample curves.

The reference's AUROC/PRC metrics cache every sample and sort once at compute
(``torcheval/metrics/classification/auroc.py:55-71``) — at 1B predictions the
cache alone is ~8 GB and the sort workspace more, beyond a single chip's HBM.
But the *sufficient statistic* for every threshold-curve metric is far
smaller: per unique score, the aggregated (tp_count, fp_count). float32
scores have at most 2^24 distinct values in any unit range, so a summary of
(score, tp, fp) rows is bounded at ~200 MB regardless of sample count — and
it is **exact**, not a binned approximation: feeding summary rows to the
weighted curve kernels (``ops/curves.py``) reproduces the raw-sample result
bit-for-bit because tied scores collapse into one cumsum step either way.

The compaction kernel keeps **static shapes** (SURVEY §7 "variable-length
results under jit"): input rows in, same-length rows out, with unique entries
compacted to the front (sorted descending) and padding rows
(``score == -inf``, zero counts) pushed to the end. Callers round buffer
capacities to powers of two so XLA compiles a handful of shapes, not one per
chunk size.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# NaN, not -inf: XLA's sort totally orders NaNs after every real float, so
# padding lands behind genuine scores INCLUDING -inf (a legal score, e.g.
# log(0) log-probs). NaN also never equals anything, so padding rows can
# never merge into a real tie group. NaN scores are thereby reserved: a NaN
# model output would be meaningless to rank anyway.
PAD_SCORE = jnp.nan


@jax.jit
def compact_counts(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Merge rows with tied scores into one (score, Σtp, Σfp) row each.

    Returns ``(scores, tp, fp, n_unique, nan_dropped)`` with arrays of the
    same static length as the input: unique rows first in descending score
    order, then ``(NaN, 0, 0)`` padding. ``n_unique`` counts rows carrying a
    nonzero count (existing padding and zero-count groups compact back into
    padding). ``nan_dropped`` counts sample rows whose score was NaN — those
    are indistinguishable from padding and excluded from the output; callers
    must fail loudly when it is nonzero rather than silently change the
    denominator.

    Counts are int32: exact while the stream's TOTAL positives and negatives
    each stay below 2^31 (~2.1e9); beyond that the cumsums in here and in
    ``ops/curves.py`` would wrap. The 1B north star fits; document-level
    guard, not runtime-checked.

    Two sorts + two log-depth scans, no gathers/scatters: sort descending
    carrying the counts, per-group delta via shifted cummax of group-end
    cumsums, then a second sort on the masked keys pushes non-end rows (keyed
    ``NaN``) behind the compacted entries.
    """
    tp_w = tp_w.astype(jnp.int32)
    fp_w = fp_w.astype(jnp.int32)
    neg, tp_c, fp_c = jax.lax.sort((-scores, tp_w, fp_w), num_keys=1)
    s = -neg
    n = s.shape[0]
    if n == 0:
        zero = jnp.zeros((0,), jnp.int32)
        zs = jnp.asarray(0, jnp.int32)
        return s, zero, zero, zs, zs
    ctp = jnp.cumsum(tp_c, dtype=jnp.int32)
    cfp = jnp.cumsum(fp_c, dtype=jnp.int32)
    last = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    # cumulative count at the end of the PREVIOUS tie group: inclusive cummax
    # of the group-end-masked cumsum, shifted right one (cumsums are
    # nondecreasing and >= 0, so 0 is a neutral mask fill)
    prev_tp = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jax.lax.cummax(jnp.where(last, ctp, 0))[:-1]]
    )
    prev_fp = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jax.lax.cummax(jnp.where(last, cfp, 0))[:-1]]
    )
    delta_tp = jnp.where(last, ctp - prev_tp, 0)
    delta_fp = jnp.where(last, cfp - prev_fp, 0)
    # a group whose delta is all-zero is padding (or contributes nothing);
    # key it NaN so it joins the padding block in the second sort
    real = last & ((delta_tp > 0) | (delta_fp > 0))
    # a NaN-scored SAMPLE (garbage model output) is indistinguishable from
    # padding in the second sort and would be silently dropped; count its
    # rows so the caller can fail loudly instead (one extra fused reduction)
    nan_dropped = jnp.sum(
        jnp.where(real & jnp.isnan(s), delta_tp + delta_fp, 0), dtype=jnp.int32
    )
    keep = real & ~jnp.isnan(s)
    key = jnp.where(keep, s, PAD_SCORE)
    # zero the counts of every non-kept row BEFORE they ride the second sort:
    # a NaN-scored sample's deltas would otherwise survive in the padding
    # block of the stored summary, re-counting into nan_dropped at every
    # later compaction and leaking into the curve totals (round-3 review)
    delta_tp = jnp.where(keep, delta_tp, 0)
    delta_fp = jnp.where(keep, delta_fp, 0)
    neg2, tp_out, fp_out = jax.lax.sort((-key, delta_tp, delta_fp), num_keys=1)
    return -neg2, tp_out, fp_out, jnp.sum(keep.astype(jnp.int32)), nan_dropped

"""Threshold-summary compaction: the bounded-memory path to 1B-sample curves.

The reference's AUROC/PRC metrics cache every sample and sort once at compute
(``torcheval/metrics/classification/auroc.py:55-71``) — at 1B predictions the
cache alone is ~8 GB and the sort workspace more, beyond a single chip's HBM.
But the *sufficient statistic* for every threshold-curve metric is far
smaller: per unique score, the aggregated (tp_count, fp_count). The summary
of (score, tp, fp) rows is bounded by the stream's score CARDINALITY, not
its sample count — model heads emit far fewer distinct values than samples
(a bf16 pipeline at most 2^16; float32 worst case over [0, 1) is ~2^30) —
and it is **exact**, not a binned approximation: feeding summary rows to the
weighted curve kernels (``ops/curves.py``) reproduces the raw-sample result
bit-for-bit because tied scores collapse into one cumsum step either way.

The compaction kernel keeps **static shapes** (SURVEY §7 "variable-length
results under jit"): input rows in, same-length rows out, with unique entries
compacted to the front (sorted descending) and padding rows
(``score == -inf``, zero counts) pushed to the end. Callers round buffer
capacities to powers of two so XLA compiles a handful of shapes, not one per
chunk size.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# NaN, not -inf: XLA's sort totally orders NaNs after every real float, so
# padding lands behind genuine scores INCLUDING -inf (a legal score, e.g.
# log(0) log-probs). NaN also never equals anything, so padding rows can
# never merge into a real tie group. NaN scores are thereby reserved: a NaN
# model output would be meaningless to rank anyway.
PAD_SCORE = jnp.nan


@jax.jit
def group_deltas_sorted(
    s: jax.Array, tp_c: jax.Array, fp_c: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-tie-group count aggregation over a stream ALREADY sorted
    descending (XLA total order: NaN-keyed padding last).

    Returns ``(delta_tp, delta_fp, keep, nan_dropped)``: summed counts
    placed at each group's END row (zeros elsewhere), ``keep`` marking
    group-end rows that carry a nonzero count and a non-NaN score, and
    ``nan_dropped`` counting samples whose score was NaN (their counts are
    zeroed in the deltas). This is the scan stage of :func:`compact_counts`,
    shared with the streaming-compaction pipeline
    (``ops/stream_compact.py``) that replaces the second sort."""
    n = s.shape[0]
    if n == 0:
        zero = jnp.zeros((0,), jnp.int32)
        return zero, zero, jnp.zeros((0,), bool), jnp.asarray(0, jnp.int32)
    ctp = jnp.cumsum(tp_c, dtype=jnp.int32)
    cfp = jnp.cumsum(fp_c, dtype=jnp.int32)
    last = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    # cumulative count at the end of the PREVIOUS tie group: inclusive cummax
    # of the group-end-masked cumsum, shifted right one (cumsums are
    # nondecreasing and >= 0, so 0 is a neutral mask fill)
    prev_tp = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jax.lax.cummax(jnp.where(last, ctp, 0))[:-1]]
    )
    prev_fp = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jax.lax.cummax(jnp.where(last, cfp, 0))[:-1]]
    )
    delta_tp = jnp.where(last, ctp - prev_tp, 0)
    delta_fp = jnp.where(last, cfp - prev_fp, 0)
    real = last & ((delta_tp > 0) | (delta_fp > 0))
    # NaN-scored SAMPLES (garbage model output) are indistinguishable from
    # padding; count their rows so the caller can fail loudly
    nan_dropped = jnp.sum(
        jnp.where(real & jnp.isnan(s), delta_tp + delta_fp, 0), dtype=jnp.int32
    )
    keep = real & ~jnp.isnan(s)
    # zero the counts of every non-kept row so they can never leak back in
    delta_tp = jnp.where(keep, delta_tp, 0)
    delta_fp = jnp.where(keep, delta_fp, 0)
    return delta_tp, delta_fp, keep, nan_dropped


@jax.jit
def compact_counts(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Merge rows with tied scores into one (score, Σtp, Σfp) row each.

    Returns ``(scores, tp, fp, n_unique, nan_dropped)`` with arrays of the
    same static length as the input: unique rows first in descending score
    order, then ``(NaN, 0, 0)`` padding. ``n_unique`` counts rows carrying a
    nonzero count (existing padding and zero-count groups compact back into
    padding). ``nan_dropped`` counts sample rows whose score was NaN — those
    are indistinguishable from padding and excluded from the output; callers
    must fail loudly when it is nonzero rather than silently change the
    denominator.

    Counts are int32: exact while the stream's TOTAL positives and negatives
    each stay below 2^31 (~2.1e9); beyond that the cumsums in here and in
    ``ops/curves.py`` would wrap. The 1B north star fits; document-level
    guard, not runtime-checked.

    Two sorts + two log-depth scans, no gathers/scatters: sort descending
    carrying the counts, per-group delta via shifted cummax of group-end
    cumsums, then a second sort on the masked keys pushes non-end rows (keyed
    ``NaN``) behind the compacted entries.
    """
    tp_w = tp_w.astype(jnp.int32)
    fp_w = fp_w.astype(jnp.int32)
    neg, tp_c, fp_c = jax.lax.sort((-scores, tp_w, fp_w), num_keys=1)
    s = -neg
    n = s.shape[0]
    if n == 0:
        zero = jnp.zeros((0,), jnp.int32)
        zs = jnp.asarray(0, jnp.int32)
        return s, zero, zero, zs, zs
    delta_tp, delta_fp, keep, nan_dropped = group_deltas_sorted(s, tp_c, fp_c)
    # key non-kept rows NaN so they join the padding block in the second
    # sort; their counts are already zeroed (group_deltas_sorted), so a
    # NaN-scored sample can never leak into the stored summary (round-3
    # review)
    key = jnp.where(keep, s, PAD_SCORE)
    neg2, tp_out, fp_out = jax.lax.sort((-key, delta_tp, delta_fp), num_keys=1)
    return -neg2, tp_out, fp_out, jnp.sum(keep.astype(jnp.int32)), nan_dropped


@functools.partial(jax.jit, static_argnames=("interpret",))
def compact_counts_fast(
    scores: jax.Array,
    tp_w: jax.Array,
    fp_w: jax.Array,
    *,
    interpret: bool = False,
):
    """:func:`compact_counts` with the second full sort replaced by the
    Pallas stream-compaction kernel (``ops/stream_compact.py``): one sort,
    the shared aggregation scans, then a single streaming pass that moves
    live rows to the front. Identical output contract (descending unique
    rows, NaN padding, ``n_unique``, ``nan_dropped``); measured 1.5-1.8x
    the two-sort formulation at the 1B bench's fold sizes on v5e. TPU-only
    in production; ``interpret=True`` runs it anywhere — the
    ``tests/ops/test_stream_compact.py`` suite pins bit-equality with
    :func:`compact_counts` over boundary tiles, NaN padding, ±inf scores,
    large counts and multi-chunk folds that way."""
    from torcheval_tpu.ops.stream_compact import compact_summary_rows

    tp_w = tp_w.astype(jnp.int32)
    fp_w = fp_w.astype(jnp.int32)
    neg, tp_c, fp_c = jax.lax.sort((-scores, tp_w, fp_w), num_keys=1)
    s = -neg
    if s.shape[0] == 0:
        zero = jnp.zeros((0,), jnp.int32)
        zs = jnp.asarray(0, jnp.int32)
        return s, zero, zero, zs, zs
    delta_tp, delta_fp, keep, nan_dropped = group_deltas_sorted(s, tp_c, fp_c)
    s2, tp2, fp2, n_live = compact_summary_rows(
        s, delta_tp, delta_fp, keep, interpret=interpret
    )
    return s2, tp2, fp2, n_live, nan_dropped

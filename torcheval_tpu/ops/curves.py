"""Sort-based ROC / PR curve kernels.

The reference's curve math (``torcheval/metrics/functional/classification/
auroc.py:50-67``, ``precision_recall_curve.py:207-230``) deduplicates tied
thresholds with boolean masking — a data-dependent shape JAX cannot trace.
These kernels keep **static shapes** via group-end propagation:

Sort scores descending (``lax.sort`` carries the targets with the keys) and
take cumulative TP/FP counts. For every position ``i``, replace its
cumulative counts with those at the *last* index of ``i``'s tie group
(boundary mask + reverse ``cummin`` propagation — log-depth scans, no
gathers). Intra-group points then coincide exactly with the group-end point,
so:

* trapezoidal ROC integration gets zero-width segments inside a group and the
  correct tie-diagonal across groups — identical to integrating the deduped
  curve;
* step (average-precision) integration gets zero ``ΔTP`` inside a group;
* PR-curve extraction keeps a boolean "last of group" mask for the host-side
  trim at the API boundary (SURVEY §7 "variable-length results under jit").

Everything is one sort + two scans + elementwise ops: O(N log N) compute,
O(N) memory, fully fused by XLA, no host sync, no random gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.obs.recompile import watched_jit


def _propagate_group_ends(
    s: jax.Array, ctp: jax.Array, cfp: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Replace each position's cumulative counts with its tie group's END
    values: boundary mask + reverse ``cummin`` (log-depth scan, no gathers —
    cumulative counts are nondecreasing, so masking non-ends to +inf and
    scanning min backwards lands every row on its group-end value)."""
    if s.shape[0] == 0:
        last = jnp.zeros((0,), bool)
    else:
        last = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    big = jnp.iinfo(jnp.int32).max
    tp = jax.lax.cummin(jnp.where(last, ctp, big), reverse=True)
    fp = jax.lax.cummin(jnp.where(last, cfp, big), reverse=True)
    return tp, fp, last


def _group_end_cumsums(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Raw-sample (unit count) case of :func:`_group_end_count_cumsums`,
    with less sort traffic: every raw row contributes exactly one count
    (``fp = 1 - tp``), so only the target rides the sort and the FP cumsum
    is recovered as ``rank+1 - cumsum(tp)`` — 8 bytes/row through the sort
    instead of 12. Assumes every row is a real sample (raw caches carry no
    padding; padded summaries take the counts path)."""
    t = target.astype(jnp.int32)
    neg, tp_c = jax.lax.sort((-input, t), num_keys=1)
    s = -neg
    ctp = jnp.cumsum(tp_c, dtype=jnp.int32)
    cfp = jnp.arange(1, s.shape[0] + 1, dtype=jnp.int32) - ctp
    tp, fp, last = _propagate_group_ends(s, ctp, cfp)
    return s, tp, fp, last


def _group_end_count_cumsums(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Weighted-count generalisation of :func:`_group_end_cumsums`.

    Each row is a (score, tp_count, fp_count) triple — a raw sample is the
    unit case ``(s, t, 1-t)``; a compacted summary row carries per-unique
    aggregated counts (``ops/summary.py``). Rows with ``NaN`` score are
    padding: XLA's total order sorts them behind every real score (including
    ``-inf``) and ``NaN != NaN`` keeps them out of real tie groups, so with
    their zero counts they add only zero-width curve segments — no validity
    mask needed.

    TPU-tuned lowering: ``lax.sort`` carries the counts alongside the keys
    (no N-element random gather), and group-end propagation is a reverse
    ``cummin`` over boundary-masked cumsums (a log-depth scan) instead of a
    ``searchsorted`` (which lowers to ~log2(N) gather passes). Measured 40x
    faster than the argsort+searchsorted formulation at N=10M on v5e.

    int32 cumulative counts: exact while total positives and negatives each
    stay below 2^31 (~2.1e9); a float32 running sum would instead silently
    stall at 2^24 (repo exactness rule, ops/confusion.py). Streams beyond
    2^31 per class would wrap — out of scope for the 1B north star.
    """
    neg, tp_c, fp_c = jax.lax.sort(
        (-scores, tp_w.astype(jnp.int32), fp_w.astype(jnp.int32)), num_keys=1
    )
    s = -neg
    ctp = jnp.cumsum(tp_c, dtype=jnp.int32)
    cfp = jnp.cumsum(fp_c, dtype=jnp.int32)
    tp, fp, last = _propagate_group_ends(s, ctp, cfp)
    return s, tp, fp, last


def _auroc_from_group_ends(itp: jax.Array, ifp: jax.Array) -> jax.Array:
    """Trapezoidal integration over group-end TP/FP counts; 0.5 when targets
    are all-one or all-zero (reference degenerate guard, ``auroc.py:60-66``)."""
    tp = jnp.concatenate([jnp.zeros(1, jnp.int32), itp]).astype(jnp.float32)
    fp = jnp.concatenate([jnp.zeros(1, jnp.int32), ifp]).astype(jnp.float32)
    factor = tp[-1] * fp[-1]
    auc = jnp.trapezoid(tp, fp)
    return jnp.where(factor == 0, 0.5, auc / jnp.maximum(factor, 1.0))


def _auprc_from_group_ends(itp: jax.Array, ifp: jax.Array) -> jax.Array:
    """Average-precision (step) integration over group-end TP/FP counts:
    ``AP = sum(ΔTP_k * precision_k) / TP_total`` over descending thresholds.
    Matches sklearn's ``average_precision_score``; 0.0 when there are no
    positives (the recall axis is undefined)."""
    tp = itp.astype(jnp.float32)
    fp = ifp.astype(jnp.float32)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    delta_tp = jnp.diff(itp, prepend=0).astype(jnp.float32)
    total = tp[-1]
    ap = jnp.sum(delta_tp * precision) / jnp.maximum(total, 1.0)
    return jnp.where(total == 0, 0.0, ap)


@watched_jit
def binary_auroc_counts_kernel(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> jax.Array:
    """Exact trapezoidal AUROC over (score, tp_count, fp_count) rows."""
    _, tp, fp, _ = _group_end_count_cumsums(scores, tp_w, fp_w)
    return _auroc_from_group_ends(tp, fp)


@watched_jit
def binary_auprc_counts_kernel(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> jax.Array:
    """Average precision over (score, tp, fp) count rows."""
    if scores.shape[0] == 0:  # static shape — resolved at trace time
        return jnp.asarray(0.0)
    _, tp, fp, _ = _group_end_count_cumsums(scores, tp_w, fp_w)
    return _auprc_from_group_ends(tp, fp)


@watched_jit
def binary_auroc_counts_presorted_kernel(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> jax.Array:
    """AUROC over rows that are ALREADY descending-sorted, tie-merged and
    (NaN, 0, 0)-padded — the invariant every ``compact_counts``(+``_fast``)
    output satisfies. Every row is its own tie group, so the cumulative
    sums feed the trapezoid directly and the compute-time sort disappears
    (padding rows add zero-width segments). The compacting metrics'
    ``compute()`` rides this when the summary provenance is known-sorted."""
    if scores.shape[0] == 0:  # static shape — resolved at trace time
        return jnp.asarray(0.5)
    ctp = jnp.cumsum(tp_w.astype(jnp.int32), dtype=jnp.int32)
    cfp = jnp.cumsum(fp_w.astype(jnp.int32), dtype=jnp.int32)
    return _auroc_from_group_ends(ctp, cfp)


@watched_jit
def binary_auprc_counts_presorted_kernel(
    scores: jax.Array, tp_w: jax.Array, fp_w: jax.Array
) -> jax.Array:
    """Average precision over presorted tie-merged count rows (see
    :func:`binary_auroc_counts_presorted_kernel`); padding rows have zero
    ``ΔTP`` and contribute nothing to the step integral."""
    if scores.shape[0] == 0:
        return jnp.asarray(0.0)
    ctp = jnp.cumsum(tp_w.astype(jnp.int32), dtype=jnp.int32)
    cfp = jnp.cumsum(fp_w.astype(jnp.int32), dtype=jnp.int32)
    return _auprc_from_group_ends(ctp, cfp)


@watched_jit
def binary_auroc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    """Exact trapezoidal AUROC on raw samples — the reduced-sort-traffic
    unit-count path (:func:`_group_end_cumsums`)."""
    _, tp, fp, _ = _group_end_cumsums(input, target)
    return _auroc_from_group_ends(tp, fp)


@watched_jit
def binary_auprc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    """Average precision on raw samples (unit-count sort path)."""
    if input.shape[0] == 0:
        return jnp.asarray(0.0)
    _, tp, fp, _ = _group_end_cumsums(input, target)
    return _auprc_from_group_ends(tp, fp)


@watched_jit
def prc_points_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full-length PR-curve points in descending-threshold order plus the
    "last of tie group" validity mask. The caller selects ``mask`` rows on the
    host and flips to ascending order (reference layout,
    ``precision_recall_curve.py:207-230``)."""
    if input.shape[0] == 0:  # static shape — resolved at trace time
        empty = jnp.empty((0,))
        return empty, empty, empty, jnp.zeros((0,), bool)
    s, itp, ifp, last = _group_end_cumsums(input, target)
    tp = itp.astype(jnp.float32)
    fp = ifp.astype(jnp.float32)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    total_pos = tp[-1]
    # no positives seen => recall defined as 1.0 (reference nan_to_num(1.0))
    recall = jnp.where(total_pos > 0, tp / jnp.maximum(total_pos, 1.0), 1.0)
    return s, precision, recall, last


# (C, N) batched variant for multiclass one-vs-all curves: vmap over classes.
multiclass_prc_points_kernel = watched_jit(
    jax.vmap(prc_points_kernel, in_axes=(0, 0), out_axes=0),
    name="multiclass_prc_points_kernel",
)


def class_onehot_rows(target: jax.Array, num_classes: int) -> jax.Array:
    """``(C, N)`` float one-vs-all membership rows from ``(N,)`` integer
    labels (out-of-range labels match no class). The shared expansion behind
    every one-vs-all multiclass curve."""
    return (
        target[None, :].astype(jnp.int32)
        == jnp.arange(num_classes, dtype=jnp.int32)[:, None]
    ).astype(jnp.float32)


@watched_jit
def multiclass_auroc_kernel(scores: jax.Array, target: jax.Array) -> jax.Array:
    """Per-class one-vs-all AUROC vector from ``(N, C)`` scores and ``(N,)``
    integer labels: the binary kernel ``vmap``-ed over the class axis — C
    independent descending sorts batched into one XLA program (TPU sorts
    vectorise across the batch dimension)."""
    onehot = class_onehot_rows(target, scores.shape[1])
    return jax.vmap(binary_auroc_kernel, in_axes=(0, 0))(scores.T, onehot)


@watched_jit
def multiclass_auprc_kernel(scores: jax.Array, target: jax.Array) -> jax.Array:
    """Per-class one-vs-all average precision, same batching as
    :func:`multiclass_auroc_kernel`."""
    onehot = class_onehot_rows(target, scores.shape[1])
    return jax.vmap(binary_auprc_kernel, in_axes=(0, 0))(scores.T, onehot)

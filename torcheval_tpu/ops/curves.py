"""Sort-based ROC / PR curve kernels.

The reference's curve math (``torcheval/metrics/functional/classification/
auroc.py:50-67``, ``precision_recall_curve.py:207-230``) deduplicates tied
thresholds with boolean masking — a data-dependent shape JAX cannot trace.
These kernels keep **static shapes** via group-end propagation:

Sort scores descending (``lax.sort`` carries the targets with the keys) and
take cumulative TP/FP counts. For every position ``i``, replace its
cumulative counts with those at the *last* index of ``i``'s tie group
(boundary mask + reverse ``cummin`` propagation — log-depth scans, no
gathers). Intra-group points then coincide exactly with the group-end point,
so:

* trapezoidal ROC integration gets zero-width segments inside a group and the
  correct tie-diagonal across groups — identical to integrating the deduped
  curve;
* step (average-precision) integration gets zero ``ΔTP`` inside a group;
* PR-curve extraction keeps a boolean "last of group" mask for the host-side
  trim at the API boundary (SURVEY §7 "variable-length results under jit").

Everything is one sort + two scans + elementwise ops: O(N log N) compute,
O(N) memory, fully fused by XLA, no host sync, no random gathers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _group_end_cumsums(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort desc and return (thresholds, tp, fp, last_of_group) with cumulative
    counts propagated to each tie group's end.

    TPU-tuned lowering: ``lax.sort`` carries the targets alongside the keys
    (no 10M-element random gather), and group-end propagation is a reverse
    ``cummin`` over boundary-masked cumsums (a log-depth scan) instead of a
    ``searchsorted`` (which lowers to ~log2(N) gather passes). Measured 40x
    faster than the argsort+searchsorted formulation at N=10M on v5e.
    """
    neg, t = jax.lax.sort(
        (-input, target.astype(jnp.int32)), num_keys=1
    )  # ascending on -input == descending on input
    s = -neg
    # int32 cumulative counts: a float32 running sum silently stops
    # incrementing at 2**24 samples (repo exactness rule, ops/confusion.py);
    # callers cast to float only at the final divisions/integration
    ctp = jnp.cumsum(t, dtype=jnp.int32)
    cfp = jnp.cumsum(1 - t, dtype=jnp.int32)
    # tie-group ends sit where the sorted key changes (plus the last element);
    # each position takes the cumsum of its group's end = the min over future
    # boundary values (cumsums are nondecreasing)
    if s.shape[0] == 0:
        last = jnp.zeros((0,), bool)
    else:
        last = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    big = jnp.iinfo(jnp.int32).max
    tp = jax.lax.cummin(jnp.where(last, ctp, big), reverse=True)
    fp = jax.lax.cummin(jnp.where(last, cfp, big), reverse=True)
    return s, tp, fp, last


@jax.jit
def binary_auroc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    """Exact trapezoidal AUROC; 0.5 when targets are all-one or all-zero
    (reference degenerate guard, ``auroc.py:60-66``)."""
    _, tp, fp, _ = _group_end_cumsums(input, target)
    tp = jnp.concatenate([jnp.zeros(1, jnp.int32), tp]).astype(jnp.float32)
    fp = jnp.concatenate([jnp.zeros(1, jnp.int32), fp]).astype(jnp.float32)
    factor = tp[-1] * fp[-1]
    auc = jnp.trapezoid(tp, fp)
    return jnp.where(factor == 0, 0.5, auc / jnp.maximum(factor, 1.0))


@jax.jit
def binary_auprc_kernel(input: jax.Array, target: jax.Array) -> jax.Array:
    """Average-precision (step) integration of the PR curve:
    ``AP = sum(ΔTP_k * precision_k) / TP_total`` over descending thresholds.
    Matches sklearn's ``average_precision_score``; 0.0 when there are no
    positives (the recall axis is undefined)."""
    if input.shape[0] == 0:  # static shape — resolved at trace time
        return jnp.asarray(0.0)
    _, itp, ifp, _ = _group_end_cumsums(input, target)
    tp = itp.astype(jnp.float32)
    fp = ifp.astype(jnp.float32)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    delta_tp = jnp.diff(itp, prepend=0).astype(jnp.float32)
    total = tp[-1]
    ap = jnp.sum(delta_tp * precision) / jnp.maximum(total, 1.0)
    return jnp.where(total == 0, 0.0, ap)


@jax.jit
def prc_points_kernel(
    input: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full-length PR-curve points in descending-threshold order plus the
    "last of tie group" validity mask. The caller selects ``mask`` rows on the
    host and flips to ascending order (reference layout,
    ``precision_recall_curve.py:207-230``)."""
    if input.shape[0] == 0:  # static shape — resolved at trace time
        empty = jnp.empty((0,))
        return empty, empty, empty, jnp.zeros((0,), bool)
    s, itp, ifp, last = _group_end_cumsums(input, target)
    tp = itp.astype(jnp.float32)
    fp = ifp.astype(jnp.float32)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    total_pos = tp[-1]
    # no positives seen => recall defined as 1.0 (reference nan_to_num(1.0))
    recall = jnp.where(total_pos > 0, tp / jnp.maximum(total_pos, 1.0), 1.0)
    return s, precision, recall, last


# (C, N) batched variant for multiclass one-vs-all curves: vmap over classes.
multiclass_prc_points_kernel = jax.jit(
    jax.vmap(prc_points_kernel, in_axes=(0, 0), out_axes=0)
)

"""Confusion-count kernels: the hot ops behind every counter metric.

The reference's hot kernel is a 1-D ``scatter_(0, labels, w, reduce="add")``
(``/root/reference/torcheval/metrics/functional/classification/f1_score.py:182-190``,
``accuracy.py:271-273``). XLA:TPU lowers scatter poorly (serialised updates),
so the TPU-first design offers four lowerings and picks by size:

* ``matmul`` — weights-vector × one-hot matrix product. The one-hot is
  ``labels[:, None] == iota`` fused by XLA into the dot; the contraction rides
  the MXU. Exact for integer-valued weights below 2**24 per batch (float32
  accumulation; every integer count <= 2**24 is f32-exact).
* ``sort`` — sort labels, then per-class run lengths via binary search of the
  class edges into the sorted array. O(N log N) but bandwidth-friendly;
  unweighted only. Wins when the virtual one-hot gets huge.
* ``scatter`` — ``zeros(C).at[labels].add(w)``; O(N) updates, no N×C
  intermediate. Never wins on TPU (serialised updates) but is the general
  weighted fallback when the one-hot is over budget.
* ``pallas`` — hand kernel (``ops/pallas_hist.py``): VMEM-resident
  accumulator tiles streamed over sample blocks; unweighted only. Auto-picked
  on real TPU backends for N·C >= 2**33 (measured 1.84x vs matmul at
  N=16.7M·C=1000, 1.42x vs sort at N=1M·C=10k); parity within noise below.

Auto-pick thresholds are measured on a v5e chip (2026-07): matmul beats
scatter 4.3× at (N=1M, C=1000) and stays ahead through N·C ≈ 2**30; the sort
path beats both ~3× at (N=1M, C=10k) and 13× at (N=8k, C=10k).

Counts accumulate into int32 when unweighted (exact to 2**31 ≈ 2.1e9 samples —
covers the 1B-pred BASELINE configs; float32 would lose exactness at 16.7M).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from torcheval_tpu.obs.recompile import watched_jit

# Above this many virtual one-hot elements (N * C), stop using the MXU
# one-hot contraction (measured crossover vs the sort path, v5e).
_MATMUL_ELEMENT_BUDGET = 1 << 30
# Above this many virtual one-hot elements (N * C * C), lower the joint
# (confusion) one-hot contraction to a flat scatter instead (measured
# crossover: matmul 4.15 ms vs scatter 5.38 ms at N=100k·C=1000 = 1e11;
# scatter ahead by 1.5× at N=1.3M·C=1000).
_CONFUSION_MATMUL_BUDGET = 2 * 10**11
# The matmul path also MATERIALISES two (N, C) bf16 one-hot operands (XLA
# cannot fuse the compare into both sides of a dot_general); cap their
# footprint (2 × 2 B × N·C) at ~2 GB so a small-C/large-N input inside the
# MAC budget cannot OOM where the O(N) scatter handles it fine.
_CONFUSION_MATMUL_ONEHOT_ELEMS = 1 << 29


_METHODS = ("auto", "matmul", "scatter", "sort", "pallas")
# Above this many virtual one-hot elements, the Pallas histogram kernel
# (ops/pallas_hist.py) beats the XLA lowerings on real TPU: measured 1.84x
# vs matmul at (N=16.7M, C=1000) = 1.7e10 and 1.42x vs sort at
# (N=1M, C=10k) = 1e10; parity within tunnel noise below ~1e9.
_PALLAS_ELEMENT_MIN = 1 << 33


def _pick_method(n: int, num_classes: int, method: str, weighted: bool) -> str:
    if method not in _METHODS:
        raise ValueError(f"method must be one of {_METHODS}, got {method!r}.")
    if method != "auto":
        return method
    # n <= 2**24 keeps unweighted per-class counts (each <= n, and 2**24
    # itself is f32-exact) exact in the float32 accumulator; weighted
    # exactness is the caller's documented contract, so the same bound is
    # applied as a proxy for "sum of weights stays small"
    if (
        not weighted
        and n <= (1 << 24)
        and n * num_classes >= _PALLAS_ELEMENT_MIN
        and jax.default_backend() == "tpu"
    ):
        # any world size: the kernel carries a custom_partitioning GSPMD rule
        # (ops/pallas_hist.py — per-shard VMEM histograms + one psum over the
        # sample-axis mesh axes), so a sharded operand is never re-gathered.
        # The lowering is further platform-dispatched in class_counts so a
        # CPU-committed array on a TPU host takes the sort path instead of a
        # Mosaic kernel.
        return "pallas"
    if n * num_classes <= _MATMUL_ELEMENT_BUDGET and n <= (1 << 24):
        return "matmul"
    # sort path is unweighted-only; weighted over-budget falls to scatter
    return "scatter" if weighted else "sort"


@partial(watched_jit, static_argnames=("num_classes", "method", "dtype"))
def class_counts(
    labels: jax.Array,
    num_classes: int,
    weights: Optional[jax.Array] = None,
    *,
    method: str = "auto",
    dtype=None,
) -> jax.Array:
    """``out[c] = sum(weights[labels == c])`` with shape ``(num_classes,)``.

    ``weights=None`` counts occurrences (int32 result); otherwise the result
    has the weights' dtype. Out-of-range labels contribute nothing.
    """
    if labels.ndim != 1:
        raise ValueError(f"labels must be 1-D, got shape {labels.shape}.")
    n = labels.shape[0]
    if weights is None:
        w = jnp.ones((n,), dtype=jnp.int32 if dtype is None else dtype)
    else:
        w = weights if dtype is None else weights.astype(dtype)
    resolved = _pick_method(n, num_classes, method, weighted=weights is not None)

    def _sort_counts(ls: jax.Array) -> jax.Array:
        s = jnp.sort(ls.astype(jnp.int32))
        edges = jnp.arange(num_classes + 1, dtype=jnp.int32)
        starts = jnp.searchsorted(s, edges, side="left")
        return (starts[1:] - starts[:-1]).astype(w.dtype)

    if resolved == "matmul":
        # (N, C) virtual one-hot contracted against (N,) weights on the MXU.
        onehot = (labels[:, None] == jnp.arange(num_classes)[None, :]).astype(
            jnp.float32
        )
        counts = jnp.matmul(
            w.astype(jnp.float32), onehot, preferred_element_type=jnp.float32
        )
        return counts.astype(w.dtype)
    if resolved == "pallas":
        if weights is not None:
            raise ValueError("method='pallas' supports only unweighted counts.")
        from torcheval_tpu.ops.pallas_hist import sharded_pallas_class_counts

        if method == "auto":
            # dispatch per LOWERING platform, not per process default: a
            # CPU-committed array on a TPU host must take an XLA lowering,
            # not a Mosaic kernel it cannot compile
            return jax.lax.platform_dependent(
                labels,
                tpu=lambda ls: sharded_pallas_class_counts(
                    ls, num_classes, False
                ).astype(w.dtype),
                default=_sort_counts,
            )
        interpret = jax.default_backend() != "tpu"
        return sharded_pallas_class_counts(
            labels, num_classes, interpret
        ).astype(w.dtype)
    if resolved == "sort":
        if weights is not None:
            raise ValueError("method='sort' supports only unweighted counts.")
        # run lengths of each class in the sorted labels; out-of-range labels
        # sort to the ends, outside every [edge_c, edge_c+1) span
        return _sort_counts(labels)
    # scatter path: drop out-of-range labels. mode="drop" only catches
    # indices past the end — negative indices would WRAP (numpy semantics)
    # and silently count against the last classes, diverging from the matmul
    # path's compare (which matches nothing) — so push them out of bounds
    # first.
    labels = jnp.where(labels < 0, num_classes, labels)
    return jnp.zeros((num_classes,), dtype=w.dtype).at[labels].add(
        w, mode="drop"
    )


@partial(watched_jit, static_argnames=("num_classes",))
def match_triple_counts(
    pred: jax.Array, target: jax.Array, num_classes: int
) -> tuple:
    """``(num_tp, num_label, num_pred)`` per class — the sufficient-statistic
    triple behind F1/precision/recall (reference scatter pattern:
    ``f1_score.py:164-191``).

    Small batches take three MXU one-hot contractions (XLA dedupes the
    shared compares). Past the matmul budget, the naive formulation costs
    two unweighted sorts plus a WEIGHTED count for tp — which has no sort
    lowering and falls to the serialised scatter (~12 ms at N=1.3M on v5e).
    Instead, tp and label fold into ONE unweighted sort over the joint key
    ``2*target + (pred == target)``: label c's misses land in bin 2c, hits
    in 2c+1, so ``num_label = bins[0::2] + bins[1::2]`` and
    ``num_tp = bins[1::2]`` — two sorts total, no scatter (measured ~2x on
    the config-3 shape).
    """
    p = pred.astype(jnp.int32)
    t = target.astype(jnp.int32)
    n = p.shape[0]
    if n * num_classes <= _MATMUL_ELEMENT_BUDGET and n <= (1 << 24):
        correct = (p == t).astype(jnp.int32)
        return (
            class_counts(t, num_classes, correct),
            class_counts(t, num_classes),
            class_counts(p, num_classes),
        )
    # joint-key lane: out-of-range targets produce out-of-range keys and
    # drop, matching the class_counts contract
    key = jnp.where(t >= 0, 2 * t + (p == t).astype(jnp.int32), -1)
    bins = class_counts(key, 2 * num_classes)
    num_tp = bins[1::2]
    num_label = bins[0::2] + num_tp
    return num_tp, num_label, class_counts(p, num_classes)


@partial(watched_jit, static_argnames=("num_classes", "normalize"))
def confusion_matrix_counts(
    pred: jax.Array,
    target: jax.Array,
    num_classes: int,
    *,
    normalize: Optional[str] = None,
) -> jax.Array:
    """``out[t, p] = #{i : target[i] == t and pred[i] == p}``.

    Two lowerings, picked by the N·C² MAC volume of the one-hot contraction:

    * ``T^T @ P`` where T/P are (N, C) one-hot matrices in bfloat16 (0/1 are
      exact in bf16) accumulated in float32 — the contraction over samples
      rides the MXU. Measured 20× faster than scatter at C=100 and still
      ahead at (N=100k, C=1000); exact while every cell count <= 2**24.
    * a single O(N) flat scatter on the joint index ``t * C + p`` for larger
      volumes, where the MAC count outgrows the MXU win.

    Out-of-range labels in either coordinate contribute nothing (a sample with
    only one bad coordinate must not fold into a valid cell: the matmul row is
    all-zero in the invalid coordinate's one-hot; the scatter path masks
    validity explicitly before forming the joint index).
    ``normalize``: None | "all" | "pred" | "true" (matching sklearn semantics).
    """
    p = pred.astype(jnp.int32)
    t = target.astype(jnp.int32)
    n = p.shape[0]
    # n <= 2**24 keeps every cell count (each <= n, and 2**24 itself is
    # f32-exact) exactly representable in the float32 accumulator; bigger
    # batches take the integer scatter
    if (
        n * num_classes * num_classes <= _CONFUSION_MATMUL_BUDGET
        and n * num_classes <= _CONFUSION_MATMUL_ONEHOT_ELEMS
        and n <= (1 << 24)
    ):
        classes = jnp.arange(num_classes, dtype=jnp.int32)[None, :]
        t_onehot = (t[:, None] == classes).astype(jnp.bfloat16)
        p_onehot = (p[:, None] == classes).astype(jnp.bfloat16)
        mat = jnp.matmul(
            t_onehot.T, p_onehot, preferred_element_type=jnp.float32
        ).astype(jnp.int32)
    else:
        valid = (p >= 0) & (p < num_classes) & (t >= 0) & (t < num_classes)
        joint = jnp.where(valid, t * num_classes + p, num_classes * num_classes)
        flat = jnp.zeros(
            (num_classes * num_classes,), dtype=jnp.int32
        ).at[joint].add(1, mode="drop")
        mat = flat.reshape(num_classes, num_classes)
    return normalize_confusion_matrix(mat, normalize)


def normalize_confusion_matrix(mat: jax.Array, normalize: Optional[str]) -> jax.Array:
    """Apply sklearn-style normalization to a (C, C) count matrix."""
    if normalize is None:
        return mat
    m = mat.astype(jnp.float32)
    if normalize == "all":
        return m / jnp.maximum(m.sum(), 1.0)
    if normalize == "pred":
        return m / jnp.maximum(m.sum(axis=0, keepdims=True), 1.0)
    if normalize == "true":
        return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)
    raise ValueError(f"normalize must be None, 'all', 'pred' or 'true', got {normalize!r}.")


@partial(watched_jit, static_argnames=("k",))
def topk_onehot(scores: jax.Array, k: int) -> jax.Array:
    """Exactly-k 0/1 membership matrix (N, C): 1 for the k top-scoring classes
    per row (ties broken by index, like ``torch.topk`` scatter — reference
    ``accuracy.py:386-396``).

    Accumulates k dense compare passes instead of materialising an (N, k, C)
    one-hot or scattering (XLA:TPU serialises scatter updates) — ~100x faster
    at (10k, 10k). Prefer gathering ``target`` at the top-k indices over
    calling this at all when only set statistics are needed
    (``accuracy._topk_multilabel_stats``).
    """
    idx = jax.lax.top_k(scores, k)[1]  # (N, k)
    cols = jnp.arange(scores.shape[-1], dtype=idx.dtype)[None, :]
    out = jnp.zeros(scores.shape, jnp.int32)
    for i in range(k):
        out = out + (cols == idx[:, i : i + 1]).astype(jnp.int32)
    return out

"""Confusion-count kernels: the hot ops behind every counter metric.

The reference's hot kernel is a 1-D ``scatter_(0, labels, w, reduce="add")``
(``/root/reference/torcheval/metrics/functional/classification/f1_score.py:182-190``,
``accuracy.py:271-273``). XLA:TPU lowers scatter poorly (serialised updates),
so the TPU-first design offers two lowerings and picks by size:

* ``matmul`` — weights-vector × one-hot matrix product. The one-hot is
  ``labels[:, None] == iota`` fused by XLA into the dot; the contraction rides
  the MXU. Exact for integer-valued weights below 2**24 per batch (float32
  accumulation). Preferred while the virtual one-hot stays small.
* ``scatter`` — ``zeros(C).at[labels].add(w)``; O(N) updates, no N×C
  intermediate. Wins for very large ``num_classes × batch``.

Counts accumulate into int32 when unweighted (exact to 2**31 ≈ 2.1e9 samples —
covers the 1B-pred BASELINE configs; float32 would lose exactness at 16.7M).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

# Above this many virtual one-hot elements (N * C), switch to scatter.
_MATMUL_ELEMENT_BUDGET = 1 << 24


def _pick_method(n: int, num_classes: int, method: str) -> str:
    if method != "auto":
        return method
    return "matmul" if n * num_classes <= _MATMUL_ELEMENT_BUDGET else "scatter"


@partial(jax.jit, static_argnames=("num_classes", "method", "dtype"))
def class_counts(
    labels: jax.Array,
    num_classes: int,
    weights: Optional[jax.Array] = None,
    *,
    method: str = "auto",
    dtype=None,
) -> jax.Array:
    """``out[c] = sum(weights[labels == c])`` with shape ``(num_classes,)``.

    ``weights=None`` counts occurrences (int32 result); otherwise the result
    has the weights' dtype. Out-of-range labels contribute nothing.
    """
    if labels.ndim != 1:
        raise ValueError(f"labels must be 1-D, got shape {labels.shape}.")
    n = labels.shape[0]
    if weights is None:
        w = jnp.ones((n,), dtype=jnp.int32 if dtype is None else dtype)
    else:
        w = weights if dtype is None else weights.astype(dtype)
    resolved = _pick_method(n, num_classes, method)
    if resolved == "matmul":
        # (N, C) virtual one-hot contracted against (N,) weights on the MXU.
        onehot = (labels[:, None] == jnp.arange(num_classes)[None, :]).astype(
            jnp.float32
        )
        counts = jnp.matmul(
            w.astype(jnp.float32), onehot, preferred_element_type=jnp.float32
        )
        return counts.astype(w.dtype)
    # scatter path: drop out-of-range labels. mode="drop" only catches
    # indices past the end — negative indices would WRAP (numpy semantics)
    # and silently count against the last classes, diverging from the matmul
    # path's compare (which matches nothing) — so push them out of bounds
    # first.
    labels = jnp.where(labels < 0, num_classes, labels)
    return jnp.zeros((num_classes,), dtype=w.dtype).at[labels].add(
        w, mode="drop"
    )


@partial(jax.jit, static_argnames=("num_classes", "normalize"))
def confusion_matrix_counts(
    pred: jax.Array,
    target: jax.Array,
    num_classes: int,
    *,
    normalize: Optional[str] = None,
) -> jax.Array:
    """``out[t, p] = #{i : target[i] == t and pred[i] == p}``.

    Lowered as a single O(N) scatter on the joint index ``t * C + p`` (a joint
    one-hot matmul would cost N·C² MACs — prohibitive at C=1000).
    Out-of-range labels in either coordinate contribute nothing (a sample with
    only one bad coordinate must not fold into a valid cell, so validity is
    masked explicitly before the joint index is formed).
    ``normalize``: None | "all" | "pred" | "true" (matching sklearn semantics).
    """
    p = pred.astype(jnp.int32)
    t = target.astype(jnp.int32)
    valid = (p >= 0) & (p < num_classes) & (t >= 0) & (t < num_classes)
    joint = jnp.where(valid, t * num_classes + p, num_classes * num_classes)
    flat = jnp.zeros((num_classes * num_classes,), dtype=jnp.int32).at[joint].add(
        1, mode="drop"
    )
    mat = flat.reshape(num_classes, num_classes)
    return normalize_confusion_matrix(mat, normalize)


def normalize_confusion_matrix(mat: jax.Array, normalize: Optional[str]) -> jax.Array:
    """Apply sklearn-style normalization to a (C, C) count matrix."""
    if normalize is None:
        return mat
    m = mat.astype(jnp.float32)
    if normalize == "all":
        return m / jnp.maximum(m.sum(), 1.0)
    if normalize == "pred":
        return m / jnp.maximum(m.sum(axis=0, keepdims=True), 1.0)
    if normalize == "true":
        return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)
    raise ValueError(f"normalize must be None, 'all', 'pred' or 'true', got {normalize!r}.")


@partial(jax.jit, static_argnames=("k",))
def topk_onehot(scores: jax.Array, k: int) -> jax.Array:
    """Exactly-k 0/1 membership matrix (N, C): 1 for the k top-scoring classes
    per row (ties broken by index, like ``torch.topk`` scatter — reference
    ``accuracy.py:386-396``).

    Accumulates k dense compare passes instead of materialising an (N, k, C)
    one-hot or scattering (XLA:TPU serialises scatter updates) — ~100x faster
    at (10k, 10k). Prefer gathering ``target`` at the top-k indices over
    calling this at all when only set statistics are needed
    (``accuracy._topk_multilabel_stats``).
    """
    idx = jax.lax.top_k(scores, k)[1]  # (N, k)
    cols = jnp.arange(scores.shape[-1], dtype=idx.dtype)[None, :]
    out = jnp.zeros(scores.shape, jnp.int32)
    for i in range(k):
        out = out + (cols == idx[:, i : i + 1]).astype(jnp.int32)
    return out

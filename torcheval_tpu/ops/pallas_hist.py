"""Pallas TPU histogram kernel: class counts via tile-local one-hot
accumulation in VMEM.

Grid = (class tiles, sample blocks), sample stream INNERMOST: each class
tile's ``(1, c_tile)`` accumulator stays resident in VMEM while every
``(block_rows, 128)`` label block streams past it; per step the kernel
compares the block against the tile's class iota and adds the column sums of
the ``(block_rows, 128, c_tile)`` one-hot. Work is the same N·C_pad VPU ops
as the XLA one-hot matmul, but with no matmul staging and no HBM round trip
for the accumulator.

Status: **in the auto-pick** for unweighted counts with
``N·C >= 2**33`` on real TPU backends of ANY world size
(``ops/confusion.py::_pick_method``; the GSPMD rule below shards the kernel
per-sample), where interleaved A/B measured 1.84x vs the matmul lowering at
(N=16.7M, C=1000) and 1.42x vs sort at (N=1M, C=10k); parity within noise
below ~1e9 elements. ``method="pallas"`` forces it anywhere; the CPU test
suite runs it in interpret mode.
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from torcheval_tpu.obs.recompile import watched_jit

# byte budget for the (block_rows, 128, c_tile) f32 one-hot intermediate —
# well under VMEM (~16 MB/core); _tile_plan sizes blocks against it
_VMEM_BUDGET_BYTES = 8 * 2**20


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# classes are tiled across the lane dim in chunks of up to this many
_MAX_CLASS_TILE = 1024


def _tile_plan(c_pad: int):
    """(block_rows, c_tile): sample rows of 128 per grid step and the class
    tile width, sized so the (rows, 128, c_tile) f32 one-hot intermediate
    stays inside the VMEM budget with rows a multiple of 8 (the f32 sublane
    count — Mosaic requires the block's second-to-last dim divisible by 8)."""
    c_tile = min(c_pad, _MAX_CLASS_TILE)
    rows = _VMEM_BUDGET_BYTES // (128 * c_tile * 4)
    return max(rows // 8 * 8, 8), c_tile


def _hist_kernel(labels_ref, out_ref, *, c_tile: int):
    # grid = (class tiles, sample blocks): sample stream INNERMOST, so the
    # output tile for class-tile j stays resident in VMEM across the whole
    # stream instead of being written back and reloaded every step
    j = pl.program_id(0)  # class-tile index
    i = pl.program_id(1)  # sample-block index

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    labels = labels_ref[:]  # (block_rows, 128) int32 — samples fill the tile
    # classes of THIS tile: [j*c_tile, (j+1)*c_tile)
    classes = j * c_tile + jax.lax.broadcasted_iota(jnp.int32, (1, 1, c_tile), 2)
    onehot = (labels[:, :, None] == classes).astype(jnp.float32)
    out_ref[:] += jnp.sum(onehot, axis=(0, 1))[None, :]


@functools.partial(watched_jit, static_argnames=("num_classes", "interpret"))
def pallas_class_counts(
    labels: jax.Array, num_classes: int, *, interpret: bool = False
) -> jax.Array:
    """Unweighted ``bincount(labels, minlength=num_classes)`` as a Pallas
    kernel. Out-of-range labels contribute nothing. Exact while the total
    count per class stays <= 2**24 (every integer up to 2**24 inclusive is
    float32-exact), as with the matmul lowering. ``interpret=True`` runs the kernel in interpret mode (any
    backend — used by the CPU test suite).

    Layout note: the labels feed in as ``(rows, 128)`` — samples fill whole
    (8, 128) tiles. A ``(N, 1)`` operand would be tiled with 128x padding
    (observed as an 8 GB HBM "copy" allocation for a 64 MB input at
    N=16.7M). Classes are tiled along lanes (grid dim 1) so the one-hot
    intermediate fits VMEM at any ``num_classes``."""
    if labels.ndim != 1:
        raise ValueError(f"labels must be 1-D, got shape {labels.shape}.")
    n = labels.shape[0]
    c_pad = _round_up(max(num_classes, 1), 128)
    block_rows, c_tile = _tile_plan(c_pad)
    c_pad = _round_up(c_pad, c_tile)
    row_elems = 128 * block_rows
    n_pad = _round_up(max(n, 1), row_elems)
    # pad with an out-of-range sentinel so padding matches no class column
    # (class iotas stop at c_pad-1; real labels >= num_classes likewise
    # match only dead padding columns sliced away below)
    padded = jnp.full((n_pad,), c_pad, jnp.int32)
    if n:
        padded = padded.at[:n].set(labels.astype(jnp.int32))
    padded = padded.reshape(n_pad // 128, 128)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, c_tile=c_tile),
        grid=(c_pad // c_tile, n_pad // row_elems),
        in_specs=[pl.BlockSpec((block_rows, 128), lambda j, i: (i, 0))],
        out_specs=pl.BlockSpec((1, c_tile), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, c_pad), jnp.float32),
        interpret=interpret,
    )(padded)
    return out[0, :num_classes].astype(jnp.int32)


# --------------------------------------------------------------- GSPMD rule
# ``pallas_call`` has no partitioning rule of its own, so under GSPMD a
# sharded operand would be all-gathered onto every device before the kernel
# runs — which is why round 2 gated the auto-pick to single-device worlds.
# ``custom_partitioning`` supplies the missing rule: the histogram is a pure
# sample-axis reduction, so each shard runs the VMEM kernel on its local
# samples and the per-shard counts fold with one int32 ``psum`` over exactly
# the mesh axes the sample axis is sharded on (ICI-resident; no operand
# movement). This is the manual-partitioning design the ShardedEvaluator's
# implicit-SPMD counters use, applied to the hand kernel.


def _sample_axes(labels_sharding) -> tuple:
    """Mesh axes the (1-D) sample axis is sharded over; () if replicated."""
    spec = getattr(labels_sharding, "spec", None)
    spec0 = spec[0] if spec else None
    if spec0 is None:
        return ()
    return tuple(spec0) if isinstance(spec0, tuple) else (spec0,)


def _counts_infer(num_classes, interpret, mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())  # (num_classes,) counts: replicated


def _counts_partition(num_classes, interpret, mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = _sample_axes(arg_shapes[0].sharding)
    # keep the operand's sample-axis sharding (never re-gather it); the
    # result replicates after the psum
    arg_sharding = NamedSharding(mesh, P(axes if axes else None))
    result_sharding = NamedSharding(mesh, P())

    def lower_fn(labels):
        local = pallas_class_counts(labels, num_classes, interpret=interpret)
        return jax.lax.psum(local, axes) if axes else local

    return mesh, lower_fn, result_sharding, (arg_sharding,)


from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402


@functools.partial(custom_partitioning, static_argnums=(1, 2))
def sharded_pallas_class_counts(labels, num_classes, interpret=False):
    """``pallas_class_counts`` with a GSPMD partitioning rule: on a mesh,
    each shard's counts accumulate in VMEM locally and fold with one
    ``psum``; on one device it is exactly ``pallas_class_counts``."""
    return pallas_class_counts(labels, num_classes, interpret=interpret)


# Shardy rule: the sample factor i is contracted; the class-axis factor j
# appears only in the result (replicated — the partition callback psums).
# Older jax predates Shardy and its def_partition has no sharding_rule
# parameter — the GSPMD callbacks alone are the complete rule there.
_def_partition_kwargs = {}
if "sharding_rule" in inspect.signature(
    sharded_pallas_class_counts.def_partition
).parameters:
    _def_partition_kwargs["sharding_rule"] = "i -> j"
sharded_pallas_class_counts.def_partition(
    infer_sharding_from_operands=_counts_infer,
    partition=_counts_partition,
    **_def_partition_kwargs,
)

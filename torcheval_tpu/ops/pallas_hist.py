"""Pallas TPU histogram kernel: class counts via block-local one-hot
accumulation in VMEM.

The XLA lowering of ``class_counts`` (``ops/confusion.py``) is a one-hot
matmul — good, but it materialises its reduction through the MXU with the
one-hot generated per pass. This kernel keeps a single ``(1, C_pad)``
accumulator resident in VMEM across a sequential grid over sample blocks;
each step compares its ``(block_n, 1)`` label block against a class iota and
adds the column sums. Work is the same N·C_pad VPU ops, but there is no
matmul staging and the accumulator never round-trips to HBM until the end.

Status: **opt-in** (``class_counts(..., method="pallas")``). Interleaved A/B
runs against the XLA matmul on the tunneled v5e measured parity-to-better
(1.0-2.4x in calm windows) but the environment's co-tenant noise has so far
prevented a clean enough measurement to move the auto-pick. Correctness is
tested everywhere via Pallas interpret mode (CPU) plus the real TPU path
when available.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# block_n chosen so the (block_n, C_pad) f32 one-hot block stays well under
# VMEM (~16 MB/core): 2048 × 1024 × 4 B = 8 MB at C=1000.
_VMEM_BUDGET_BYTES = 8 * 2**20


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(labels_ref, out_ref, *, c_pad: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    labels = labels_ref[:]  # (block_n, 1) int32
    classes = jax.lax.broadcasted_iota(jnp.int32, (1, c_pad), 1)
    onehot = (labels == classes).astype(jnp.float32)  # (block_n, c_pad)
    out_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("num_classes", "interpret"))
def pallas_class_counts(
    labels: jax.Array, num_classes: int, *, interpret: bool = False
) -> jax.Array:
    """Unweighted ``bincount(labels, minlength=num_classes)`` as a Pallas
    kernel. Out-of-range labels contribute nothing. Exact while the total
    count per class stays < 2**24 (float32 accumulator), as with the matmul
    lowering. ``interpret=True`` runs the kernel in interpret mode (any
    backend — used by the CPU test suite)."""
    if labels.ndim != 1:
        raise ValueError(f"labels must be 1-D, got shape {labels.shape}.")
    n = labels.shape[0]
    c_pad = _round_up(max(num_classes, 1), 128)
    block_n = max(_VMEM_BUDGET_BYTES // (c_pad * 4), 8)
    n_pad = _round_up(max(n, 1), block_n)
    # pad with an out-of-range sentinel so padding matches no class column;
    # (the class iota stops at c_pad-1, and real labels >= num_classes match
    # only dead padding columns that are sliced away below)
    padded = jnp.full((n_pad, 1), c_pad, jnp.int32)
    if n:
        padded = padded.at[:n, 0].set(labels.astype(jnp.int32))
    out = pl.pallas_call(
        functools.partial(_hist_kernel, c_pad=c_pad),
        grid=(n_pad // block_n,),
        in_specs=[pl.BlockSpec((block_n, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, c_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, c_pad), jnp.float32),
        interpret=interpret,
    )(padded)
    return out[0, :num_classes].astype(jnp.int32)

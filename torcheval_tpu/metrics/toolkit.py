"""Distributed metric toolkit — the TPU-native replacement for
``torcheval/metrics/toolkit.py`` (311 LoC, reference L3).

The reference syncs by **pickling whole Metric objects** through
``torch.distributed.gather_object`` (``toolkit.py:235-257``) and re-merging on
one rank. The TPU-native design never moves Python objects:

* **Implicit SPMD sync (the hot path).** Feed metrics *global sharded arrays*
  (see :mod:`torcheval_tpu.parallel`): every update kernel then runs SPMD
  across the mesh and XLA inserts the cross-chip collectives (psum for the
  counter reductions) over ICI automatically. There is nothing to "sync" —
  state is replicated and already global. This is how the 32-chip BASELINE
  config runs.

* **Explicit cross-process sync (this module).** For the multi-host pattern
  where each process streams *local* (host-resident or single-chip) batches
  into its own metric replica — the reference's model — every state variable
  declares a :class:`~torcheval_tpu.metrics.state.Reduction`, and sync rides
  a batched typed wire: ONE descriptor round plus ONE concatenated
  byte-payload round for all of a metric's (or a whole collection's) states,
  folded per declared reduction after the exchange. States cross the network
  as arrays (via ``multihost_utils.process_allgather``, i.e. a compiled XLA
  all-gather over ICI/DCN), never as pickles.

Semantics preserved from the reference (``toolkit.py:24-311``): works with
``recipient_rank`` int or ``"all"``; no-op with a warning at world size 1;
``None`` / ``{}`` returned on non-recipient ranks; source metrics are never
mutated; ``_prepare_for_merge_state`` compacts sample caches pre-sync.

**Failure semantics (ISSUE 5).** A collective with a dead or straggling
member does not fail — it hangs, forever, on every healthy rank. Every sync
API therefore takes ``timeout_s=`` (a watchdog thread around each blocking
collective round; expiry raises :class:`SyncTimeoutError` naming the round
and lane) and ``on_failure="raise"|"local"`` — ``"local"`` warns once,
bumps ``toolkit.sync.timeouts{policy=local}`` and returns the **local**
(unsynced) result on every calling rank, so one preempted worker degrades
the report instead of wedging the job. The full per-API table lives in
``docs/robustness.md``; fault-injection coverage in ``tests/resilience/``.
"""

from __future__ import annotations

import contextlib
import copy
import functools
import logging
import math
import threading
import time
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, TypeVar, Union

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction, TState
from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs.annotate import traced as _traced
from torcheval_tpu.resilience import chaos as _chaos
from torcheval_tpu.utils import quant as _quant
from torcheval_tpu.utils.devices import DeviceLike
from torcheval_tpu.utils.telemetry import log_once as _log_once

_logger = logging.getLogger(__name__)

TMetric = TypeVar("TMetric", bound=Metric)
_RecipientRank = Union[int, str]


# ------------------------------------------------------- failure semantics
class SyncError(RuntimeError):
    """Base for explicit-sync failures (timeouts and in-round errors)."""


class SyncTimeoutError(SyncError):
    """A collective round did not complete within the sync deadline.

    Carries the failing ``round`` (``"descriptor"`` / ``"payload"`` /
    ``"object-length"`` / ``"object-payload"``), the ``lane`` (``"typed"``
    or ``"object"``) and the overall ``timeout_s`` budget, so a log line is
    enough to tell *which* exchange a dead rank wedged."""

    def __init__(self, round_label: str, lane: str, timeout_s: float) -> None:
        super().__init__(
            f"sync round {round_label!r} ({lane} lane) did not complete "
            f"within timeout_s={timeout_s}: a participating process is "
            "likely dead or stalled. Use on_failure='local' to degrade to "
            "local results instead of raising."
        )
        self.round = round_label
        self.lane = lane
        self.timeout_s = timeout_s


class SyncRoundError(SyncError):
    """A collective round FAILED (rather than hanging) while a sync
    deadline was active — e.g. the transport surfaced a peer death as a
    connection error, or the coordinator aborted the world. Wrapped so
    ``on_failure="local"`` covers both ways a dead rank can manifest; the
    original error is ``__cause__``."""

    def __init__(self, round_label: str, lane: str, cause: BaseException) -> None:
        super().__init__(
            f"sync round {round_label!r} ({lane} lane) failed: {cause!r}"
        )
        self.round = round_label
        self.lane = lane


_FAILURE_POLICIES = ("raise", "local")


def _check_failure_policy(on_failure: str) -> None:
    if on_failure not in _FAILURE_POLICIES:
        raise ValueError(
            f"on_failure must be one of {_FAILURE_POLICIES}, got {on_failure!r}."
        )


def _check_timeout_s(timeout_s: Optional[float]) -> None:
    """Validate ``timeout_s`` at the API boundary (ISSUE 8 satellite):
    ``None`` means no deadline; anything else must be a positive FINITE
    number of seconds. Non-positive values would expire instantly,
    ``inf`` would arm a watchdog that never fires, and ``nan`` slips past
    a plain ``<= 0`` comparison into a deadline whose every remaining-time
    computation is ``nan`` — a degenerate watchdog that neither fires nor
    guards. All three are caller bugs, rejected before any collective
    (or any state mutation) happens."""
    if timeout_s is None:
        return
    try:
        ok = math.isfinite(timeout_s) and timeout_s > 0
    except TypeError:
        ok = False
    if not ok:
        raise ValueError(
            "timeout_s must be None or a positive finite number of "
            f"seconds, got {timeout_s!r}."
        )


class _Deadline:
    __slots__ = ("expires_at", "timeout_s")

    def __init__(self, expires_at: float, timeout_s: float) -> None:
        self.expires_at = expires_at
        self.timeout_s = timeout_s


_deadline_local = threading.local()


@contextlib.contextmanager
def _sync_deadline(timeout_s: Optional[float]):
    """Install a sync deadline for the calling thread: every collective
    round dispatched under it runs on a watchdog (``_run_guarded``) and the
    budget is shared across rounds — ``timeout_s`` bounds the WHOLE sync,
    not each round. ``None`` = no deadline (the pre-ISSUE-5 behavior:
    block forever)."""
    if timeout_s is None:
        yield
        return
    _check_timeout_s(timeout_s)  # backstop; entry points validate earlier
    prev = getattr(_deadline_local, "deadline", None)
    _deadline_local.deadline = _Deadline(
        time.monotonic() + timeout_s, timeout_s
    )
    try:
        yield
    finally:
        _deadline_local.deadline = prev


def _run_guarded(fn: Callable[[], Any], round_label: str, lane: str) -> Any:
    """Run one blocking collective round under the active deadline (if any).

    The round executes on a daemon watchdog thread; the caller joins with
    the remaining budget. On expiry the caller raises
    :class:`SyncTimeoutError` and moves on — the watchdog thread stays
    blocked inside the collective (there is no portable way to cancel a
    native collective) but, being daemonic, never blocks process exit. If
    the round *raises* instead (a peer death surfaced as a transport
    error), the error is re-raised as :class:`SyncRoundError` so both
    failure shapes hit the same ``on_failure`` policy."""
    deadline = getattr(_deadline_local, "deadline", None)
    if deadline is None:
        return fn()
    remaining = deadline.expires_at - time.monotonic()
    if remaining <= 0:
        raise SyncTimeoutError(round_label, lane, deadline.timeout_s)
    box: Dict[str, Any] = {}

    def _worker() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - relayed to the caller
            box["error"] = e

    t = threading.Thread(
        target=_worker, name=f"toolkit-sync-{round_label}", daemon=True
    )
    t.start()
    t.join(remaining)
    if t.is_alive():
        raise SyncTimeoutError(round_label, lane, deadline.timeout_s)
    if "error" in box:
        raise SyncRoundError(round_label, lane, box["error"]) from box["error"]
    return box["value"]


def _sync_failure(err: SyncError, on_failure: str) -> None:
    """Account a sync failure and apply the policy: re-raise, or warn ONCE
    and fall through to the caller's local degraded return."""
    _obs.counter("toolkit.sync.timeouts", policy=on_failure)
    if on_failure == "raise":
        raise err
    _log_once(
        "toolkit.sync.degraded",
        "explicit sync failed (%s); continuing with LOCAL (unsynced) "
        "results under on_failure='local'. Later syncs may degrade the "
        "same way; this warning is emitted once per process.",
        err,
    )


# --------------------------------------------------------------------- local
def clone_metric(metric: TMetric) -> TMetric:
    """Deep-copy a metric (reference ``toolkit.py:121-131``)."""
    return copy.deepcopy(metric)


def clone_metrics(metrics: List[TMetric]) -> List[TMetric]:
    """Deep-copy a list of metrics (reference ``toolkit.py:134-142``)."""
    return [clone_metric(m) for m in metrics]


def reset_metrics(metrics: List[TMetric]) -> List[TMetric]:
    """Reset every metric (reference ``toolkit.py:260-281``)."""
    return [m.reset() for m in metrics]


def to_device(
    metrics: List[TMetric], device: DeviceLike, *args: Any, **kwargs: Any
) -> List[TMetric]:
    """Move every metric's state to ``device`` (reference ``toolkit.py:284-311``)."""
    return [m.to(device, *args, **kwargs) for m in metrics]


def merge_metrics(metrics: List[TMetric]) -> Optional[TMetric]:
    """Merge replicas into a fresh metric without mutating any source —
    the local equivalent of the reference's gathered-object merge
    (``toolkit.py:217-232``)."""
    if not metrics:
        return None
    base = clone_metric(metrics[0])
    return base.merge_state(clone_metrics(metrics[1:]))


# ----------------------------------------------------- typed state reduction
def _fold_states(
    gathered: List[Dict[str, TState]],
    reductions: Dict[str, Reduction],
) -> Dict[str, TState]:
    """Fold per-rank state dicts into one, using each state's declared
    reduction. Pure host/device math — shared by the multihost gather path
    and the tests (which feed simulated rank dicts)."""
    out: Dict[str, TState] = {}
    for name, red in reductions.items():
        values = [sd[name] for sd in gathered]
        if red is Reduction.CAT:
            arrays: List[jax.Array] = []
            for v in values:
                if isinstance(v, (list, deque)):
                    if v:
                        arrays.append(jnp.concatenate(list(v), axis=0))
                elif v.shape[0]:
                    arrays.append(v)
            out[name] = [jnp.concatenate(arrays, axis=0)] if arrays else []
        elif red is Reduction.SUM:
            acc = values[0]
            for v in values[1:]:
                acc = acc + v
            out[name] = acc
        elif red is Reduction.MAX:
            acc = values[0]
            for v in values[1:]:
                acc = jnp.maximum(acc, v)
            out[name] = acc
        elif red is Reduction.MIN:
            acc = values[0]
            for v in values[1:]:
                acc = jnp.minimum(acc, v)
            out[name] = acc
        elif red is Reduction.NONE:
            out[name] = values[0]
        elif red is Reduction.WINDOW:
            # per-rank entries extend in rank order; each rank's value is a
            # stacked (k, ...) array off the wire (or a deque/list of rows
            # from the simulated-rank test path, or [] when empty) — either
            # way iteration yields the per-update rows. The deque bound is
            # re-imposed at install (get_synced_metric), where the state's
            # declared maxlen is known.
            rows: List[jax.Array] = []
            for v in values:
                rows.extend(list(v))
            out[name] = rows
        else:  # Reduction.CUSTOM
            raise NotImplementedError(
                f"State {name!r} declares Reduction.CUSTOM and cannot be "
                "synced with typed collectives; merge replicas explicitly "
                "with merge_metrics()/metric.merge_state()."
            )
    return out


# ------------------------------------------------------------ process world
# CAT-cache wire descriptor: [n_local, ndim, dtype_code, dim1..dim4]
_CAT_DTYPES = (
    jnp.float32,
    jnp.int32,
    jnp.bool_,
    jnp.bfloat16,
    jnp.float16,
    jnp.int8,
    jnp.uint8,
    jnp.uint32,
    jnp.float64,
    jnp.int64,
    # appended (codes are wire format — extend only at the END): round 3
    # routed ALL typed states through this allowlist, not just CAT caches,
    # so the exotic-but-legal state dtypes must stay syncable
    jnp.int16,
    jnp.uint16,
    jnp.uint64,
)
_MAX_CAT_RANK = 5


def _check_cat_descriptors(name: str, all_desc: np.ndarray) -> None:
    """Post-exchange validation: runs on every rank on identical gathered
    descriptors, so a failure raises everywhere instead of hanging the
    collective."""
    max_rank = int(all_desc[:, 1].max()) if all_desc.size else 0
    if max_rank > _MAX_CAT_RANK:
        raise NotImplementedError(
            f"State {name!r} has rank {max_rank} on some process, above the "
            f"sync wire-format limit {_MAX_CAT_RANK}; reshape the state or "
            "extend the descriptor layout past _MAX_CAT_RANK."
        )
    if all_desc.size and int(all_desc[:, 2].min()) < 0:
        raise NotImplementedError(
            f"State {name!r} has a dtype outside the sync wire-format "
            f"allowlist {[jnp.dtype(d).name for d in _CAT_DTYPES]} "
            "on some process; cast the state or extend _CAT_DTYPES."
        )


def _world_size() -> int:
    return jax.process_count()


def _process_index() -> int:
    return jax.process_index()


# ------------------------------------------------------- process subgroups
# The reference's every toolkit API takes a ``process_group`` and syncs only
# within it (``torcheval/metrics/toolkit.py:24-78``, via ``PGWrapper``). The
# TPU-native analogue is a ``processes`` sequence of global process indices:
# collectives then run over a Mesh built from ONE device per member process,
# so non-member processes are genuinely uninvolved — they neither execute the
# exchange nor block on it (torch.distributed subgroup semantics).
_ProcessGroup = Optional[Sequence[int]]


def _resolve_group(processes: _ProcessGroup) -> Optional[Tuple[int, ...]]:
    """Validate and normalise a ``processes`` argument. ``None`` = the full
    world. A member-only contract is enforced eagerly: a non-member entering
    the collective path would hang the member processes (same rule as a
    ``torch.distributed`` group you are not part of)."""
    if processes is None:
        return None
    group = tuple(sorted({int(p) for p in processes}))
    if not group:
        raise ValueError(
            "processes must be a non-empty collection of process indices "
            "or None (the full world)."
        )
    world = _world_size()
    for p in group:
        if not 0 <= p < world:
            raise ValueError(
                f"process index {p} out of range for world size {world}."
            )
    me = _process_index()
    if me not in group:
        raise ValueError(
            f"process {me} is not a member of processes={group}; only "
            "member processes may call sync APIs on a subgroup (a "
            "non-member entering the collective would hang the members). "
            "Gate the call on membership, as with a torch.distributed "
            "subgroup."
        )
    return group


def _check_group_recipient(
    group: Optional[Tuple[int, ...]], recipient_rank: _RecipientRank
) -> None:
    if (
        group is not None
        and recipient_rank != "all"
        and recipient_rank not in group
    ):
        raise ValueError(
            f"recipient_rank {recipient_rank} is not a member of "
            f"processes={group}."
        )


@functools.lru_cache(maxsize=None)
def _subgroup_mesh(group: Tuple[int, ...]) -> jax.sharding.Mesh:
    """One (lowest-id) device per member process — globally consistent, so
    every member builds the identical mesh."""
    devs = [
        sorted(
            (d for d in jax.devices() if d.process_index == p),
            key=lambda d: d.id,
        )[0]
        for p in group
    ]
    return jax.sharding.Mesh(np.array(devs), ("p",))


@functools.lru_cache(maxsize=None)
def _subgroup_replicate(group: Tuple[int, ...]):
    """Cached jitted replicating identity for a subgroup mesh — the
    all-gather collective. jit's cache keys on callable identity, so a fresh
    lambda per call would recompile every sync round."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = _subgroup_mesh(group)
    return jax.jit(
        lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
    )


def _subgroup_allgather(x: np.ndarray, group: Tuple[int, ...]) -> np.ndarray:
    """All-gather ``x`` (same shape/dtype on every member) across the
    subgroup only: each member contributes its row of a dim-0-sharded global
    array over the subgroup mesh, and a jitted identity with replicated
    out-sharding is the all-gather — XLA inserts the collective over the
    member devices; non-members never participate."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = _subgroup_mesh(group)
    pos = group.index(_process_index())
    local = jax.device_put(x[None, ...], mesh.devices.reshape(-1)[pos])
    garr = jax.make_array_from_single_device_arrays(
        (len(group),) + np.shape(x),
        NamedSharding(mesh, PartitionSpec("p")),
        [local],
    )
    return np.asarray(_subgroup_replicate(group)(garr))


def _allgather_stacked(
    x: np.ndarray,
    group: Optional[Tuple[int, ...]],
    round_label: str = "collective",
    lane: str = "typed",
) -> np.ndarray:
    """Per-rank-stacked all-gather of a HOST numpy buffer: the full-world
    path rides ``multihost_utils.process_allgather`` (one compiled XLA
    collective); a subgroup rides :func:`_subgroup_allgather`, which keeps
    the buffer host-side until its single ``device_put``. Returns shape
    ``(n_members, *x.shape)`` in group order (ascending process index).

    Every explicit cross-process collective round funnels through here, so
    three per-round mechanisms live at this choke point:

    * **accounting** — with obs enabled, each call increments
      ``toolkit.sync.rounds``, accumulates the local payload bytes sent,
      and times the round (the gather blocks on the result, so the span is
      real wall time, not dispatch time). The two-collective-round
      invariant of :func:`sync_and_compute` is thereby an observable:
      ``snapshot()["counters"]["toolkit.sync.rounds"]`` reads exactly 2
      after one typed sync;
    * **deadlines** — under an active ``timeout_s`` deadline the blocking
      gather runs on a watchdog thread (:func:`_run_guarded`) and a hang
      raises :class:`SyncTimeoutError` naming ``round_label``/``lane``;
    * **fault injection** — the env-gated chaos hook
      (``resilience/chaos.py``) counts rounds here and can kill or delay
      this process at a chosen round, which is how the 4-process recovery
      tests produce a real dead-rank hang."""
    _chaos.on_sync_round()
    if not _obs.enabled():
        return _run_guarded(
            lambda: _allgather_stacked_impl(x, group), round_label, lane
        )
    world = len(group) if group is not None else _world_size()
    t0 = time.perf_counter()
    # per-(lane, round) span series: the labels ride into the snapshot keys
    # AND the timeline event (the flight recorder shows which exchange each
    # round was, not only that "a round" happened)
    with _obs.span("toolkit.sync.round", lane=lane, round=round_label):
        out = _run_guarded(
            lambda: _allgather_stacked_impl(x, group), round_label, lane
        )
    _obs.counter("toolkit.sync.rounds")
    _obs.counter("toolkit.sync.payload_bytes", float(x.nbytes))
    # latency DISTRIBUTION per lane, not only total/max: a straggling rank
    # shows up as a fat p99 here long before a deadline fires
    _obs.histo(
        "toolkit.sync.round_seconds", time.perf_counter() - t0, lane=lane
    )
    _obs.gauge("toolkit.sync.world_size", world)
    return out


def _allgather_stacked_impl(
    x: np.ndarray, group: Optional[Tuple[int, ...]]
) -> np.ndarray:
    if group is None:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(jnp.asarray(x)))
    return _subgroup_allgather(np.ascontiguousarray(x), group)


# ------------------------------------------------------- object-gather lane
def _tree_to_host(value):
    """Recursively convert a TState container's arrays to host numpy so the
    pickled wire payload is backend-independent. Container metadata
    (defaultdict factory, deque maxlen) is preserved so the round trip
    through :func:`_tree_to_device` is the identity on structure."""
    if isinstance(value, dict):
        out = {k: _tree_to_host(v) for k, v in value.items()}
        if isinstance(value, defaultdict):
            d = defaultdict(value.default_factory)
            d.update(out)
            return d
        return out
    if isinstance(value, deque):
        return deque((_tree_to_host(v) for v in value), maxlen=value.maxlen)
    if isinstance(value, list):
        return [_tree_to_host(v) for v in value]
    if isinstance(value, jax.Array):
        return np.asarray(value)
    return value


def _tree_to_device(value):
    """Inverse of :func:`_tree_to_host`: numpy leaves back to jax arrays."""
    if isinstance(value, dict):
        out = {k: _tree_to_device(v) for k, v in value.items()}
        if isinstance(value, defaultdict):
            d = defaultdict(value.default_factory)
            d.update(out)
            return d
        return out
    if isinstance(value, deque):
        return deque((_tree_to_device(v) for v in value), maxlen=value.maxlen)
    if isinstance(value, list):
        return [_tree_to_device(v) for v in value]
    if isinstance(value, np.ndarray):
        return jnp.asarray(value)
    return value


def _allgather_object(
    obj: Any, group: Optional[Tuple[int, ...]] = None
) -> List[Any]:
    """All-gather an arbitrary picklable object across JAX processes (all of
    them, or a validated subgroup).

    This is the reference's ``dist.all_gather_object`` (``toolkit.py:235-257``)
    rebuilt on typed XLA collectives: pickle → uint8 payload → length exchange
    → pad to the max → stacked all-gather → trim + unpickle per rank. Used
    only for states the typed lanes cannot carry (dict-keyed state, CUSTOM
    reductions); array/list states always travel as typed arrays.
    """
    import pickle

    world = len(group) if group is not None else _world_size()
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    _obs.counter("toolkit.sync.object_lane_bytes", float(payload.size))
    lengths = _allgather_stacked(
        np.asarray([payload.size], dtype=np.int32),
        group,
        "object-length",
        "object",
    ).reshape(world)
    max_len = int(lengths.max())
    padded = np.zeros(max(max_len, 1), dtype=np.uint8)
    padded[: payload.size] = payload
    all_bytes = _allgather_stacked(
        padded, group, "object-payload", "object"
    ).reshape(world, -1)
    return [
        pickle.loads(all_bytes[rank, : lengths[rank]].tobytes())
        for rank in range(world)
    ]


def _needs_object_sync(metric: Metric) -> bool:
    """True when some state cannot travel on the typed lanes: dict-keyed
    state (arbitrary keys) or a CUSTOM reduction (only the metric's own
    ``merge_state`` knows how to fold it). WINDOW deques ride the typed
    wire (stacked per-update rows), so they do NOT force the object lane."""
    for name, red in metric._state_name_to_reduction.items():
        if red is Reduction.CUSTOM or isinstance(getattr(metric, name), dict):
            return True
    return False


def _object_synced_metric(
    metric: TMetric,
    recipient_rank: _RecipientRank,
    group: Optional[Tuple[int, ...]] = None,
) -> Optional[TMetric]:
    """Fallback sync for dict/CUSTOM states: all-gather the whole state_dict
    as a pickled payload (over typed uint8 collectives) and fold with the
    metric's own ``merge_state`` — the reference's object-gather semantics
    (``toolkit.py:217-257``) without ``torch.distributed``."""
    gathered_sds = _allgather_object(_tree_to_host(metric.state_dict()), group)
    if recipient_rank != "all" and _process_index() != recipient_rank:
        return None
    replicas = []
    for sd in gathered_sds:
        rep = clone_metric(metric)
        rep.load_state_dict(_tree_to_device(sd))
        replicas.append(rep)
    return replicas[0].merge_state(replicas[1:])


@_traced("toolkit.get_synced_metric")
def get_synced_metric(
    metric: TMetric,
    recipient_rank: _RecipientRank = 0,
    *,
    processes: _ProcessGroup = None,
    timeout_s: Optional[float] = None,
    on_failure: str = "raise",
    quantize: Optional[bool] = None,
    _gathered: Optional[List[Dict[str, TState]]] = None,
) -> Optional[TMetric]:
    """Sync metric states over all JAX processes — or the ``processes``
    subgroup — and return the merged metric on the recipient rank(s);
    ``None`` elsewhere.

    ``quantize`` engages the wire codecs for additive lanes (integer
    lanes narrow losslessly; f32 SUM lanes block-quantize with a bounded,
    documented error — docs/distributed.md "Quantized sync"). ``None``
    defers to ``TORCHEVAL_TPU_SYNC_QUANTIZE``; ``False`` is the per-call
    opt-out that restores exact raw bytes whatever the environment says.

    Reference parity: ``toolkit.py:145-232`` — world size 1 returns the input
    metric with a warning; ``recipient_rank="all"`` returns on every rank;
    ``processes`` is the ``process_group`` analogue (a sequence of global
    process indices; only members may call, and collectives involve only
    member processes). Array/list states travel on the batched typed wire
    (one descriptor round + one byte-payload round, shared with
    :func:`sync_and_compute_collection`); dict-keyed and CUSTOM-reduction
    states fall back to a pickled object gather (:func:`_allgather_object`)
    folded by the metric's own ``merge_state``.

    ``timeout_s`` bounds the WHOLE sync (all collective rounds share the
    budget); on expiry — or a transport error surfacing a dead peer —
    ``on_failure="raise"`` raises the :class:`SyncError`, while
    ``on_failure="local"`` warns once, bumps
    ``toolkit.sync.timeouts{policy=local}`` and returns a clone of the
    LOCAL (unsynced) metric on every calling rank — including
    non-recipients, since the recipient contract is unsatisfiable once the
    exchange has failed and each survivor's local state is the only data
    it still has.
    """
    if not (isinstance(recipient_rank, int) or recipient_rank == "all"):
        raise ValueError(
            "recipient_rank should be an integer or 'all', "
            f"got {recipient_rank} instead."
        )
    _check_failure_policy(on_failure)
    _check_timeout_s(timeout_s)
    group = _resolve_group(processes)
    _check_group_recipient(group, recipient_rank)
    world = len(group) if group is not None else _world_size()
    if world == 1:
        _logger.warning(
            "World size is 1, and metric(s) not synced. "
            "returning the input metric(s)."
        )
        return metric
    metric._prepare_for_merge_state()
    try:
        with _sync_deadline(timeout_s):
            if _gathered is None and _needs_object_sync(metric):
                return _object_synced_metric(metric, recipient_rank, group)
            if _gathered is not None:
                gathered = _gathered
            else:
                # ride the batched collection wire: exactly two collective
                # rounds (descriptor matrix + one concatenated byte payload)
                # regardless of how many states the metric has — the
                # per-state path pays one round per SUM/MAX state and two
                # per CAT state, which on a DCN-attached pod is a per-round
                # latency hit (and on the bench's timeshared host, a
                # scheduling-noise amplifier)
                gathered = [
                    per_rank["m"]
                    for per_rank in _gather_collection_states(
                        {"m": metric},
                        group,
                        quantize=_quant.sync_quantize_enabled(quantize),
                    )
                ]
    except SyncError as err:
        _sync_failure(err, on_failure)
        return clone_metric(metric)
    if recipient_rank != "all" and _process_index() != recipient_rank:
        return None
    if getattr(metric, "_sliced_sync", False):
        # row-keyed sliced states (ISSUE 15): ranks hold ragged cohort
        # populations under private id→row mappings, so the gathered
        # leading axes are NOT elementwise-alignable yet. Remap every
        # rank's rows onto the deterministic sorted-union id table —
        # pure local post-gather work, zero extra collective rounds; the
        # fold below then treats the slices as the ordinary SUM/MAX/MIN
        # lanes they are (with a leading axis). Slice-axis-sharded states
        # (ISSUE 17) arrive here as host rows already: the gather step
        # reads per-shard blocks and concatenates them in block order, so
        # the remap sees the same dense [S, ...] view either way and the
        # synced clone re-installs shards on adoption.
        from torcheval_tpu.metrics.sliced import align_sliced_gathered

        gathered = align_sliced_gathered(metric, gathered)
    folded = _fold_states(gathered, metric._state_name_to_reduction)
    synced = clone_metric(metric)
    for name, red in metric._state_name_to_reduction.items():
        value = folded[name]
        default = metric._state_name_to_default[name]
        if red is Reduction.CAT and not isinstance(default, (list, deque)):
            value = value[0] if value else jnp.empty((0,))
        if red is Reduction.WINDOW:
            # re-impose the bounded-deque invariant: rank rows arrived in
            # rank order, the declared maxlen keeps the newest — identical
            # semantics to a local merge_state fold
            value = deque(value, maxlen=getattr(default, "maxlen", None))
        synced._set_states({name: value})
    if getattr(metric, "_sliced_sync", False):
        # the union table is now IN the installed id lanes; rebuild the
        # synced clone's host table/capacity/statics to match
        synced._adopt_state_shapes()
    return synced


def get_synced_state_dict(
    metric: Metric,
    recipient_rank: _RecipientRank = 0,
    *,
    processes: _ProcessGroup = None,
    timeout_s: Optional[float] = None,
    on_failure: str = "raise",
    quantize: Optional[bool] = None,
) -> Dict[str, TState]:
    """Globally-merged ``state_dict``; ``{}`` on non-recipient ranks
    (reference ``toolkit.py:81-118``; ``processes`` = subgroup sync;
    ``timeout_s``/``on_failure``/``quantize`` as in
    :func:`get_synced_metric` — a degraded ``"local"`` call returns the
    LOCAL state dict)."""
    _check_timeout_s(timeout_s)
    synced = get_synced_metric(
        metric,
        recipient_rank,
        processes=processes,
        timeout_s=timeout_s,
        on_failure=on_failure,
        quantize=quantize,
    )
    return synced.state_dict() if synced is not None else {}


@_traced("toolkit.sync_and_compute")
def sync_and_compute(
    metric: Metric,
    recipient_rank: _RecipientRank = 0,
    *,
    processes: _ProcessGroup = None,
    timeout_s: Optional[float] = None,
    on_failure: str = "raise",
    quantize: Optional[bool] = None,
) -> Optional[Any]:
    """Sync states across all processes — or the ``processes`` subgroup —
    and compute on the recipient rank(s).

    Reference parity: ``toolkit.py:24-78`` (``processes`` plays the
    ``process_group`` role). Because states travel as typed arrays (not
    pickled objects), every rank could fold cheaply; we still honor the
    recipient contract — non-recipients get ``None``.

    ``timeout_s`` + ``on_failure="local"`` is the preemption-survival
    spelling: if a rank died and the collective hangs, every survivor
    returns its LOCAL compute within the deadline instead of wedging
    (see :func:`get_synced_metric` for the exact degradation contract).
    ``quantize`` is the wire-codec knob (also documented there).
    """
    _check_timeout_s(timeout_s)
    synced = get_synced_metric(
        metric,
        recipient_rank,
        processes=processes,
        timeout_s=timeout_s,
        on_failure=on_failure,
        quantize=quantize,
    )
    if synced is None:
        return None
    return synced.compute()


# ------------------------------------------------ batched collection sync
# One descriptor exchange + one byte-payload gather for a WHOLE collection,
# instead of one gather round per state per metric (round-2 verdict Weak #7:
# on a DCN-attached pod every round is a cross-host latency hit). Wire:
#   round 1: (n_entries, 9) int32 descriptor matrix
#            [d0, ndim, dtype_code, d1, d2, d3, d4, codec, enc_nbytes]
#            (ndim == -1: empty CAT; codec 0 = raw, then enc_nbytes is
#            derived from shape x dtype and the column stays 0)
#   round 2: uint8 payload — every entry's raw C-order bytes (or its
#            encoded form, per the codec column) concatenated, padded to
#            the max total length across ranks
# Entry order is (metric key, registered state order) — identical on every
# rank by SPMD lockstep, same assumption the per-metric path already makes.
# WINDOW entries are truncated between the rounds to the rows that survive
# the maxlen fold (_window_keep_counts): the gathered descriptors tell every
# rank every rank's row counts, so the payload round moves <= maxlen window
# rows total instead of maxlen per rank.
#
# ---- quantized lanes (ISSUE 12, EQuARX-shaped). With quantization on
# (per-call ``quantize=`` or TORCHEVAL_TPU_SYNC_QUANTIZE=1), additive
# entries encode BEFORE the descriptor round and the codec travels in the
# descriptor, so every rank decodes every peer's entries from the wire
# metadata alone — ranks may even disagree on the knob (env drift) and
# still interoperate, because decode is per-rank-per-entry:
#   codec 1 (narrow): SUM/MAX/MIN integer lanes, min-offset narrowed —
#     LOSSLESS; decode widens back to the declared dtype before the fold,
#     so integer counts accumulate bit-exactly (widened accumulation).
#   codec 2 (q8): f32 SUM lanes of >= Q8_MIN_ELEMENTS elements,
#     int8-block-quantized with per-block f32 scales — bounded error
#     (per element <= max|block|/254 per contributing rank; the tolerance
#     table lives in docs/distributed.md). Scalars and small states stay
#     raw and bit-exact even when quantization is forced on; non-finite
#     entries fall back to raw (counted in
#     toolkit.sync.quantize_fallbacks{reason=nonfinite} — the dist-curves
#     error-channel shape: detect, never silently corrupt).
#   codec 3 (bucket): SUM/MAX/MIN integer lanes again — the sparse
#     nonzero encoding (delta-narrowed indices + narrowed values,
#     utils/quant.py) that the ISSUE 13 resident sketch histograms made
#     worth having; LOSSLESS (scatter into zeros, widened accumulation).
#     Raced against codec 1 per entry; the smaller encoding ships.
# An encoder that would not shrink an entry returns None and the entry
# ships raw — the codec can only reduce wire bytes, never grow them.

_SYNC_CODEC_RAW, _SYNC_CODEC_NARROW, _SYNC_CODEC_Q8 = 0, 1, 2
_SYNC_CODEC_BUCKET = 3  # ISSUE 13: sparse nonzero encoding (sketch lanes)
_SYNC_CODEC_NAMES = ("raw", "narrow", "q8", "bucket")
_DESC_COLS = 9
_QUANT_LANES = (Reduction.SUM, Reduction.MAX, Reduction.MIN)


def _encode_sync_entry(
    red: Reduction, local: Optional[np.ndarray], quantize: bool
) -> Tuple[int, Optional[bytes]]:
    """Pick and run the wire codec for one entry: ``(codec_id, encoded
    bytes or None)``. Raw (``(0, None)``) whenever quantization is off,
    the lane is not additive, or encoding would not shrink the entry.
    Integer lanes race the lossless candidates — min-offset narrowing vs
    the sparse bucket-payload codec (the resident sketch histograms'
    natural shape: few occupied buckets in a large count array) — and the
    smaller encoding wins per entry."""
    if not quantize or local is None or red not in _QUANT_LANES:
        return _SYNC_CODEC_RAW, None
    if local.dtype.kind in "iu":
        enc = _quant.narrow_int_encode(local)
        enc_b = _quant.bucket_payload_encode(local)
        if enc_b is not None and (enc is None or len(enc_b) < len(enc)):
            return _SYNC_CODEC_BUCKET, enc_b
        if enc is not None:
            return _SYNC_CODEC_NARROW, enc
    elif (
        red is Reduction.SUM
        and local.dtype == np.float32
        and local.size >= _quant.Q8_MIN_ELEMENTS
    ):
        if not np.isfinite(local).all():
            # would have quantized, but the values cannot be represented:
            # fall back to the raw lane LOUDLY (the error-channel shape)
            _obs.counter("toolkit.sync.quantize_fallbacks", reason="nonfinite")
            return _SYNC_CODEC_RAW, None
        # the ONE finiteness scan above is authoritative — skip q8's own
        enc = _quant.q8_encode(local, check_finite=False)
        if enc is not None:
            return _SYNC_CODEC_Q8, enc
    return _SYNC_CODEC_RAW, None


def _cat_cache_concat(value) -> Optional[jax.Array]:
    """Concatenate a CAT state's non-empty cache entries into one array
    (``None`` for an empty cache). Shared by the per-metric and collection
    gather paths so both compact caches — and promote mixed dtypes — the
    same way."""
    cache = list(value) if isinstance(value, (list, deque)) else [value]
    nonempty = [v for v in cache if v.ndim and v.shape[0]]
    return jnp.concatenate(nonempty, axis=0) if nonempty else None


def _collection_entries(metrics: Dict[str, Metric]):
    entries = []
    for mkey, metric in metrics.items():
        sd = metric.state_dict()
        for name, red in metric._state_name_to_reduction.items():
            value = sd[name]
            if red is Reduction.CAT:
                cat = _cat_cache_concat(value)
                local = None if cat is None else np.asarray(cat)
            elif red is Reduction.WINDOW:
                # deque of same-shape per-update rows -> ONE stacked array;
                # the leading axis is the boundary structure a CAT concat
                # would destroy (empty window = the empty-entry descriptor).
                # Stack on device, read back ONCE — a per-row np.asarray
                # loop would pay one host transfer per window entry
                local = (
                    np.asarray(jnp.stack(list(value))) if len(value) else None
                )
            else:
                local = np.asarray(value)
            entries.append((mkey, name, red, local))
    return entries


def _encode_entry_descriptor(
    local: Optional[np.ndarray],
    codec: int = _SYNC_CODEC_RAW,
    enc_nbytes: int = 0,
) -> list:
    if local is None:
        return [0, -1, 0, 0, 0, 0, 0, 0, 0]  # empty CAT cache
    if local.ndim > _MAX_CAT_RANK:
        # oversized rank: encode it rather than raising here — a one-sided
        # pre-collective raise would hang the peers; _check_cat_descriptors
        # fails uniformly on every rank after the exchange
        return [0, local.ndim, 0, 0, 0, 0, 0, 0, 0]
    codes = [
        i for i, d in enumerate(_CAT_DTYPES) if np.dtype(jnp.dtype(d)) == local.dtype
    ]
    code = codes[0] if codes else -1
    shape = list(local.shape) + [0] * (_MAX_CAT_RANK - local.ndim)
    d0 = shape[0] if local.ndim else 1
    return (
        [d0, local.ndim, code]
        + shape[1:_MAX_CAT_RANK]
        + [codec, enc_nbytes]
    )


def _window_keep_counts(d0: np.ndarray, maxlen: int) -> np.ndarray:
    """Per-rank surviving row counts for one WINDOW entry, given every
    rank's gathered row count ``d0`` (group order) and the deque ``maxlen``.

    The install-time fold keeps the NEWEST ``maxlen`` rows of the
    rank-ordered concatenation (``get_synced_metric``), so a row from rank
    ``r`` survives only if fewer than ``maxlen`` rows follow it — i.e. rank
    ``r`` contributes its newest ``clamp(maxlen - rows_after_r, 0, d0_r)``
    rows, where ``rows_after_r`` is the total row count of ranks > r. The
    kept counts always total ``min(maxlen, sum(d0))``: rows that cannot
    survive the fold need not cross the wire at all."""
    d0 = np.maximum(np.asarray(d0, dtype=np.int64), 0)
    rows_after = np.concatenate(
        [np.cumsum(d0[::-1])[::-1][1:], np.zeros((1,), np.int64)]
    )
    return np.clip(maxlen - rows_after, 0, d0)


def _entry_nbytes(desc: np.ndarray) -> int:
    ndim = int(desc[1])
    if ndim < 0:
        return 0
    if int(desc[7]):  # encoded entry: the wire length IS the descriptor's
        return int(desc[8])
    dtype = np.dtype(jnp.dtype(_CAT_DTYPES[int(desc[2])]))
    shape = _entry_shape(desc)
    n = 1
    for d in shape:
        n *= d
    return n * dtype.itemsize


def _entry_shape(desc: np.ndarray) -> tuple:
    ndim = int(desc[1])
    if ndim <= 0:
        return ()
    return (int(desc[0]),) + tuple(int(d) for d in desc[3 : 3 + ndim - 1])


def _schema_digest_row(metrics: Dict[str, Metric]) -> list:
    """Header row for the descriptor exchange: entry count + 24 bytes of a
    SHA-256 digest over the ordered ``(metric key, metric class, state name,
    reduction, config-extra)`` schema. The byte payload in round 2 is decoded
    positionally, so every rank MUST enumerate the same entries in the same
    order; this row turns a violated assumption (previously a silent
    mis-decode whenever shapes and dtypes happened to coincide) into a
    uniform post-exchange error. The metric class is part of the schema so
    two *different* metric types with coinciding state names/reductions
    still mismatch; metrics with fold-relevant configuration (e.g. windowed
    metrics' ``window_size``) expose it via ``_sync_schema_extra`` so
    config-drifted replicas mismatch too — the typed fold never calls
    ``merge_state``, which is where the local eager validation lives."""
    import hashlib

    schema = []
    for mkey, metric in metrics.items():  # same order as _collection_entries
        extra = tuple(getattr(metric, "_sync_schema_extra", ()))
        for name, red in metric._state_name_to_reduction.items():
            schema.append(
                (mkey, type(metric).__qualname__, name, red.name) + extra
            )
    digest = hashlib.sha256(repr(schema).encode()).digest()[:24]
    # padded to the descriptor width; the pad stays zero so old and new
    # header rows compare equal column-for-column
    return (
        [len(schema)]
        + np.frombuffer(digest, dtype="<i4").tolist()
        + [0] * (_DESC_COLS - 7)
    )


def _gather_collection_states(
    metrics: Dict[str, Metric],
    group: Optional[Tuple[int, ...]] = None,
    quantize: bool = False,
) -> List[Dict[str, Dict[str, TState]]]:
    """All-gather every rank's states for a whole collection in exactly two
    collective rounds (full world, or the ``group`` subgroup); returns
    per-rank ``{metric_key: state_dict}`` in group order.

    Row 0 of the descriptor matrix is a schema digest
    (:func:`_schema_digest_row`) validated post-exchange, so ranks that
    built their collections in different orders fail loudly on every rank
    instead of folding bytes into the wrong states. (Ranks with *different
    entry counts* diverge in collective shape and fail inside XLA already;
    the digest covers the dangerous same-shape case.)

    ``quantize`` engages the wire codecs for additive lanes (see the
    lane-codec comment block above); the payload round then carries each
    entry's encoded form and the descriptor's codec column drives every
    peer's decode. Still exactly two rounds — encoding is pure local
    work."""
    world = len(group) if group is not None else _world_size()
    entries = _collection_entries(metrics)
    encodings = [
        _encode_sync_entry(red, local, quantize)
        for _, _, red, local in entries
    ]
    desc = np.asarray(
        [_schema_digest_row(metrics)]
        + [
            _encode_entry_descriptor(
                local, codec, len(enc) if enc is not None else 0
            )
            for (_, _, _, local), (codec, enc) in zip(entries, encodings)
        ],
        dtype=np.int32,
    ).reshape(len(entries) + 1, _DESC_COLS)
    all_desc = _allgather_stacked(desc, group, "descriptor", "typed").reshape(
        world, len(entries) + 1, _DESC_COLS
    )
    # uniform validation AFTER the exchange (a one-sided raise would hang the
    # payload collective on the other ranks): first the schema digest, then
    # the per-entry wire-format checks. Every rank sees identical gathered
    # rows, so any raise here happens on every rank.
    header = all_desc[:, 0, :]
    if not (header == header[0]).all():
        raise RuntimeError(
            "Collection sync schema mismatch: ranks enumerated different "
            "(metric key, state name, reduction, config) entries "
            f"(digest rows: {header.tolist()}). Every process must build "
            "the collection with the same metric keys, construction order, "
            "metric types and fold-relevant configuration (e.g. windowed "
            "metrics' window_size/num_tasks) before calling sync."
        )
    all_desc = all_desc[:, 1:, :]
    # column layout matches the CAT wire descriptor
    # ([d0, ndim, dtype_code, ...]) so the same checker serves
    for e, (mkey, name, red, _) in enumerate(entries):
        _check_cat_descriptors(f"{name} of metric {mkey}", all_desc[:, e, :])
    # ---- WINDOW wire bound (round-5 verdict weak #5). The install-time
    # fold keeps only the newest ``maxlen`` rows of the rank-ordered
    # concatenation, so after the descriptor round — where every rank
    # learns every rank's row counts — each rank truncates its WINDOW
    # payload to the rows that can actually survive. The byte round then
    # carries at most ``maxlen`` window rows TOTAL across the whole world
    # instead of ``maxlen × world_size`` (the descriptor round is
    # unaffected: a fixed 28 bytes per entry per rank). Every rank computes
    # the same kept-counts from the same gathered descriptors, so payload
    # layout and decode stay in agreement. Unbounded deques (maxlen=None)
    # have no fold bound and ship in full.
    my_pos = (
        group.index(_process_index()) if group is not None else _process_index()
    )
    entries = list(entries)
    for e, (mkey, name, red, local) in enumerate(entries):
        # gate on the DESCRIPTORS, never on this rank's own `local`: every
        # rank must apply the identical all_desc rewrite (totals, padding
        # and decode offsets are derived from it), including ranks whose
        # own window is empty this sync
        if red is not Reduction.WINDOW:
            continue
        maxlen = getattr(
            metrics[mkey]._state_name_to_default[name], "maxlen", None
        )
        if maxlen is None:
            continue
        keep = _window_keep_counts(all_desc[:, e, 0], maxlen)
        if (keep == np.maximum(all_desc[:, e, 0], 0)).all():
            continue
        if not all_desc.flags.writeable:  # allgather output may be a view
            all_desc = np.array(all_desc)
        all_desc[:, e, 0] = keep
        if local is not None:  # empty local window: nothing to truncate
            entries[e] = (
                mkey,
                name,
                red,
                local[local.shape[0] - int(keep[my_pos]):],
            )
    if _obs.enabled():
        # per-Reduction-lane payload accounting: how many bytes each lane
        # (SUM/MAX/MIN/CAT/WINDOW/NONE) contributes to the byte-payload
        # round. ``lane_bytes`` keeps the post-truncation RAW bytes
        # (dashboard continuity across the codec introduction);
        # ``lane_bytes_encoded`` records what actually crosses the wire,
        # per codec — the pair is the observable behind the >=4x claim
        # (and must agree exactly on every raw-codec entry)
        for (_, _, red, local), (codec, enc) in zip(entries, encodings):
            raw_bytes = float(local.nbytes) if local is not None else 0.0
            _obs.counter("toolkit.sync.lane_bytes", raw_bytes, lane=red.name)
            _obs.counter(
                "toolkit.sync.lane_bytes_encoded",
                float(len(enc)) if enc is not None else raw_bytes,
                lane=red.name,
                codec=_SYNC_CODEC_NAMES[codec],
            )
    totals = [
        sum(_entry_nbytes(all_desc[r, e]) for e in range(len(entries)))
        for r in range(world)
    ]
    max_total = max(max(totals), 1)
    payload = np.zeros(max_total, dtype=np.uint8)
    offset = 0
    for (_, _, _, local), (_codec, enc) in zip(entries, encodings):
        if enc is not None:
            raw = np.frombuffer(enc, dtype=np.uint8)
        elif local is None:
            continue
        else:
            raw = np.ascontiguousarray(local).view(np.uint8).reshape(-1)
        payload[offset : offset + raw.size] = raw
        offset += raw.size
    all_bytes = _allgather_stacked(
        payload, group, "payload", "typed"
    ).reshape(world, max_total)
    gathered: List[Dict[str, Dict[str, TState]]] = [
        {mkey: {} for mkey in metrics} for _ in range(world)
    ]
    for r in range(world):
        offset = 0
        for e, (mkey, name, red, _) in enumerate(entries):
            d = all_desc[r, e]
            nbytes = _entry_nbytes(d)
            if int(d[1]) < 0:  # empty CAT
                gathered[r][mkey][name] = []
                continue
            dtype = np.dtype(jnp.dtype(_CAT_DTYPES[int(d[2])]))
            shape = _entry_shape(d)
            wire = all_bytes[r, offset : offset + nbytes].tobytes()
            codec = int(d[7])
            if codec == _SYNC_CODEC_NARROW:
                value = _quant.narrow_int_decode(wire, dtype, shape)
            elif codec == _SYNC_CODEC_Q8:
                value = _quant.q8_decode(wire, shape)
            elif codec == _SYNC_CODEC_BUCKET:
                value = _quant.bucket_payload_decode(wire, dtype, shape)
            else:
                value = np.frombuffer(wire, dtype=dtype).reshape(shape)
            offset += nbytes
            decoded = jnp.asarray(value)
            if decoded.dtype != value.dtype:
                # 64-bit state with jax x64 disabled: jnp.asarray would
                # silently truncate (an int64 count >= 2^31 wraps). Keep the
                # faithful numpy array — TState accepts numpy leaves and
                # _fold_states' arithmetic works on them exactly.
                decoded = value
            if red is Reduction.CAT:
                gathered[r][mkey][name] = [decoded]
            else:
                gathered[r][mkey][name] = decoded
    return gathered


@_traced("toolkit.sync_and_compute_collection")
def sync_and_compute_collection(
    metrics: Dict[str, Metric],
    recipient_rank: _RecipientRank = 0,
    *,
    processes: _ProcessGroup = None,
    timeout_s: Optional[float] = None,
    on_failure: str = "raise",
    quantize: Optional[bool] = None,
) -> Optional[Dict[str, Any]]:
    """Sync and compute a named collection of metrics in ONE gather pass.

    All metrics' array/CAT states ride a single two-round typed exchange
    (descriptors, then one concatenated byte payload); metrics needing the
    object lane (dict-keyed / CUSTOM states) share a single pickled gather.
    ``processes`` restricts the sync to a subgroup (reference
    ``process_group`` semantics). Results follow :func:`sync_and_compute`
    semantics per metric: ``None`` on non-recipient ranks.

    ``timeout_s`` bounds ALL of the collection's collective rounds under
    one shared budget; on failure with ``on_failure="local"`` every
    calling rank gets ``{name: local compute}`` for the whole collection
    (one degraded exchange degrades every member uniformly — a mixed
    synced/unsynced result dict would be unreadable)."""
    if not (isinstance(recipient_rank, int) or recipient_rank == "all"):
        raise ValueError(
            "recipient_rank should be an integer or 'all', "
            f"got {recipient_rank} instead."
        )
    _check_failure_policy(on_failure)
    _check_timeout_s(timeout_s)
    group = _resolve_group(processes)
    _check_group_recipient(group, recipient_rank)
    world = len(group) if group is not None else _world_size()
    if world == 1:
        _logger.warning(
            "World size is 1, and metric(s) not synced. "
            "returning the input metric(s)."
        )
        return {name: m.compute() for name, m in metrics.items()} or None
    for m in metrics.values():
        m._prepare_for_merge_state()
    obj_lane = {k: m for k, m in metrics.items() if _needs_object_sync(m)}
    arr_lane = {k: m for k, m in metrics.items() if k not in obj_lane}
    try:
        with _sync_deadline(timeout_s):
            gathered = (
                _gather_collection_states(
                    arr_lane,
                    group,
                    quantize=_quant.sync_quantize_enabled(quantize),
                )
                if arr_lane
                else None
            )
            obj_gathered = (
                _allgather_object(
                    {
                        k: _tree_to_host(m.state_dict())
                        for k, m in obj_lane.items()
                    },
                    group,
                )
                if obj_lane
                else None
            )
    except SyncError as err:
        _sync_failure(err, on_failure)
        return {name: m.compute() for name, m in metrics.items()} or None
    if recipient_rank != "all" and _process_index() != recipient_rank:
        return None
    out: Dict[str, Any] = {}
    for name, metric in arr_lane.items():
        synced = get_synced_metric(
            metric,
            recipient_rank,
            processes=processes,
            _gathered=[g[name] for g in gathered],
        )
        if synced is not None:
            out[name] = synced.compute()
    for name, metric in obj_lane.items():
        replicas = []
        for rank_payload in obj_gathered:
            rep = clone_metric(metric)
            rep.load_state_dict(_tree_to_device(rank_payload[name]))
            replicas.append(rep)
        out[name] = replicas[0].merge_state(replicas[1:]).compute()
    return out or None

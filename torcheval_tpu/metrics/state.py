"""Metric state containers and collective-reduction declarations.

TPU-first design departure from the reference: in torcheval, merge semantics
live only inside each metric's ``merge_state`` method, and distributed sync
pickles whole ``Metric`` objects through ``dist.gather_object``
(``/root/reference/torcheval/metrics/toolkit.py:235-257``). Here every state
variable *declares* its reduction (:class:`Reduction`) at registration time, so
the sync layer can compile the merge into typed XLA collectives —
``lax.psum`` for SUM states, ``lax.pmax``/``lax.pmin`` for MAX/MIN,
``all_gather`` + concat for CAT (sample-cache) states — instead of moving
pickled Python objects over the wire.

Supported state container types mirror the reference's ``TState`` union
(``/root/reference/torcheval/metrics/metric.py:18-20``):

* ``jax.Array`` — the fast path; lives in HBM, updated by jitted kernels.
* ``list[jax.Array]`` — unbounded sample caches (AUROC/PRC/Cat). Appends are
  O(1) host ops; compaction to a single array happens at compute / pre-merge.
* ``dict[Any, jax.Array]`` — host-side keyed accumulators (test fixtures; no
  shipped metric uses them, see SURVEY §7).
* ``deque[jax.Array]`` — bounded window state (the shipped windowed metrics:
  ``WindowedClickThroughRate`` / ``WindowedWeightedCalibration``).
"""

from __future__ import annotations

import enum
import functools
from collections import defaultdict, deque
from typing import Any, Deque, Dict, List, Union

import jax
import jax.numpy as jnp

TState = Union[jax.Array, List[jax.Array], Dict[Any, jax.Array], Deque[jax.Array]]


class Reduction(enum.Enum):
    """How a state variable combines across metric replicas / mesh ranks."""

    SUM = "sum"  # elementwise add            -> lax.psum
    MAX = "max"  # elementwise max            -> lax.pmax
    MIN = "min"  # elementwise min            -> lax.pmin
    CAT = "cat"  # concatenate along axis 0   -> all_gather(..., tiled=True)
    NONE = "none"  # replicated / identical on all ranks (e.g. threshold grids)
    # bounded deque of SAME-SHAPE per-update rows: ranks' entries extend in
    # rank order, the deque maxlen keeps the newest. Rides the typed wire as
    # ONE stacked array per rank (the leading axis preserves per-update
    # boundaries a CAT concat would destroy)
    WINDOW = "window"
    CUSTOM = "custom"  # only mergeable via the metric's merge_state()


def check_state_type(name: str, value: Any) -> None:
    """Validate a state value against the TState union (recursively)."""
    if isinstance(value, jax.Array) or hasattr(value, "shape") and hasattr(value, "dtype"):
        return
    if isinstance(value, list) or isinstance(value, deque):
        for v in value:
            if not (hasattr(v, "shape") and hasattr(v, "dtype")):
                raise TypeError(
                    f"Element of state {name!r} must be an array, got {type(v)!r}."
                )
        return
    if isinstance(value, dict):
        for k, v in value.items():
            if not (hasattr(v, "shape") and hasattr(v, "dtype")):
                raise TypeError(
                    f"Value of state {name!r}[{k!r}] must be an array, got {type(v)!r}."
                )
        return
    raise TypeError(
        f"State {name!r} must be a jax.Array, list, dict or deque of jax.Array, "
        f"got {type(value)!r}."
    )


def _put_leaf(value, device, *, strict_layout: bool = False):
    """Place one array leaf on ``device``.

    ``strict_layout`` distinguishes two callers on multi-process meshes,
    where a global array in a *different* layout cannot be re-placed
    (cross-host transfer):

    * state placement (``put_state`` / ``Metric.to``), ``strict_layout=False``
      — any global array on the same mesh passes through unchanged. Correct:
      CAT caches are legitimately data-sharded and every compute kernel
      consumes them in whatever layout they carry.
    * layout-promising APIs (``parallel.replicate``), ``strict_layout=True``
      — a mismatched layout raises rather than silently returning something
      other than what the API name promises.
    """
    import numpy as np

    if not isinstance(value, (jax.Array, np.ndarray)):
        # scalars, python sequences, torch tensors: jnp.asarray as before.
        # numpy stays raw — device_put places it natively in one transfer,
        # where asarray would pay a separate transfer dispatch first.
        value = jnp.asarray(value)
    if isinstance(value, jax.Array) and not isinstance(
        device, jax.sharding.Sharding
    ):
        # single-device fast path, mirroring Metric._input: device_put costs
        # ~75 µs host-side even as a placement no-op (and a full dispatch
        # floor on tunneled backends) — skip it when the buffer is already
        # resident on the target device
        try:
            if value.devices() == {device}:
                # exact single-device residency only: membership alone would
                # pass a mesh-sharded array through un-gathered when the
                # target is merely one of its shard devices
                return value
        except Exception:
            pass
    if (
        isinstance(device, jax.sharding.Sharding)
        and not device.is_fully_addressable
    ):
        # multi-process mesh: device_put would need a cross-host transfer,
        # which backends may not support. State placed through .to() is
        # process-local (replicated-identical on every host by SPMD
        # lockstep), so build the global array from each host's own copy —
        # no bytes cross hosts.
        if (
            isinstance(value, jax.Array)
            and getattr(value.sharding, "device_set", None) == device.device_set
        ):
            if not strict_layout or value.sharding.is_equivalent_to(
                device, value.ndim
            ):
                return value  # already global on this mesh
            raise ValueError(
                f"cannot re-place a global array (sharding {value.sharding}) "
                f"to {device} on a multi-process mesh: cross-host transfers "
                "are not available. Build the value in the target layout "
                "(jax.make_array_from_process_local_data / a jitted "
                "computation with the right out_shardings) instead."
            )
        try:
            host = np.asarray(value)
        except RuntimeError as e:
            # a non-addressable global array from a DIFFERENT mesh cannot be
            # read on this host; np.asarray's RuntimeError names none of that
            raise ValueError(
                f"cannot place a global array (sharding "
                f"{getattr(value, 'sharding', None)}) onto {device}: its "
                "shards are not addressable from this process and cross-host "
                "transfers are not available. Build the value on the target "
                "mesh (jax.make_array_from_process_local_data / a jitted "
                "computation with the right out_shardings) instead."
            ) from e
        return jax.make_array_from_callback(
            host.shape, device, lambda idx: host[idx]
        )
    return jax.device_put(value, device)


def put_state(value: TState, device) -> TState:
    """Place a state value (any container type) on ``device``."""
    if isinstance(value, (list, deque)):
        moved = [_put_leaf(v, device) for v in value]
        if isinstance(value, deque):
            return deque(moved, maxlen=value.maxlen)
        return moved
    if isinstance(value, dict):
        out = {k: _put_leaf(v, device) for k, v in value.items()}
        if isinstance(value, defaultdict) and value.default_factory is not None:
            d = defaultdict(value.default_factory)
            d.update(out)
            return d
        return out
    # host array-likes (numpy defaults) go straight to _put_leaf's
    # device_put — a jnp.asarray here would pay a separate transfer dispatch
    # before the placement
    return _put_leaf(value, device)


@functools.lru_cache(maxsize=256)
def _zeros_template(shape, dtype):
    return jnp.zeros(shape, dtype)


def zeros_state(shape=(), dtype=jnp.float32):
    """A zeros array for a state default.

    On backends where donation is off (``utils/platform.py`` — every buffer
    stays immutable forever), the SAME cached device template is returned
    for a given (shape, dtype): ``copy_state`` aliases it and ``put_state``
    passes it through, so metric construction/reset costs ZERO device
    dispatches (0.2-8 ms each on a tunneled chip). With donation on, a
    shared device template would be invalidated by the first donated window
    step (ISSUE 6 donates EVERY live state tree at window close), so a
    HOST-side ``np.zeros`` is returned instead: defaults are schema
    templates that only become device state through ``put_state`` (at
    ``_add_state`` and every ``reset``), which mints the fresh placed
    buffer in ONE transfer — where a fresh ``jnp.zeros`` default paid 3-4
    dispatches per state for buffers that were immediately copied again
    (~0.9 ms per 2-state metric construction on the bench CPU, the whole
    per-run host budget). The live-state freshness guard is
    regression-tested in tests/metrics/test_window_step.py (copy-on-read
    template guard).
    """
    import numpy as np

    from torcheval_tpu.utils.platform import donation_pipelines

    shape = tuple(shape) if hasattr(shape, "__len__") else (shape,)
    if donation_pipelines():
        return np.zeros(shape, jnp.dtype(dtype))
    return _zeros_template(shape, jnp.dtype(dtype))


def _copy_leaf(value):
    # real buffer copies, not aliases: the donated window step / deferred
    # folds (metrics/deferred.py) invalidate live state buffers — and the
    # window step also donates library-owned CHUNK buffers — so a default
    # snapshot or state_dict that merely shared the array would die with
    # it. Arrays are immutable, but buffer LIFETIME is not — EXCEPT when
    # this process never donates (tunneled backends gate donation off,
    # utils/platform.py):
    # then aliasing an immutable array is safe and skips a device dispatch.
    # That dispatch is the dominant cost of metric construction/reset on a
    # tunneled chip: ~2 copy dispatches per state × a 0.2-8 ms floor was
    # measured at 25-47 ms per fresh 3-state metric, vs ~6 ms for the whole
    # fold it precedes.
    if isinstance(value, jax.Array):
        from torcheval_tpu.utils.platform import donation_pipelines

        if not donation_pipelines():
            return value
        return jnp.copy(value)
    if hasattr(value, "copy"):
        return value.copy()  # numpy leaf: also guards against host mutation
    return value


def copy_state(value: TState) -> TState:
    """Deep copy of a state value: fresh array buffers, copied containers
    (the reference's detach+clone semantics, ``metric.py:158-219``)."""
    if isinstance(value, list):
        return [_copy_leaf(v) for v in value]
    if isinstance(value, deque):
        return deque((_copy_leaf(v) for v in value), maxlen=value.maxlen)
    if isinstance(value, defaultdict):
        d = defaultdict(value.default_factory)
        d.update({k: _copy_leaf(v) for k, v in value.items()})
        return d
    if isinstance(value, dict):
        return {k: _copy_leaf(v) for k, v in value.items()}
    return _copy_leaf(value)

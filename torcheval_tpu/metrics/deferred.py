"""Deferred batch folding: make ``update()`` an O(1) host append.

TPU-first rationale. The reference's hot loop dispatches one scatter-add per
``update()`` call (``/root/reference/torcheval/metrics/functional/
classification/f1_score.py:182-190``) — cheap on CPU where dispatch is a
function call, but on an accelerator every dispatch pays an enqueue (and on
this project's tunneled chip, 0.2-5 ms of transport). Worse, per-batch
kernels are *small*: a (8192, 5) argmax+compare keeps the chip busy for tens
of microseconds; the round trip dominates by 10-100×.

So deferring metrics here do not fold per batch. ``update()`` validates shapes
(host metadata only), places the arrays, and **appends them to a pending
list**. The actual math runs later as ONE fused XLA program over the pending
batches, triggered by:

* a read of the logical state — ``compute`` / ``state_dict`` / ``to`` /
  ``merge_state`` / pickling / deepcopy / ``_prepare_for_merge_state``;
* a memory budget (``_DEFER_BUDGET_BYTES`` of pending update args) or a
  chunk-count cap (``_DEFER_MAX_CHUNKS``), so an unbounded stream folds
  periodically and pending device buffers can be freed.

Since the lane unification (ISSUE 2) the mixin carries every array-state
metric — the counter families (accuracy, F1/precision/recall, confusion),
the regression/NE sufficient-statistic metrics, and the aggregations
(Sum/Mean and, via a state-threading reduce, Max/Min) — so a whole
``MetricCollection`` folds in one XLA program per budget window and XLA
CSEs the members' shared math.

The fold itself has two physical shapes, picked at trace time per pending
signature — always ONE dispatch either way:

* **Scan fold (the steady-loop path).** When every pending chunk shares one
  full ``(shape, dtype)`` signature — the common case in a constant-batch
  eval loop — the fold program stacks the chunks into ONE
  ``(num_chunks, batch, ...)`` operand per update argument and runs
  ``jax.lax.scan`` over the leading axis (Podracer's
  many-logical-steps-in-one-device-program recipe, arXiv:2104.06272).
  The metric math (``fold_fn``) is traced ONCE as the scan body instead of
  being unrolled per chunk, so trace size and compile time are O(1) in the
  chunk count, and the retrace-signature space is O(1) per batch shape — a
  steady constant-batch loop compiles ``deferred.fold`` at most twice per
  batch shape (the valve-cadence chunk count plus the final partial flush),
  which the ``obs`` recompile watchdog verifies. The stack happens INSIDE
  the jitted program: stacking on the host would pay one extra dispatch per
  update argument, and dispatches are the scarce resource on a tunneled
  chip. Applies to per-sample-reduce folds (``_fold_per_chunk``);
  state threads through the scan carry, which is how non-additive states
  (Max/Min extrema via ``_fold_reduce``) ride the same machinery.
* **Concat fold (everything else).** Concat-regime folds
  (``_fold_per_chunk = False``) take one ``jnp.concatenate`` over the
  pending columns — their count kernels want the whole stream as a single
  large-N operand. Ragged chunk signatures under a per-sample-reduce fold
  take the per-chunk accumulation loop (correct for any shape mix, trace is
  O(chunk count) — which is why the scan path exists). Mesh-sharded pending
  chunks also keep this path: the SPMD partitioner, not a leading stack
  axis, should own the batch dimension.

Concat-regime folds (``_fold_per_chunk = False``: confusion, F1 triples)
still see the whole stream as one large-N operand either way, so the
auto-picked lowering rides its *large-N* regime — e.g. the confusion update
at (N=1.3M, C=1000) runs the flat joint scatter at ~110M preds/s where 13
separate 100k-batch one-hot matmuls manage ~24M (docs/performance.md).

Semantics are unchanged: folding is a physical-representation change with the
same logical state (sums and extrema are order-insensitive — grouping cannot
change them beyond float associativity, and counts are integer-exact), the
same trick the reference itself plays in ``_prepare_for_merge_state``
(``metric.py:112-121``). Two visible differences, documented here:

* reading a state attribute directly (``m.num_correct``) between updates sees
  the *folded-so-far* value; go through ``state_dict()``/``compute()`` (which
  fold first) for the logical value.
* a jitted fold compiles per pending-shape signature. Steady loops (constant
  batch size) see one or two signatures; wildly varying batch shapes fall
  back to more compiles, never wrong results. Mixed signatures (e.g. a
  (N, C) score batch after (N,) label batches) flush the pending list first
  so one fold never mixes ranks.

Tracer transparency: when ``update`` is called inside someone else's trace
(a user jitting their eval step around a metric), deferral would leak
tracers into the pending list — so tracer args take the eager fold path,
which is exactly the pre-deferral behavior.

Donation caveat: on backends where ``donation_pipelines()`` is true, a fold
donates the previous state buffers. A raw reference captured from a state
attribute (``ref = m.num_total``) dies at the next fold — read state through
``state_dict()`` / ``compute()`` instead of holding array refs across
updates.

Observability: every fold dispatch increments ``deferred.folds{entry=,path=}``
(and ``deferred.folded_chunks{entry=}`` with the chunk count) in the obs
registry while obs is enabled — the counters a dispatch-count regression
test asserts O(1) programs per budget window on (tests/obs).
"""

from __future__ import annotations

import weakref
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.obs import registry as _obs
from torcheval_tpu.obs.recompile import watched_jit as _watched_jit


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


# Live unmanaged deferred metrics (round-4 verdict ask 8): when one folds, it
# scans here for peers whose pending chunks are the IDENTICAL placed arrays —
# the signature of standalone metrics fed the same batches (`cm.update(x, y);
# f1.update(x, y)` outside any collection) — and folds the whole group in one
# program, so XLA dedupes the shared math exactly as the MetricCollection
# lane does. WeakSet: registration must not keep metrics alive.
_live_deferred: "weakref.WeakSet" = weakref.WeakSet()
_defer_seq_counter = 0


def _chunks_identical(a, b) -> bool:
    """True when two pending lists hold the same chunk ARRAY OBJECTS in the
    same order — identity, not value: it is free to check and exactly
    captures "fed the same placed batches"."""
    return len(a) == len(b) and all(
        len(c) == len(h) and all(x is y for x, y in zip(c, h))
        for c, h in zip(a, b)
    )


def _is_prefix(short, long) -> bool:
    """``short`` is a (non-strict) identity-prefix of ``long``. Standalone
    metrics fed the same stream are usually one chunk apart mid-loop (A got
    batch N before B did), so exact equality would miss every
    valve-triggered fold; prefix grouping folds the common part and leaves
    the stragglers pending."""
    return len(short) <= len(long) and _chunks_identical(
        short, long[: len(short)]
    )


def _add(state, delta):
    return state + delta


def _combine(states, deltas, fold_reduce):
    """Merge ``deltas`` into ``states`` with the metric's reduce (add for
    accumulator states, max/min for extrema — the state-threading fold that
    lets non-additive states ride the same machinery). EVERY state is
    returned (merged), not just the delta'd ones: under donation all input
    buffers are invalidated, so an untouched state must still be threaded
    through to a live output buffer."""
    red = _add if fold_reduce is None else fold_reduce
    return {**states, **{n: red(states[n], d) for n, d in deltas.items()}}


def _uniform_chunks(chunks) -> bool:
    """Every chunk shares one full (shape, dtype) signature. Shapes are
    static inside a trace, so the fold bodies branch on this at TRACE time —
    the compiled program contains only the selected path."""
    head = chunks[0]
    for c in chunks[1:]:
        if len(c) != len(head):
            return False
        for x, h in zip(c, head):
            if x.shape != h.shape or x.dtype != h.dtype:
                return False
    return True


def _scan_fold(states_by_key, chunks, specs):
    """State-threading scan fold of uniform chunks for one or more
    ``(key, fold_fn, fold_params, fold_reduce)`` specs — the single shared
    scan recipe for the solo and group dispatch bodies (each member's fold
    runs inside ONE ``lax.scan`` step, so shared math dedupes per step).

    The chunks past the first stack INSIDE the program (a host-side stack
    would pay an extra dispatch per column) into one
    ``(num_chunks - 1, batch, ...)`` operand per column, and ``lax.scan``
    folds them with the metric math traced ONCE. The first chunk folds
    OUTSIDE the scan so dtype promotion settles the carry structure (an
    int32 counter meeting a float delta promotes on the first combine; the
    scan carry must be shape/dtype-stable)."""

    def step(states, chunk):
        return {
            key: _combine(
                states[key], fold_fn(*chunk, *fold_params), fold_reduce
            )
            for key, fold_fn, fold_params, fold_reduce in specs
        }

    carry = step(states_by_key, chunks[0])
    if len(chunks) == 1:
        return carry
    rest = tuple(jnp.stack(cols, axis=0) for cols in zip(*chunks[1:]))
    carry, _ = jax.lax.scan(
        lambda c, chunk: (step(c, chunk), None), carry, rest
    )
    return carry


def _fold_deltas(chunks, fold_fn, fold_params, per_chunk, fold_reduce):
    """Deltas over the pending batches: one kernel over the concatenated
    stream (count kernels want the large-N regime), or per-chunk kernels with
    reduced deltas when the fold is per-sample independent + reduce
    (``per_chunk``) — a many-operand ``jnp.concatenate`` measured ~1.4× the
    cost of per-chunk accumulation at 200 chunks on v5e, and count kernels
    gain nothing from it there. Ragged-signature fallback for per-chunk
    folds; the steady-loop path is the scan fold (module doc)."""
    if per_chunk and len(chunks) > 1:
        red = _add if fold_reduce is None else fold_reduce
        acc = None
        for chunk in chunks:
            deltas = fold_fn(*chunk, *fold_params)
            acc = (
                deltas
                if acc is None
                else {n: red(acc[n], d) for n, d in deltas.items()}
            )
        return acc
    cat = tuple(
        jnp.concatenate(cols, axis=0) if len(cols) > 1 else cols[0]
        for cols in zip(*chunks)
    )
    return fold_fn(*cat, *fold_params)


def _fold_body(
    states, chunks, fold_fn, fold_params, per_chunk, fold_reduce, scan_ok
):
    if scan_ok and per_chunk and len(chunks) > 1 and _uniform_chunks(chunks):
        spec = (("s", fold_fn, fold_params, fold_reduce),)
        return _scan_fold({"s": states}, chunks, spec)["s"]
    deltas = _fold_deltas(chunks, fold_fn, fold_params, per_chunk, fold_reduce)
    return _combine(states, deltas, fold_reduce)


# Module-level jitted dispatchers shared by ALL metric instances: the trace
# cache keys on (fold_fn identity, fold_params, pending pytree signature), so
# a fresh metric instance reuses the compiled fold instead of re-tracing a
# wide concat program per instance (measured ~200 ms of host tracing for a
# 200-chunk fold — more than the fold itself; the scan path cuts exactly
# that cost to O(1)).
# watched_jit: the deferred fold is the canonical retrace-storm site (the
# trace cache keys on the pending pytree signature — wildly varying batch
# shapes recompile the fold per signature) and the watchdog's per-signature
# counts make that visible; the scope name attributes the fold's device
# time in XLA traces.
_FOLD_STATICS = ("fold_fn", "fold_params", "per_chunk", "fold_reduce", "scan_ok")
_fold_dispatch = partial(
    _watched_jit, name="deferred.fold", static_argnames=_FOLD_STATICS
)(_fold_body)
_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.fold",
    static_argnames=_FOLD_STATICS,
    donate_argnums=(0,),
)(_fold_body)


def _group_fold_body(states_by_member, chunks, specs, scan_ok):
    """Fold SEVERAL metrics' pending batches (identical args) in one program.

    ``specs`` is a static tuple of ``(member_key, fold_fn, fold_params,
    per_chunk, fold_reduce)``. Because every member folds the same arrays
    inside one XLA program, common subcomputations dedupe: a
    MulticlassConfusionMatrix and a MulticlassF1Score over the same batch
    share the argmax and (depending on lowerings) the count kernels instead
    of dispatching them twice.

    Under a uniform pending signature (and ``scan_ok``), every per-chunk
    member folds inside ONE shared ``lax.scan`` whose carry holds all their
    states — the members' shared math dedupes per scan step, not just per
    program; concat-regime members keep their large-N concatenated operand
    in the same program.
    """
    uniform = (
        scan_ok and len(chunks) > 1 and _uniform_chunks(chunks)
    )
    out = {}
    scan_specs = []
    for spec in specs:
        key, fold_fn, fold_params, per_chunk, fold_reduce = spec
        if uniform and per_chunk:
            scan_specs.append(spec)
            continue
        deltas = _fold_deltas(
            chunks, fold_fn, fold_params, per_chunk, fold_reduce
        )
        out[key] = _combine(states_by_member[key], deltas, fold_reduce)
    if scan_specs:
        out.update(
            _scan_fold(
                {s[0]: states_by_member[s[0]] for s in scan_specs},
                chunks,
                tuple(
                    (key, fold_fn, fold_params, fold_reduce)
                    for key, fold_fn, fold_params, _, fold_reduce in scan_specs
                ),
            )
        )
    return out


_group_fold_dispatch = partial(
    _watched_jit,
    name="deferred.group_fold",
    static_argnames=("specs", "scan_ok"),
)(_group_fold_body)
_group_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.group_fold",
    static_argnames=("specs", "scan_ok"),
    donate_argnums=(0,),
)(_group_fold_body)


def _scan_allowed(chunks) -> bool:
    """Host-side gate for the scan path: single-device pending arrays only.
    Mesh-sharded chunks keep the concat/per-chunk program — a leading stack
    axis would fight the SPMD partitioner for the batch dimension. (Shape
    uniformity is checked inside the trace, where shapes are static.)"""
    for a in chunks[0]:
        try:
            if len(a.sharding.device_set) != 1:
                return False
        except Exception:
            return False
    return True


def _member_spec(key, m) -> Tuple[Any, ...]:
    """Static per-member fold spec for the group dispatchers."""
    cls = type(m)
    return (key, cls._fold_fn, m._fold_params, cls._fold_per_chunk, cls._fold_reduce)


def _count_fold(entry: str, path: str, n_chunks: int) -> None:
    """Obs accounting: one increment per fold *dispatch* — the quantity the
    dispatch-count regression test bounds (O(1) programs per budget window,
    never O(batches))."""
    _obs.counter("deferred.folds", entry=entry, path=path)
    _obs.counter("deferred.folded_chunks", float(n_chunks), entry=entry)


def group_fold(members: Dict[str, "DeferredFoldMixin"]) -> None:
    """Fold every member's pending batches in ONE dispatch when their pending
    structures are identical (the MetricCollection case: every member was fed
    the same placed arrays); falls back to per-member folds otherwise."""
    pending = [m for m in members.values() if getattr(m, "_pending", None)]
    if not pending:
        return
    head = pending[0]._pending
    aligned = len(pending) == len(members) and all(
        _chunks_identical(m._pending, head) for m in pending[1:]
    )
    if not aligned:
        for m in pending:
            m._fold_now()
        return
    chunks = head
    specs = tuple(_member_spec(key, m) for key, m in members.items())
    states = {
        key: {n: getattr(m, n) for n in m._state_name_to_default}
        for key, m in members.items()
    }
    from torcheval_tpu.utils.platform import donation_pipelines

    dispatch = (
        _group_fold_dispatch_donated
        if donation_pipelines()
        else _group_fold_dispatch
    )
    scan_ok = _scan_allowed(chunks)
    new_states = dispatch(states, chunks, specs=specs, scan_ok=scan_ok)
    _count_fold("group_fold", "scan" if scan_ok else "concat", len(chunks))
    # clear pending only after a successful dispatch (see _fold_now)
    for m in pending:
        m._pending = []
        m._pending_bytes = 0
    for key, m in members.items():
        for n, v in new_states[key].items():
            setattr(m, n, v)


class DeferredFoldMixin:
    """Mixin for array-state metrics: pending-batch cache + lazy fused fold.

    Contract for subclasses::

        def _my_fold(input, target, threshold):   # MODULE-level pure fn:
            ...                                    # math on one (stream of)
            return {"num_tp": ..., "num_fp": ...}  # batches -> {state: delta}

        class MyMetric(DeferredFoldMixin, Metric[jax.Array]):
            _fold_fn = staticmethod(_my_fold)

            def __init__(self, ...):
                super().__init__(device=device)
                self._add_state(...)
                self._init_deferred()
                self._fold_params = (threshold,)   # hashable statics

            def update(self, input, target):
                input, target = self._input(input), self._input(target)
                _my_input_check(input, target)
                self._defer(input, target)
                return self

    ``_fold_fn`` must be a module-level function (shared identity across
    instances — it keys the shared jit cache) taking the update args (a whole
    concatenated stream when ``_fold_per_chunk`` is False, one chunk at a
    time otherwise) followed by ``*_fold_params``. Optional update arguments
    (a per-sample weight) defer as extra positional chunk columns; the fold
    fn discriminates on arity. Deltas merge into state with ``_fold_reduce``
    (``None`` = add; ``jnp.maximum``/``jnp.minimum`` thread extrema states).
    ``compute``/``merge_state`` implementations must call ``_fold_now()``
    (and fold merge sources) before reading state; the :class:`Metric` base
    class folds in ``state_dict``/``to``/``_prepare_for_merge_state``/pickle.
    """

    # pending-args budget before a fold is forced. 256 MB holds e.g. 32 chunks
    # of (2^20, 5) float32 scores+labels; the fold dispatch amortises to
    # ~0.7 ns/byte of pending data even at the tunnel's worst measured
    # 5 ms/dispatch floor.
    _DEFER_BUDGET_BYTES: int = 1 << 28
    # cap on pending chunk count: bounds the stacked operand's leading axis
    # (and, on the mixed-shape fallback, the concat arity / trace size) for
    # small-batch streams. Under a steady constant-batch loop every
    # valve-triggered fold fires at exactly this count, so the stacked fold
    # sees ONE pending signature all stream long.
    _DEFER_MAX_CHUNKS: int = 256
    _defers = True  # MetricCollection: deferral is the (only) fused lane

    _fold_params: Tuple[Any, ...] = ()
    # True for folds that are per-sample independent + reduce (accuracy
    # family, regression/NE sufficient statistics, aggregations): the scan
    # path folds chunk-wise with the math traced once, and the ragged
    # fallback accumulates per chunk — both beat a many-operand concat.
    # Count kernels (confusion, F1 triples) keep the concat to stay in
    # their measured large-N regime.
    _fold_per_chunk: bool = False
    # None = states merge by addition. Non-additive states (Max/Min extrema)
    # set a module-level combine (e.g. ``staticmethod(jnp.maximum)``) and the
    # fold threads state through it instead.
    _fold_reduce: Optional[Any] = None

    def _init_deferred(self) -> None:
        global _defer_seq_counter
        self._pending: List[Tuple[jax.Array, ...]] = []
        self._pending_bytes = 0
        # cached (ndim, dtype, trailing-shape) signature of the chunks in
        # _pending — _defer compares one tuple instead of re-deriving the
        # head chunk's signature attribute-by-attribute on every call
        self._pending_sig: Optional[Tuple[Any, ...]] = None
        # registration order: the stable tie-break for group-member ordering
        # (jit caches on the static specs tuple; WeakSet iteration order and
        # id() are both unstable)
        _defer_seq_counter += 1
        self._defer_seq = _defer_seq_counter
        _live_deferred.add(self)

    def _fold_kernel(self, *cat_args: jax.Array) -> Dict[str, jax.Array]:
        """Per-batch deltas; used directly on the tracer fallback path."""
        return type(self)._fold_fn(*cat_args, *self._fold_params)

    # -------------------------------------------------------------- machinery
    def _defer(self, *args: jax.Array) -> None:
        if any(_is_tracer(a) for a in args):
            # inside an enclosing trace: fold eagerly so no tracer outlives
            # its trace in the pending list
            self._apply_deltas(self._fold_kernel(*args))
            return
        sig = tuple((a.ndim, a.dtype, a.shape[1:]) for a in args)
        if self._pending and sig != self._pending_sig:
            # arity/rank/width/dtype change: one fold never mixes signatures
            # (concatenation would be illegal or silently promote) — flush
            # the old signature FIRST, then append the new chunk
            self._fold_now()
        self._pending.append(args)
        self._pending_sig = sig
        self._pending_bytes += sum(int(a.nbytes) for a in args)
        # _defer_managed: a MetricCollection owns the fold trigger so sibling
        # metrics fold in ONE dispatch (XLA CSEs shared math, e.g. confusion
        # matrix + F1 over the same batch). A managed member streamed into
        # DIRECTLY (bypassing the collection) still self-folds at 2x the
        # budget as a hard memory valve.
        scale = 2 if getattr(self, "_defer_managed", False) else 1
        if (
            self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
            or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
        ):
            # group first: same-stream peers are typically one chunk behind
            # right now, so the shared prefix frees (almost) everything in
            # one dispatch; fold solo only if that left us over budget
            self._group_fold_attempt()
            if (
                self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
                or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
            ):
                self._fold_now()

    def _apply_deltas(self, deltas: Dict[str, jax.Array]) -> None:
        red = type(self)._fold_reduce or _add
        for name, delta in deltas.items():
            setattr(self, name, red(getattr(self, name), delta))

    def _group_fold_attempt(self) -> None:
        """Fold the longest common pending-chunk prefix shared with live
        standalone peers in ONE program (see :data:`_live_deferred`);
        no-op without peers. Chunks past the common prefix (a peer one
        batch behind mid-stream) stay pending on their owners."""
        pending = getattr(self, "_pending", None)
        if not pending or getattr(self, "_defer_managed", False):
            return
        peers = [
            m
            for m in _live_deferred
            if m is not self
            and not getattr(m, "_defer_managed", False)
            and m.device == self.device
            and getattr(m, "_pending", None)
            and (
                _is_prefix(m._pending, pending)
                or _is_prefix(pending, m._pending)
            )
        ]
        if not peers:
            return
        # stable member order: jit caches on the static specs tuple, so the
        # same group must enumerate identically whichever member triggers
        group = sorted(
            [self, *peers],
            key=lambda m: (type(m).__qualname__, m._defer_seq),
        )
        common = min(len(m._pending) for m in group)
        chunks = self._pending[:common]
        # transitivity guard: every member must agree on the common prefix
        # (pairwise prefix vs self guarantees it, but stay explicit)
        if not all(_is_prefix(chunks, m._pending) for m in group):
            return
        specs = tuple(
            _member_spec(str(i), m) for i, m in enumerate(group)
        )
        states = {
            str(i): {n: getattr(m, n) for n in m._state_name_to_default}
            for i, m in enumerate(group)
        }
        from torcheval_tpu.utils.platform import donation_pipelines

        dispatch = (
            _group_fold_dispatch_donated
            if donation_pipelines()
            else _group_fold_dispatch
        )
        scan_ok = _scan_allowed(chunks)
        new_states = dispatch(states, chunks, specs=specs, scan_ok=scan_ok)
        _count_fold(
            "group_fold", "scan" if scan_ok else "concat", len(chunks)
        )
        for i, m in enumerate(group):
            m._pending = m._pending[common:]
            m._pending_bytes = sum(
                int(a.nbytes) for c in m._pending for a in c
            )
            for n, v in new_states[str(i)].items():
                setattr(m, n, v)

    def _fold_now(self) -> None:
        """Fold all pending batches into the metric state: one dispatch —
        shared with every standalone peer metric whose pending chunks are
        an identity-prefix match (see :meth:`_group_fold_attempt`); any
        remainder folds solo so the full-fold contract holds."""
        pending = getattr(self, "_pending", None)
        if not pending:
            return
        self._group_fold_attempt()
        pending = self._pending
        if not pending:
            return
        from torcheval_tpu.utils.platform import donation_pipelines

        # donation keeps counters updating in place in HBM; gated off on
        # tunneled backends where it serialises dispatches (utils/platform.py)
        dispatch = (
            _fold_dispatch_donated if donation_pipelines() else _fold_dispatch
        )
        states = {n: getattr(self, n) for n in self._state_name_to_default}
        cls = type(self)
        scan_ok = _scan_allowed(pending)
        new_states = dispatch(
            states,
            pending,
            fold_fn=cls._fold_fn,
            fold_params=self._fold_params,
            per_chunk=cls._fold_per_chunk,
            fold_reduce=cls._fold_reduce,
            scan_ok=scan_ok,
        )
        _count_fold("fold", "scan" if scan_ok else "concat", len(pending))
        # clear pending only after a successful dispatch: a fold that raises
        # (bad batch reaching the trace) must not silently discard the valid
        # batches queued alongside it
        self._pending = []
        self._pending_bytes = 0
        for name, value in new_states.items():
            setattr(self, name, value)

    # ------------------------------------------------------ lifecycle hooks
    def reset(self):
        self._pending = []
        self._pending_bytes = 0
        self._pending_sig = None
        return super().reset()

    # NOTE no load_state_dict override: the base class folds pending chunks
    # into the OLD state before overwriting (Metric.load_state_dict), which
    # both keeps partial (strict=False) loads exact for the states they do
    # not touch and guarantees stale chunks never fold into restored state —
    # regression-tested in tests/metrics/test_deferred.py (mid-window
    # restore) and tests/resilience/test_snapshot.py.

    def __getstate__(self) -> Dict[str, Any]:
        self._fold_now()
        state = super().__getstate__()
        state["_pending"] = []
        # management is a live relationship with one collection instance; a
        # restored/cloned metric answers to no collection and must self-fold
        state.pop("_defer_managed", None)
        return state

    def __setstate__(self, state) -> None:
        super().__setstate__(state)
        # restored metrics must be visible to peers' group folds again
        self._pending = []
        self._pending_bytes = 0
        self._pending_sig = None
        _live_deferred.add(self)

    def __deepcopy__(self, memo):
        self._fold_now()
        new = super().__deepcopy__(memo)
        new.__dict__.pop("_defer_managed", None)
        _live_deferred.add(new)  # clones group with future same-batch peers
        return new

"""Deferred batch folding: make ``update()`` an O(1) host append.

TPU-first rationale. The reference's hot loop dispatches one scatter-add per
``update()`` call (``/root/reference/torcheval/metrics/functional/
classification/f1_score.py:182-190``) — cheap on CPU where dispatch is a
function call, but on an accelerator every dispatch pays an enqueue (and on
this project's tunneled chip, 0.2-5 ms of transport). Worse, per-batch
kernels are *small*: a (8192, 5) argmax+compare keeps the chip busy for tens
of microseconds; the round trip dominates by 10-100×.

So counter metrics here do not fold per batch. ``update()`` validates shapes
(host metadata only), places the arrays, and **appends them to a pending
list**. The actual math runs later as ONE fused XLA program over the
concatenated pending batches, triggered by:

* a read of the logical state — ``compute`` / ``state_dict`` / ``to`` /
  ``merge_state`` / pickling / deepcopy / ``_prepare_for_merge_state``;
* a memory budget (``_DEFER_BUDGET_BYTES`` of pending update args) or a
  chunk-count cap (``_DEFER_MAX_CHUNKS``), so an unbounded stream folds
  periodically and pending device buffers can be freed.

This is strictly better on TPU for two measured reasons (docs/performance.md):
dispatch count drops from O(batches) to O(total_bytes / budget), and the big
fused fold lets the auto-picked lowering ride its *large-N* regime — e.g. the
confusion update at (N=1.3M, C=1000) runs the flat joint scatter at ~110M
preds/s where 13 separate 100k-batch one-hot matmuls manage ~24M.

Semantics are unchanged: folding is a physical-representation change with the
same logical state (counts are integer — grouping cannot change them), the
same trick the reference itself plays in ``_prepare_for_merge_state``
(``metric.py:112-121``). Two visible differences, documented here:

* reading a state attribute directly (``m.num_correct``) between updates sees
  the *folded-so-far* value; go through ``state_dict()``/``compute()`` (which
  fold first) for the logical value.
* a jitted fold compiles per pending-shape signature. Steady loops (constant
  batch size) see one or two signatures; wildly varying batch shapes fall
  back to more compiles, never wrong results. Mixed signatures (e.g. a
  (N, C) score batch after (N,) label batches) flush the pending list first
  so one concatenation never mixes ranks.

Tracer transparency: when ``update`` is called inside someone else's trace
(a user jitting their eval step around a metric), deferral would leak
tracers into the pending list — so tracer args take the eager fold path,
which is exactly the pre-deferral behavior.

Donation caveat (same as ``MetricCollection``'s fused lane): on backends
where ``donation_pipelines()`` is true, a fold donates the previous state
buffers. A raw reference captured from a state attribute (``ref =
m.num_total``) dies at the next fold — read state through ``state_dict()``
/ ``compute()`` instead of holding array refs across updates.
"""

from __future__ import annotations

import weakref
from functools import partial
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from torcheval_tpu.obs.recompile import watched_jit as _watched_jit


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


# Live unmanaged deferred metrics (round-4 verdict ask 8): when one folds, it
# scans here for peers whose pending chunks are the IDENTICAL placed arrays —
# the signature of standalone metrics fed the same batches (`cm.update(x, y);
# f1.update(x, y)` outside any collection) — and folds the whole group in one
# program, so XLA dedupes the shared math exactly as the MetricCollection
# lane does. WeakSet: registration must not keep metrics alive.
_live_deferred: "weakref.WeakSet" = weakref.WeakSet()
_defer_seq_counter = 0


def _chunks_identical(a, b) -> bool:
    """True when two pending lists hold the same chunk ARRAY OBJECTS in the
    same order — identity, not value: it is free to check and exactly
    captures "fed the same placed batches"."""
    return len(a) == len(b) and all(
        len(c) == len(h) and all(x is y for x, y in zip(c, h))
        for c, h in zip(a, b)
    )


def _is_prefix(short, long) -> bool:
    """``short`` is a (non-strict) identity-prefix of ``long``. Standalone
    metrics fed the same stream are usually one chunk apart mid-loop (A got
    batch N before B did), so exact equality would miss every
    valve-triggered fold; prefix grouping folds the common part and leaves
    the stragglers pending."""
    return len(short) <= len(long) and _chunks_identical(
        short, long[: len(short)]
    )


def _fold_deltas(chunks, fold_fn, fold_params, per_chunk):
    """Deltas over the pending batches: one kernel over the concatenated
    stream (count kernels want the large-N regime), or per-chunk kernels with
    summed deltas when the fold is per-sample independent + reduce
    (``per_chunk``) — a many-operand ``jnp.concatenate`` measured ~1.4× the
    cost of per-chunk accumulation at 200 chunks on v5e, and count kernels
    gain nothing from it there."""
    if per_chunk and len(chunks) > 1:
        acc = None
        for chunk in chunks:
            deltas = fold_fn(*chunk, *fold_params)
            acc = (
                deltas
                if acc is None
                else {n: acc[n] + d for n, d in deltas.items()}
            )
        return acc
    cat = tuple(
        jnp.concatenate(cols, axis=0) if len(cols) > 1 else cols[0]
        for cols in zip(*chunks)
    )
    return fold_fn(*cat, *fold_params)


def _fold_body(states, chunks, fold_fn, fold_params, per_chunk):
    deltas = _fold_deltas(chunks, fold_fn, fold_params, per_chunk)
    # return EVERY state (merged), not just the delta'd ones: under donation
    # all input buffers are invalidated, so an untouched state must still be
    # threaded through to a live output buffer
    return {**states, **{n: states[n] + d for n, d in deltas.items()}}


# Module-level jitted dispatchers shared by ALL metric instances: the trace
# cache keys on (fold_fn identity, fold_params, pending pytree signature), so
# a fresh metric instance reuses the compiled fold instead of re-tracing a
# wide concat program per instance (measured ~200 ms of host tracing for a
# 200-chunk fold — more than the fold itself).
# watched_jit: the deferred fold is the canonical retrace-storm site (the
# trace cache keys on the pending pytree signature — wildly varying batch
# shapes recompile the wide concat program per fold) and the watchdog's
# per-signature counts make that visible; the scope name attributes the
# fold's device time in XLA traces.
_fold_dispatch = partial(
    _watched_jit,
    name="deferred.fold",
    static_argnames=("fold_fn", "fold_params", "per_chunk"),
)(_fold_body)
_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.fold",
    static_argnames=("fold_fn", "fold_params", "per_chunk"),
    donate_argnums=(0,),
)(_fold_body)


def _group_fold_body(states_by_member, chunks, specs):
    """Fold SEVERAL metrics' pending batches (identical args) in one program.

    ``specs`` is a static tuple of ``(member_key, fold_fn, fold_params,
    per_chunk)``. Because every member folds the same arrays inside one XLA
    program, common subcomputations dedupe: a MulticlassConfusionMatrix and a
    MulticlassF1Score over the same batch share the argmax and (depending on
    lowerings) the count kernels instead of dispatching them twice.
    """
    out = {}
    for key, fold_fn, fold_params, per_chunk in specs:
        states = states_by_member[key]
        deltas = _fold_deltas(chunks, fold_fn, fold_params, per_chunk)
        out[key] = {**states, **{n: states[n] + d for n, d in deltas.items()}}
    return out


_group_fold_dispatch = partial(
    _watched_jit, name="deferred.group_fold", static_argnames=("specs",)
)(_group_fold_body)
_group_fold_dispatch_donated = partial(
    _watched_jit,
    name="deferred.group_fold",
    static_argnames=("specs",),
    donate_argnums=(0,),
)(_group_fold_body)


def group_fold(members: Dict[str, "DeferredFoldMixin"]) -> None:
    """Fold every member's pending batches in ONE dispatch when their pending
    structures are identical (the MetricCollection case: every member was fed
    the same placed arrays); falls back to per-member folds otherwise."""
    pending = [m for m in members.values() if getattr(m, "_pending", None)]
    if not pending:
        return
    head = pending[0]._pending
    aligned = len(pending) == len(members) and all(
        _chunks_identical(m._pending, head) for m in pending[1:]
    )
    if not aligned:
        for m in pending:
            m._fold_now()
        return
    chunks = head
    specs = tuple(
        (key, type(m)._fold_fn, m._fold_params, type(m)._fold_per_chunk)
        for key, m in members.items()
    )
    states = {
        key: {n: getattr(m, n) for n in m._state_name_to_default}
        for key, m in members.items()
    }
    from torcheval_tpu.utils.platform import donation_pipelines

    dispatch = (
        _group_fold_dispatch_donated
        if donation_pipelines()
        else _group_fold_dispatch
    )
    new_states = dispatch(states, chunks, specs=specs)
    # clear pending only after a successful dispatch (see _fold_now)
    for m in pending:
        m._pending = []
        m._pending_bytes = 0
    for key, m in members.items():
        for n, v in new_states[key].items():
            setattr(m, n, v)


class DeferredFoldMixin:
    """Mixin for counter metrics: pending-batch cache + lazy fused fold.

    Contract for subclasses::

        def _my_fold(input, target, threshold):   # MODULE-level pure fn:
            ...                                    # math on the CONCATENATED
            return {"num_tp": ..., "num_fp": ...}  # args -> {state: delta}

        class MyMetric(DeferredFoldMixin, Metric[jax.Array]):
            _fold_fn = staticmethod(_my_fold)

            def __init__(self, ...):
                super().__init__(device=device)
                self._add_state(...)
                self._init_deferred()
                self._fold_params = (threshold,)   # hashable statics

            def update(self, input, target):
                input, target = self._input(input), self._input(target)
                _my_input_check(input, target)
                self._defer(input, target)
                return self

    ``_fold_fn`` must be a module-level function (shared identity across
    instances — it keys the shared jit cache) taking the concatenated update
    args followed by ``*_fold_params``. ``compute``/``merge_state``
    implementations must call ``_fold_now()`` (and fold merge sources) before
    reading state; the :class:`Metric` base class folds in
    ``state_dict``/``to``/``_prepare_for_merge_state``/pickle.
    """

    # pending-args budget before a fold is forced. 256 MB holds e.g. 32 chunks
    # of (2^20, 5) float32 scores+labels; the fold dispatch amortises to
    # ~0.7 ns/byte of pending data even at the tunnel's worst measured
    # 5 ms/dispatch floor.
    _DEFER_BUDGET_BYTES: int = 1 << 28
    # cap on pending chunk count: bounds the concat arity (trace size) and the
    # shape-signature space for small-batch streams.
    _DEFER_MAX_CHUNKS: int = 256
    _defers = True  # MetricCollection: do not re-fuse; deferral already fuses

    _fold_params: Tuple[Any, ...] = ()
    # True for folds that are per-sample independent + reduce (accuracy
    # family, binned threshold counts): per-chunk kernels with summed deltas
    # beat a many-operand concat. Count kernels (confusion, F1 triples) keep
    # the concat to stay in their measured large-N regime.
    _fold_per_chunk: bool = False

    def _init_deferred(self) -> None:
        global _defer_seq_counter
        self._pending: List[Tuple[jax.Array, ...]] = []
        self._pending_bytes = 0
        # registration order: the stable tie-break for group-member ordering
        # (jit caches on the static specs tuple; WeakSet iteration order and
        # id() are both unstable)
        _defer_seq_counter += 1
        self._defer_seq = _defer_seq_counter
        _live_deferred.add(self)

    def _fold_kernel(self, *cat_args: jax.Array) -> Dict[str, jax.Array]:
        """Per-batch deltas; used directly on the tracer fallback path."""
        return type(self)._fold_fn(*cat_args, *self._fold_params)

    # -------------------------------------------------------------- machinery
    def _defer(self, *args: jax.Array) -> None:
        if any(_is_tracer(a) for a in args):
            # inside an enclosing trace: fold eagerly so no tracer outlives
            # its trace in the pending list
            self._apply_deltas(self._fold_kernel(*args))
            return
        if self._pending:
            head = self._pending[0]
            if len(head) != len(args) or any(
                h.ndim != a.ndim
                or h.shape[1:] != a.shape[1:]
                or h.dtype != a.dtype
                for h, a in zip(head, args)
            ):
                # rank/width/dtype change: concatenation would be illegal (or
                # silently promote) — flush the old signature first
                self._fold_now()
        self._pending.append(args)
        self._pending_bytes += sum(int(a.nbytes) for a in args)
        # _defer_managed: a MetricCollection owns the fold trigger so sibling
        # metrics fold in ONE dispatch (XLA CSEs shared math, e.g. confusion
        # matrix + F1 over the same batch). A managed member streamed into
        # DIRECTLY (bypassing the collection) still self-folds at 2x the
        # budget as a hard memory valve.
        scale = 2 if getattr(self, "_defer_managed", False) else 1
        if (
            self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
            or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
        ):
            # group first: same-stream peers are typically one chunk behind
            # right now, so the shared prefix frees (almost) everything in
            # one dispatch; fold solo only if that left us over budget
            self._group_fold_attempt()
            if (
                self._pending_bytes >= scale * self._DEFER_BUDGET_BYTES
                or len(self._pending) >= scale * self._DEFER_MAX_CHUNKS
            ):
                self._fold_now()

    def _apply_deltas(self, deltas: Dict[str, jax.Array]) -> None:
        for name, delta in deltas.items():
            setattr(self, name, getattr(self, name) + delta)

    def _group_fold_attempt(self) -> None:
        """Fold the longest common pending-chunk prefix shared with live
        standalone peers in ONE program (see :data:`_live_deferred`);
        no-op without peers. Chunks past the common prefix (a peer one
        batch behind mid-stream) stay pending on their owners."""
        pending = getattr(self, "_pending", None)
        if not pending or getattr(self, "_defer_managed", False):
            return
        peers = [
            m
            for m in _live_deferred
            if m is not self
            and not getattr(m, "_defer_managed", False)
            and m.device == self.device
            and getattr(m, "_pending", None)
            and (
                _is_prefix(m._pending, pending)
                or _is_prefix(pending, m._pending)
            )
        ]
        if not peers:
            return
        # stable member order: jit caches on the static specs tuple, so the
        # same group must enumerate identically whichever member triggers
        group = sorted(
            [self, *peers],
            key=lambda m: (type(m).__qualname__, m._defer_seq),
        )
        common = min(len(m._pending) for m in group)
        chunks = self._pending[:common]
        # transitivity guard: every member must agree on the common prefix
        # (pairwise prefix vs self guarantees it, but stay explicit)
        if not all(_is_prefix(chunks, m._pending) for m in group):
            return
        specs = tuple(
            (str(i), type(m)._fold_fn, m._fold_params, type(m)._fold_per_chunk)
            for i, m in enumerate(group)
        )
        states = {
            str(i): {n: getattr(m, n) for n in m._state_name_to_default}
            for i, m in enumerate(group)
        }
        from torcheval_tpu.utils.platform import donation_pipelines

        dispatch = (
            _group_fold_dispatch_donated
            if donation_pipelines()
            else _group_fold_dispatch
        )
        new_states = dispatch(states, chunks, specs=specs)
        for i, m in enumerate(group):
            m._pending = m._pending[common:]
            m._pending_bytes = sum(
                int(a.nbytes) for c in m._pending for a in c
            )
            for n, v in new_states[str(i)].items():
                setattr(m, n, v)

    def _fold_now(self) -> None:
        """Fold all pending batches into the counter state: one dispatch —
        shared with every standalone peer metric whose pending chunks are
        an identity-prefix match (see :meth:`_group_fold_attempt`); any
        remainder folds solo so the full-fold contract holds."""
        pending = getattr(self, "_pending", None)
        if not pending:
            return
        self._group_fold_attempt()
        pending = self._pending
        if not pending:
            return
        from torcheval_tpu.utils.platform import donation_pipelines

        # donation keeps counters updating in place in HBM; gated off on
        # tunneled backends where it serialises dispatches (utils/platform.py)
        dispatch = (
            _fold_dispatch_donated if donation_pipelines() else _fold_dispatch
        )
        states = {n: getattr(self, n) for n in self._state_name_to_default}
        new_states = dispatch(
            states,
            pending,
            fold_fn=type(self)._fold_fn,
            fold_params=self._fold_params,
            per_chunk=type(self)._fold_per_chunk,
        )
        # clear pending only after a successful dispatch: a fold that raises
        # (bad batch reaching the trace) must not silently discard the valid
        # batches queued alongside it
        self._pending = []
        self._pending_bytes = 0
        for name, value in new_states.items():
            setattr(self, name, value)

    # ------------------------------------------------------ lifecycle hooks
    def reset(self):
        self._pending = []
        self._pending_bytes = 0
        return super().reset()

    def load_state_dict(self, state_dict, strict: bool = True) -> None:
        # loading REPLACES the logical state wholesale; pending batches belong
        # to the stream being replaced and are dropped with it
        self._pending = []
        self._pending_bytes = 0
        super().load_state_dict(state_dict, strict)

    def __getstate__(self) -> Dict[str, Any]:
        self._fold_now()
        state = super().__getstate__()
        state["_pending"] = []
        # management is a live relationship with one collection instance; a
        # restored/cloned metric answers to no collection and must self-fold
        state.pop("_defer_managed", None)
        return state

    def __setstate__(self, state) -> None:
        super().__setstate__(state)
        # restored metrics must be visible to peers' group folds again
        self._pending = []
        self._pending_bytes = 0
        _live_deferred.add(self)

    def __deepcopy__(self, memo):
        self._fold_now()
        new = super().__deepcopy__(memo)
        new.__dict__.pop("_defer_managed", None)
        _live_deferred.add(new)  # clones group with future same-batch peers
        return new
